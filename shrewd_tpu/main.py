"""The framework CLI — the ``m5.main`` analog.

``python -m shrewd_tpu <subcommand>`` is the user-facing entry point the
reference exposes as ``gem5.opt <config.py> --flags``
(``/root/reference/src/python/m5/main.py:387``, options ``:227-248``).  A
campaign is reproducible from its config dump alone:

    python -m shrewd_tpu run plan.json --outdir out --debug-flags Campaign
    python -m shrewd_tpu resume out/campaign_ckpt --outdir out2
    python -m shrewd_tpu hostdiff --trials 1000 --workload workloads/sort.c
    python -m shrewd_tpu bench --quick

Run artifacts land in ``--outdir`` as ``config.json`` / ``stats.txt`` /
``stats.json`` (``python/m5/main.py:227-248`` m5out analog).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _apply_common(args) -> None:
    from shrewd_tpu.utils import debug

    if args.debug_flags:
        debug.enable(*args.debug_flags.split(","))
    if getattr(args, "platform", None):
        import jax
        jax.config.update("jax_platforms", args.platform)


def _apply_obs(args) -> None:
    """--trace installs the process-wide tracer BEFORE the orchestrator
    is built (elaboration events are part of the run's story); without
    it the tracer stays the zero-overhead no-op constant."""
    if getattr(args, "trace", None):
        from shrewd_tpu.obs import trace as obs_trace

        obs_trace.enable(ring=getattr(args, "trace_ring", None)
                         or obs_trace.DEFAULT_RING)


def _apply_resilience_overrides(orch, args) -> None:
    """CLI flags override the plan's resilience posture (and land in the
    config/checkpoint dumps, so the overridden run stays reproducible)."""
    cfg = orch.rcfg
    if getattr(args, "escalation_threshold", None) is not None:
        cfg.escalation_threshold = args.escalation_threshold
    if getattr(args, "escalation_action", None):
        cfg.escalation_action = args.escalation_action
    if getattr(args, "dispatch_timeout", None) is not None:
        cfg.dispatch_timeout = args.dispatch_timeout
        orch.watchdog.timeout = float(args.dispatch_timeout)
    if getattr(args, "max_retries", None) is not None:
        cfg.max_retries = args.max_retries
    icfg = orch.icfg
    if getattr(args, "audit_rate", None) is not None:
        icfg.audit_rate = args.audit_rate
    if getattr(args, "audit_threshold", None) is not None:
        icfg.audit_threshold = args.audit_threshold
    if getattr(args, "audit_action", None):
        icfg.audit_action = args.audit_action
    if getattr(args, "canary_trials", None) is not None:
        icfg.canary_trials = args.canary_trials
    if getattr(args, "certify", None):
        from shrewd_tpu import analysis as analysis_mod
        from shrewd_tpu.parallel import exec_cache

        orch.plan.analysis.certify = args.certify   # reproducible dump
        if args.certify == "off":
            # an EXPLICIT off must disarm a plan-installed auditor, or
            # the dumped config ('off') and the run's behavior (strict)
            # would disagree — the reproducibility contract
            exec_cache.clear_auditor()
            orch.auditor = None
        else:
            orch.auditor = analysis_mod.install_step_auditor(
                args.certify, orch.plan.analysis.transfer_budget)
    pcfg = orch.pcfg
    if getattr(args, "sync_every", None) is not None:
        pcfg.sync_every = args.sync_every
    if getattr(args, "pipeline_depth", None) is not None:
        pcfg.depth = args.pipeline_depth
    if getattr(args, "until_ci", None):
        pcfg.until_ci = True
    if getattr(args, "max_super_interval", None) is not None:
        pcfg.max_super_interval = args.max_super_interval
    if getattr(args, "compilation_cache_dir", None):
        from shrewd_tpu.parallel.exec_cache import enable_persistent_cache

        pcfg.compilation_cache_dir = args.compilation_cache_dir
        enable_persistent_cache(args.compilation_cache_dir)


def _apply_chaos_elastic(orch, args) -> None:
    """--chaos-plan attaches the deterministic failure schedule;
    --elastic-dir joins (or starts) an elastic multi-host campaign over a
    shared coordination directory."""
    worker = getattr(args, "worker", "") or f"w{os.getpid()}"
    if getattr(args, "chaos_plan", None):
        from shrewd_tpu.chaos import ChaosEngine

        orch.plan.chaos.plan_path = args.chaos_plan   # reproducible dump
        orch.attach_chaos(ChaosEngine.from_path(args.chaos_plan,
                                                worker=worker))
    if getattr(args, "elastic_dir", None):
        from shrewd_tpu.parallel.elastic import ElasticContext

        orch.attach_elastic(ElasticContext(args.elastic_dir, worker,
                                           orch.plan.elastic))


def _drive(orch, args) -> int:
    """Drive the orchestrator's event loop to completion (the stdlib
    Simulator.run analog: typed exit events → handlers,
    ``python/gem5/simulate/simulator.py:530``)."""
    _apply_resilience_overrides(orch, args)
    _apply_chaos_elastic(orch, args)
    # graceful preemption: SIGTERM/SIGINT finish the in-flight batch,
    # write a resumable checkpoint, and exit rc 4 (distinct from the
    # budget-abort rc 3 so schedulers can tell drain from distrust)
    restore_signals = orch.install_signal_handlers()
    t0 = time.monotonic()
    ckpt_every = orch.plan.checkpoint_every
    try:
        n_batches = _drive_events(orch, ckpt_every)
    finally:
        # the second-signal KeyboardInterrupt escape hatch (and any
        # ladder/elastic error) must still restore handlers and leave the
        # elastic membership gracefully — a stale heartbeat file would
        # make peers burn a full timeout declaring us lost and pollute
        # the shared coordination dir for later campaigns
        restore_signals()
        if orch._elastic is not None:
            orch._elastic.stop()      # graceful leave: peers see it
    orch.write_outputs()
    return _drive_outputs(orch, args, t0, n_batches)


def _drive_events(orch, ckpt_every: int) -> int:
    """Consume the orchestrator's event stream, logging each typed event;
    returns the number of completed batches."""
    from shrewd_tpu.resilience import TIERS
    from shrewd_tpu.sim.exit_event import ExitEvent

    n_batches = 0
    for event, payload in orch.events():
        if event == ExitEvent.BATCH_COMPLETE:
            n_batches += 1
            if ckpt_every and n_batches % ckpt_every == 0:
                orch.checkpoint()
        elif event in (ExitEvent.CI_CONVERGED, ExitEvent.MAX_TRIALS):
            r = payload
            hw = (r.avf_interval.hi - r.avf_interval.lo) / 2
            _log(f"  {r.simpoint}/{r.structure}: trials={r.trials} "
                 f"avf={r.avf:.4f} ±{hw:.4f}"
                 + ("" if r.converged else " (trial cap, unconverged)"))
        elif event == ExitEvent.BACKEND_DEGRADED:
            d = payload
            _log(f"  {d.simpoint}/{d.structure} batch {d.batch_id}: "
                 f"ran on {TIERS[d.tier]} tier "
                 f"({d.attempts} dispatch attempts)")
        elif event == ExitEvent.INTEGRITY_VIOLATION:
            from shrewd_tpu.integrity import AuditBudgetInfo
            if isinstance(payload, AuditBudgetInfo):
                _log(f"AUDIT MISMATCH BUDGET EXCEEDED: {payload.rate:.1%} "
                     f"of audited trials disagreed (threshold "
                     f"{payload.threshold:.1%}, action={payload.action}) "
                     f"— reasons {payload.reasons}")
            else:
                _log(f"INTEGRITY: {payload}")
        elif event == ExitEvent.ESCALATION_EXCEEDED:
            e = payload
            _log(f"ESCALATION BUDGET EXCEEDED: {e.rate:.1%} of trials ran "
                 f"below the device tier (threshold {e.threshold:.1%}, "
                 f"action={e.action}) — tiers {e.tier_trials}")
        elif event == ExitEvent.PREEMPTED:
            _log(f"PREEMPTED: drained to checkpoint "
                 f"{payload or '(no outdir — progress lost)'}")
        elif event == ExitEvent.WORKER_LOST:
            _log(f"WORKER LOST: {payload.worker} (lease {payload.batch_key}"
                 f" revoked; survivors: "
                 f"{', '.join(payload.survivors) or 'this worker'})")
        elif event == ExitEvent.SIMPOINT_COMPLETE:
            _log(f"simpoint {payload}: done")
        elif event == ExitEvent.CAMPAIGN_COMPLETE:
            break
    return n_batches


def _drive_outputs(orch, args, t0, n_batches) -> int:
    from shrewd_tpu.resilience import TIERS

    if orch.outdir:
        orch.checkpoint()
    esc = orch.budget
    if esc.escalated:
        _log(f"escalation: {esc.escalated}/{esc.total} trials "
             f"({esc.rate():.1%}) ran below the device tier "
             f"({', '.join(f'{t}={int(c)}' for t, c in zip(TIERS, esc.counts))})")
    mon = orch.monitor
    if mon.ledger.audited or mon.canary_trials or mon.quarantined:
        _log(f"integrity: {mon.canary_trials} canary trials "
             f"({mon.canary_failures} failed), {mon.ledger.audited} "
             f"audited ({mon.ledger.mismatched} mismatched), "
             f"{mon.quarantined} batches quarantined "
             f"({mon.recovered} recovered)")
    chaos = orch.chaos
    if chaos is not None and chaos.injected:
        _log(f"chaos: injected {dict(chaos.injected)}, "
             f"survived {dict(chaos.survived)}")
    el = orch._elastic
    if el is not None:
        _log(f"elastic ({el.worker}): {el.counters()}")
    if orch.preempted:
        _log(f"campaign PREEMPTED after {n_batches} batches in "
             f"{time.monotonic() - t0:.1f}s"
             + (f" → {orch.outdir} (resumable)" if orch.outdir else ""))
        return 4
    if orch.aborted:
        _log(f"campaign ABORTED by "
             f"{orch.abort_reason or 'escalation budget'} after "
             f"{n_batches} batches in {time.monotonic() - t0:.1f}s"
             + (f" → {orch.outdir} (resumable)" if orch.outdir else ""))
        return 3
    _log(f"campaign complete: {n_batches} batches in "
         f"{time.monotonic() - t0:.1f}s"
         + (f" → {orch.outdir}" if orch.outdir else ""))
    return 0


def cmd_run(args) -> int:
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan

    _apply_obs(args)
    with open(args.plan) as f:
        plan = CampaignPlan.from_dict(json.load(f))
    orch = Orchestrator(plan, outdir=args.outdir)
    return _drive(orch, args)


def cmd_resume(args) -> int:
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    _apply_obs(args)
    orch = Orchestrator.resume(args.ckpt_dir, outdir=args.outdir)
    return _drive(orch, args)


def cmd_hostdiff(args) -> int:
    from shrewd_tpu.ingest import hostdiff as hd

    rep = hd.run_diff(args.trials, args.seed, args.workload, mode=args.mode)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=1)
    print(json.dumps({k: rep[k] for k in
                      ("trials", "host_avf", "device_avf", "avf_abs_err",
                       "agreement_exact", "agreement_vulnerable",
                       "cis_overlap")}))
    return 0


def cmd_trace(args) -> int:
    """Dump an Exec-style instruction trace of a replay window
    (trace/exec_trace.py; the reference's --debug-flags Exec family,
    src/cpu/exetrace.cc)."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.trace.exec_trace import exec_trace
    from shrewd_tpu.utils import debug

    if args.all:
        debug.enable("ExecAll")
    elif not debug.enabled("Exec"):
        debug.enable("Exec")
    if args.results:
        debug.enable("ExecResult")
    if args.workload:
        from shrewd_tpu.ingest import hostdiff as hd

        paths = hd.build_tools(workload_c=args.workload)
        tr, _meta = hd.capture_and_lift(paths)
    else:
        from shrewd_tpu.trace.synth import WorkloadConfig, generate

        tr = generate(WorkloadConfig(n=args.window, nphys=64,
                                     mem_words=1024,
                                     working_set_words=256,
                                     seed=args.seed))
    if args.pipeline:
        from shrewd_tpu.models.timing import compute_scoreboard
        from shrewd_tpu.trace.pipeview import dump_pipeview

        sb = compute_scoreboard(tr)
        n = dump_pipeview(tr, sb, out=sys.stdout, start=args.start,
                          count=args.n)
        _log(f"rendered {n} µops")
        return 0
    kern = TrialKernel(tr, O3Config(pallas="off"))
    n = exec_trace(tr, kern.golden_rec, out=sys.stdout, start=args.start,
                   count=args.n)
    _log(f"traced {n} µops")
    return 0


def cmd_bench(args) -> int:
    """Re-exec the repo-root bench supervisor (it must own the process: it
    re-execs per platform with hard timeouts)."""
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    if not os.path.exists(bench):
        _log(f"bench.py not found at {bench}")
        return 2
    argv = [sys.executable, bench]
    if args.quick:
        argv.append("--quick")
    os.execv(sys.executable, argv)
    return 0   # unreachable


def main(argv: list[str] | None = None) -> int:
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--debug-flags", default=os.environ.get(
        "SHREWD_DEBUG_FLAGS", ""), help="comma-separated debug flags "
        "(the reference's --debug-flags, python/m5/main.py)")
    common.add_argument("--platform", default=None,
                        help="jax platform override (cpu/tpu/axon)")
    ap = argparse.ArgumentParser(
        prog="python -m shrewd_tpu",
        description="TPU-native statistical fault-injection framework",
        parents=[common])
    sub = ap.add_subparsers(dest="cmd", required=True)

    resil = argparse.ArgumentParser(add_help=False)
    resil.add_argument("--escalation-threshold", type=float, default=None,
                       help="max fraction of trials allowed off the device "
                            "tier before the run is flagged "
                            "(plan.resilience.escalation_threshold)")
    resil.add_argument("--escalation-action", default=None,
                       choices=("off", "warn", "abort"),
                       help="what to do when the escalation budget is "
                            "exceeded (abort exits rc=3, resumable)")
    resil.add_argument("--dispatch-timeout", type=float, default=None,
                       help="watchdog seconds per device dispatch "
                            "(0 = no watchdog)")
    resil.add_argument("--max-retries", type=int, default=None,
                       help="re-dispatch attempts per tier before the "
                            "ladder degrades")
    resil.add_argument("--audit-rate", type=float, default=None,
                       help="fraction of each batch re-run on the "
                            "alternate kernel for the differential audit "
                            "(plan.integrity.audit_rate, default 0.01; "
                            "0 disables)")
    resil.add_argument("--audit-threshold", type=float, default=None,
                       help="max audited-trial mismatch rate before the "
                            "run is flagged (plan.integrity)")
    resil.add_argument("--audit-action", default=None,
                       choices=("off", "warn", "abort"),
                       help="what to do when the audit mismatch budget is "
                            "exceeded (abort exits rc=3, resumable)")
    resil.add_argument("--canary-trials", type=int, default=None,
                       help="seed-canary trials salted per batch "
                            "(0 disables canaries)")
    resil.add_argument("--chaos-plan", default=None,
                       help="chaos-plan JSON file: a deterministic "
                            "failure schedule injected at the watchdog/"
                            "ladder/integrity/checkpoint hook points "
                            "(shrewd_tpu/chaos.py)")
    resil.add_argument("--elastic-dir", default=None,
                       help="shared coordination directory for an elastic "
                            "multi-host campaign (heartbeats + batch "
                            "leases; parallel/elastic.py).  Start N "
                            "processes with the same plan and dir; lost "
                            "workers' batches are re-dispatched by "
                            "survivors bit-identically")
    resil.add_argument("--worker", default=None,
                       help="worker name for elastic/chaos runs "
                            "(default: w<pid>)")
    resil.add_argument("--sync-every", type=int, default=None,
                       help="batches accumulated on device per host "
                            "transfer (plan.pipeline.sync_every; 1 = the "
                            "serial loop, >1 enables the pipelined "
                            "engine — bit-identical tallies either way)")
    resil.add_argument("--pipeline-depth", type=int, default=None,
                       help="max sync intervals in flight "
                            "(plan.pipeline.depth, default 2 = double "
                            "buffering)")
    resil.add_argument("--compilation-cache-dir", default=None,
                       help="opt-in persistent jax compilation cache "
                            "directory: re-runs and resumes skip "
                            "retrace/recompile of unchanged campaign "
                            "steps (plan.pipeline.compilation_cache_dir)")
    resil.add_argument("--until-ci", action="store_true", default=None,
                       help="device-resident run-until-CI: fuse the "
                            "Wilson/post-stratified stopping rule into "
                            "the jitted step (lax.while_loop) — ONE host "
                            "transfer per super-interval, results "
                            "bit-identical to the serial loop including "
                            "the consumed trial count "
                            "(plan.pipeline.until_ci)")
    resil.add_argument("--max-super-interval", type=int, default=None,
                       help="max batches per device-resident until-CI "
                            "super-interval "
                            "(plan.pipeline.max_super_interval)")
    resil.add_argument("--trace", action="store_true", default=None,
                       help="install the process-wide tracer "
                            "(shrewd_tpu/obs/): structured events at "
                            "every load-bearing seam, Perfetto "
                            "trace.json in --outdir, flight-recorder "
                            "dump on abnormal exits.  Off by default "
                            "(the disabled tracer is a no-op constant)")
    resil.add_argument("--trace-ring", type=int, default=None,
                       help="flight-recorder ring capacity in events "
                            "(default 8192; bounds memory and dump "
                            "size, never correctness — drops are "
                            "counted in campaign.obs.events_dropped)")
    resil.add_argument("--certify", default=None,
                       choices=("off", "warn", "strict"),
                       help="statically certify every compiled campaign "
                            "step at executable-cache admission (jaxpr/"
                            "HLO replay-safety audit, shrewd_tpu/"
                            "analysis/): 'strict' refuses a violating "
                            "executable before any trial runs "
                            "(plan.analysis.certify)")

    p = sub.add_parser("run", help="run a campaign plan to completion",
                       parents=[common, resil])
    p.add_argument("plan", help="CampaignPlan config.json")
    p.add_argument("--outdir", default="m5out",
                   help="artifact directory (config.json/stats.txt/json)")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("resume", help="resume a checkpointed campaign",
                       parents=[common, resil])
    p.add_argument("ckpt_dir", help="campaign_ckpt directory")
    p.add_argument("--outdir", default="m5out")
    p.set_defaults(fn=cmd_resume)

    p = sub.add_parser("hostdiff", parents=[common],
                       help="host-silicon differential AVF campaign")
    p.add_argument("--trials", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workload", default="workloads/sort.c")
    p.add_argument("--mode", default="output",
                   choices=("output", "liveness", "abi", "emu64", "device64", "fp"))
    p.add_argument("--out", default="")
    p.set_defaults(fn=cmd_hostdiff)

    p = sub.add_parser("trace", parents=[common],
                       help="Exec-style instruction trace of a window")
    p.add_argument("--workload", default="",
                   help="C workload to capture+lift (default: synth trace)")
    p.add_argument("--start", type=int, default=0)
    p.add_argument("-n", type=int, default=64, help="µops to print")
    p.add_argument("--window", type=int, default=256,
                   help="synthetic window length (independent of -n)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--all", action="store_true",
                   help="ExecAll (results + opclasses)")
    p.add_argument("--results", action="store_true", help="ExecResult")
    p.add_argument("--pipeline", action="store_true",
                   help="render scoreboard pipeline timelines "
                        "(the o3-pipeview analog) instead of exec lines")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("bench", parents=[common],
                       help="headline benchmark (one JSON line)")
    p.add_argument("--quick", action="store_true")
    p.set_defaults(fn=cmd_bench)

    args = ap.parse_args(argv)
    _apply_common(args)
    return args.fn(args)
