"""shrewd_tpu — a TPU-native statistical fault-injection (SFI) framework.

A ground-up re-design of the capabilities of the reference simulator (a gem5
v25.0 fork carrying the SHREWD shadow-FU redundant-execution work and a SPEC
CPU2017 campaign driver) for TPU hardware.  Instead of an event-driven C++
simulator (reference: ``src/sim/eventq.hh:254``, ``src/sim/simulate.cc:191``),
the core computation is a *pure, batched* trial kernel::

    trial(snapshot, fault) -> outcome in {MASKED, SDC, DUE, DETECTED}

vmapped over tens of thousands of (structure, bit, cycle) fault samples,
sharded across a ``jax.sharding.Mesh`` of chips with ``shard_map``, with
AVF/SDC tallies reduced via ``psum``.

Package layout
--------------
- ``shrewd_tpu.utils``    — typed params/config, units, PRNG, debug flags,
  probes, MemChecker
- ``shrewd_tpu.stats``    — statistics framework with text/json/HDF5 dumps
- ``shrewd_tpu.isa``      — the µop dataflow ISA used for trace replay
- ``shrewd_tpu.trace``    — trace schema, synthetic workloads, Exec tracer,
  pipeline viewer
- ``shrewd_tpu.models``   — fault-target machine models (O3 + scoreboard
  timing, Minor latches, cache lifetime, MESI protocol, NoC, FU pool)
- ``shrewd_tpu.ops``      — inject / replay / classify kernels (JAX + Pallas)
- ``shrewd_tpu.parallel`` — mesh, sharded campaign step (device escape
  resolution, post-stratified estimation), CI stopping, multi-host init
- ``shrewd_tpu.campaign`` — plans, orchestrator, checkpoint/resume+upgraders
- ``shrewd_tpu.sim``      — Simulator / typed exit-event protocol
- ``shrewd_tpu.ingest``   — real-workload path (ptrace capture, x86→µop
  lifter, m5.cpt checkpoints, SimPoints, host-diff, 64-bit emulator)
- ``shrewd_tpu.native``   — ctypes bindings to the C++ runtime (csrc/)

Entry point: ``python -m shrewd_tpu`` (run/resume/hostdiff/trace/bench).
"""

from shrewd_tpu._version import __version__

__all__ = ["__version__"]
