"""Post-window liveness: the program-visible state at window end.

The host-silicon oracle (tools/hostsfi.cc) classifies a perturbed run by
*program output* — the reference's golden-stdout classification
(``/root/reference/tests/gem5/verifier.py:158`` MatchStdout).  The replay
kernel classifies at *window end* by comparing architectural state.  Window-
end state that the post-window code never reads (registers it overwrites or
ignores, memory it overwrites or never loads) cannot reach the output, so
counting its corruption as SDC over-reports AVF — the 25-point gap VERDICT
r2 measured.

This module computes, from a second nativetrace capture of the *post-window*
region (kernel_end → process exit), the first-access liveness of every GPR
and every replay-modeled memory word:

- register: LIVE if first post-window occurrence is a read (including use
  as an address base/index), DEAD if it is a full-width write;
- memory word: LIVE if read before written, DEAD if overwritten first or
  never touched.

Classification then compares only the live set — the exact analog of the
reference campaign's end-to-end program-outcome classification
(``/root/reference/x86_spec/x86-spec-cpu2017.py:403-436``) projected onto
the window boundary.

The analysis needs only static decode (objdump) + the captured per-step
register file for effective addresses; no semantic lifting, so it is robust
on libc code the lifter would demote to opaque.  Unknown instructions are
handled conservatively (their operands count as reads).
"""

from __future__ import annotations

import subprocess
from pathlib import Path
from typing import NamedTuple

import numpy as np

from shrewd_tpu.ingest.lift import (M32, N_GPR, NativeTrace, Inst, Operand,
                                    read_nativetrace, static_decode)

# canonical encoding order (tools/ptrace_common.h / lift.GPR_NAMES_64):
# rax rcx rdx rbx rsp rbp rsi rdi r8..r15
RAX, RCX, RDX, RBX, RSP, RBP, RSI, RDI = range(8)
R8, R9, R10, R11, R12, R13, R14, R15 = range(8, 16)

# Linux x86-64 syscall ABI: number in rax, args rdi rsi rdx r10 r8 r9
_SYSCALL_ARG_REGS = (RDI, RSI, RDX, R10, R8, R9)
_SYS_WRITE, _SYS_EXIT, _SYS_EXIT_GROUP = 1, 60, 231

_UNKNOWN, _LIVE, _DEAD = 0, 1, 2

# mnemonic stems whose last (AT&T) operand is write-only at full width
# (string-op mnemonics like the exact "movsb" are dispatched before stem
# matching — only the ≥6-char sign-extending movsbl/movswq forms reach here)
_MOV_STEMS = {"mov", "movabs", "movzb", "movzw", "movzx", "movsb", "movsw",
              "movsl", "movsx", "movsxd", "lea", "set", "cmov"}
# read-modify-write stems (last operand read and written)
_RMW_STEMS = {"add", "sub", "and", "or", "xor", "adc", "sbb", "imul", "mul",
              "shl", "sal", "shr", "sar", "rol", "ror", "rcl", "rcr",
              "inc", "dec", "neg", "not", "bts", "btr", "btc", "xadd"}
# read-only stems (flags only / no architectural write)
_RO_STEMS = {"cmp", "test", "bt", "nop", "prefetch"}
_BRANCH_STEMS = {"jmp", "je", "jne", "jb", "jae", "ja", "jbe", "jl", "jge",
                 "jg", "jle", "js", "jns", "jo", "jno", "jp", "jnp", "jc",
                 "jnc", "jrcxz", "loop"}
_STRING_EXACT = {p + s for p in ("movs", "stos", "lods", "scas", "cmps")
                 for s in ("", "b", "w", "l", "q")}


def _stem(mnemonic: str) -> str:
    m = mnemonic
    if m.startswith("lock"):
        m = m[4:].lstrip()
    for stems in (_RMW_STEMS, _RO_STEMS, _BRANCH_STEMS, _MOV_STEMS):
        for s in sorted(stems, key=len, reverse=True):
            if m.startswith(s):
                return s
    return m.rstrip("bwlq")


class Access(NamedTuple):
    reg_reads: tuple
    reg_writes: tuple           # full-width (zero/64-bit) writes only
    mem_reads: tuple            # ((addr, nbytes), ...)
    mem_writes: tuple
    stop: bool                  # process exit reached
    unknown: bool


def _ea(op: Operand, regs: np.ndarray) -> int | None:
    if op.base in (-3, -4, -5) or op.seg:
        return None
    if op.rip_rel:
        return op.disp
    ea = op.disp
    if op.base >= 0:
        ea += int(regs[op.base])
    if op.index >= 0:
        ea += int(regs[op.index]) * op.scale
    return ea & 0xFFFFFFFFFFFFFFFF


_SIMD_WIDTHS = (("vmovdq", 32), ("vmovap", 32), ("vmovup", 32),
                ("vlddqu", 32),
                ("movdq", 16), ("movap", 16), ("movup", 16), ("lddqu", 16),
                ("movlp", 8), ("movhp", 8))


def _mem_width(inst: Inst) -> int:
    # SIMD moves carry xmm/ymm operands (reg=-2, width unknown); size them
    # by mnemonic so a 16/32-byte access doesn't get recorded as ≤8 bytes
    # (an under-sized DEAD marking could hide host-visible SDC)
    for pfx, w in _SIMD_WIDTHS:
        if inst.mnemonic.startswith(pfx) and w:
            return w
    for o in inst.operands:
        if o.kind == "reg" and o.reg >= 0:
            return max(1, abs(o.width) // 8)
    sfx = inst.mnemonic[-1]
    return {"b": 1, "w": 2, "l": 4, "q": 8}.get(sfx, 8)


def classify_access(inst: Inst, regs: np.ndarray) -> Access:
    """Read/write sets of one dynamic instruction (conservative)."""
    mnem = inst.mnemonic
    stem = _stem(mnem)
    ops = inst.operands
    rr: list[int] = []
    rw: list[int] = []
    mr: list[tuple] = []
    mw: list[tuple] = []

    def addr_regs(o: Operand) -> None:
        if o.base >= 0:
            rr.append(o.base)
        if o.index >= 0:
            rr.append(o.index)

    def read_op(o: Operand, width: int) -> None:
        if o.kind == "reg" and o.reg >= 0:
            rr.append(o.reg)
        elif o.kind == "mem":
            addr_regs(o)
            a = _ea(o, regs)
            if a is not None:
                mr.append((a, width))

    def write_op(o: Operand, width: int) -> None:
        if o.kind == "reg" and o.reg >= 0:
            # 8/16-bit destinations merge into the old value (a read);
            # 32-bit zero-extends and 64-bit overwrites → full write
            if 0 < abs(o.width) < 32:
                rr.append(o.reg)
            rw.append(o.reg)
        elif o.kind == "mem":
            addr_regs(o)
            a = _ea(o, regs)
            if a is not None:
                mw.append((a, width))

    w = _mem_width(inst)

    if mnem.startswith(("rep", "repz", "repe", "repnz", "repne")):
        # objdump tokenizes "rep movsq %ds:(%rsi),%es:(%rdi)" with "rep" as
        # the mnemonic, so the element size is unrecoverable here.  Treat
        # BOTH ranges as reads (LIVE) — never as writes: with unknown
        # element size and direction a mis-sized DEAD marking could hide a
        # host-visible SDC, and over-live only over-reports.
        count = int(regs[RCX])
        if count == 0:
            return Access((RCX,), (RCX,), (), (), False, False)
        span = min(count, 1 << 22) * 8
        df_down = bool(int(regs[17]) & (1 << 10)) if len(regs) > 17 else False
        def rrng(base_reg):
            start = int(regs[base_reg])
            return (start - span + 8, span) if df_down else (start, span)
        return Access((RCX, RSI, RDI, RAX), (RCX, RSI, RDI),
                      (rrng(RSI), rrng(RDI)), (), False, False)
    if mnem in _STRING_EXACT:
        esz = {"b": 1, "w": 2, "l": 4, "q": 8}.get(mnem[-1], 8)
        kind = mnem[:4]
        # DF affects the post-access pointer update, not the address of
        # this element's access — the accessed range starts at the pointer
        def srng(base_reg):
            return (int(regs[base_reg]), esz)
        if kind in ("movs", "lods", "cmps"):
            rr.append(RSI)
            mr.append(srng(RSI))
        if kind in ("movs", "stos"):
            rr.append(RDI)
            mw.append(srng(RDI))
            if kind == "stos":
                rr.append(RAX)
        if kind in ("cmps", "scas"):
            rr.append(RDI)
            mr.append(srng(RDI))
            if kind == "scas":
                rr.append(RAX)
        rw.extend([RSI, RDI])
        if kind == "lods":
            rw.append(RAX)
        return Access(tuple(rr), tuple(rw), tuple(mr), tuple(mw), False, False)

    if stem == "syscall" or mnem == "syscall":
        nr = int(regs[RAX])
        rr.append(RAX)
        rr.extend(_SYSCALL_ARG_REGS)
        if nr == _SYS_WRITE:
            mr.append((int(regs[RSI]), int(regs[RDX])))
        stop = nr in (_SYS_EXIT, _SYS_EXIT_GROUP)
        return Access(tuple(rr), (RAX, RCX, R11), tuple(mr), (), stop, False)

    if stem in ("push",):
        for o in ops:
            read_op(o, 8)
        rr.append(RSP)
        mw.append((int(regs[RSP]) - 8, 8))
        return Access(tuple(rr), (RSP,), tuple(mr), tuple(mw), False, False)
    if stem in ("pop",):
        rr.append(RSP)
        mr.append((int(regs[RSP]), 8))
        for o in ops:
            write_op(o, 8)
        rw.append(RSP)
        return Access(tuple(rr), tuple(rw), tuple(mr), tuple(mw), False, False)
    if stem.startswith("call"):
        for o in ops:
            if o.kind == "reg":
                read_op(o, 8)
            elif o.kind == "mem":
                addr_regs(o)
                a = _ea(o, regs)
                if a is not None:
                    mr.append((a, 8))
        rr.append(RSP)
        mw.append((int(regs[RSP]) - 8, 8))
        return Access(tuple(rr), (RSP,), tuple(mr), tuple(mw), False, False)
    if stem.startswith("ret"):
        rr.append(RSP)
        mr.append((int(regs[RSP]), 8))
        return Access(tuple(rr), (RSP,), tuple(mr), (), False, False)
    if stem == "leave":
        rr.append(RBP)
        mr.append((int(regs[RBP]), 8))
        return Access((RBP,), (RSP, RBP), tuple(mr), (), False, False)
    if stem in _BRANCH_STEMS:
        for o in ops:
            if o.kind == "reg":
                read_op(o, 8)
            elif o.kind == "mem":
                addr_regs(o)
                a = _ea(o, regs)
                if a is not None:
                    mr.append((a, 8))
        return Access(tuple(rr), (), tuple(mr), (), False, False)
    if stem == "lea":
        # address computation only — the mem operand is NOT accessed
        for o in ops[:-1]:
            if o.kind == "mem":
                addr_regs(o)
            elif o.kind == "reg":
                read_op(o, w)
        if ops and ops[-1].kind == "reg":
            write_op(ops[-1], w)
        return Access(tuple(rr), tuple(rw), (), (), False, False)
    if stem in _RO_STEMS:
        for o in ops:
            read_op(o, w)
        return Access(tuple(rr), (), tuple(mr), (), False, False)
    if stem in _MOV_STEMS:
        for o in ops[:-1]:
            read_op(o, w)
        if ops:
            if stem == "cmov":          # may leave dst unchanged → read too
                read_op(ops[-1], w)
            write_op(ops[-1], w)
        return Access(tuple(rr), tuple(rw), tuple(mr), tuple(mw), False, False)
    if stem in _RMW_STEMS or stem in ("xchg",):
        for o in ops:
            read_op(o, w)
        if ops:
            write_op(ops[-1], w)
        if stem == "xchg" and len(ops) == 2:
            write_op(ops[0], w)
        if stem in ("mul", "imul") and len(ops) == 1:
            rr.append(RAX)
            rw.extend([RAX, RDX])
        return Access(tuple(rr), tuple(rw), tuple(mr), tuple(mw), False, False)
    if stem in ("div", "idiv"):
        for o in ops:
            read_op(o, w)
        rr.extend([RAX, RDX])
        return Access(tuple(rr), (RAX, RDX), tuple(mr), (), False, False)
    if stem in ("cdq", "cqo", "cltq", "cdqe", "cwtl", "cltd", "cqto"):
        return Access((RAX,), (RDX,) if stem in ("cdq", "cqo", "cltd",
                                                 "cqto") else (RAX,),
                      (), (), False, False)
    if stem in ("endbr64", "endbr32", "hlt", "ud2", "int3", "pause",
                "mfence", "lfence", "sfence", "cld", "std"):
        return Access((), (), (), (), False, False)
    if stem == "rdtsc":
        return Access((), (RAX, RDX), (), (), False, False)
    if stem == "cpuid":
        return Access((RAX, RCX), (RAX, RBX, RCX, RDX), (), (), False, False)

    # unknown: conservative — every operand both read and written
    for o in ops:
        read_op(o, w)
        write_op(o, w)
    return Access(tuple(rr), (), tuple(mr), tuple(mw), False, True)


class Liveness(NamedTuple):
    reg_live: np.ndarray        # bool[N_GPR] — read-before-write post-window
    mem_live32: set             # low-32 byte addresses (word-aligned) live
    steps: int
    truncated: bool             # hit max_steps before process exit
    unknown_insts: int

    def mem_word_mask(self, clusters, mem_words: int) -> np.ndarray:
        """Project live byte addresses onto the replay word array."""
        mask = np.zeros(mem_words, dtype=bool)
        for lo, hi, word_off in clusters:
            for a in self.mem_live32:
                if lo <= a < hi:
                    mask[word_off + (a - lo) // 4] = True
        return mask


def analyze(nt: NativeTrace, insts: dict[int, Inst],
            track32: "set | None" = None) -> Liveness:
    """First-access liveness over a post-window capture.

    ``track32``: optional set of low-32 word-aligned addresses to track
    (e.g. the replay clusters' footprint); accesses outside it are ignored,
    which keeps the scan cheap on libc-heavy exit paths."""
    reg_state = np.zeros(N_GPR, dtype=np.int8)
    mem_state: dict[int, int] = {}
    unknown = 0
    steps = nt.steps
    stopped = False

    def touch_mem(addr: int, nbytes: int, state: int) -> None:
        # A DEAD marking requires the word to be FULLY overwritten; a
        # sub-word write leaves live neighbor bytes in the word, so the
        # partially-covered head/tail words are marked LIVE instead
        # (over-live over-reports; a wrong DEAD hides real SDC).
        a0 = addr & ~0x3
        for a in range(a0, addr + nbytes, 4):
            a32 = a & M32
            if track32 is not None and a32 not in track32:
                continue
            if a32 not in mem_state:
                covered = addr <= a and (a + 4) <= (addr + nbytes)
                mem_state[a32] = state if (state == _LIVE or covered) \
                    else _LIVE

    n = len(steps)
    all_regs_live = False
    for i in range(n):
        regs = steps[i]
        pc = int(regs[16])
        inst = insts.get(pc)
        if inst is None:
            # code outside the static decode (vdso etc.): its register
            # reads are invisible, so later writes must not mark regs DEAD
            # — conservatively pin every still-unknown register LIVE once
            if not all_regs_live:
                reg_state[reg_state == _UNKNOWN] = _LIVE
                all_regs_live = True
            unknown += 1
            continue
        acc = classify_access(inst, regs)
        if acc.unknown:
            unknown += 1
        for r in acc.reg_reads:
            if 0 <= r < N_GPR and reg_state[r] == _UNKNOWN:
                reg_state[r] = _LIVE
        for a, nb in acc.mem_reads:
            touch_mem(a, nb, _LIVE)
        for a, nb in acc.mem_writes:
            touch_mem(a, nb, _DEAD)
        for r in acc.reg_writes:
            if 0 <= r < N_GPR and reg_state[r] == _UNKNOWN:
                reg_state[r] = _DEAD
        if acc.stop:
            stopped = True
            break

    live32 = {a for a, s in mem_state.items() if s == _LIVE}
    return Liveness(reg_live=reg_state == _LIVE, mem_live32=live32,
                    steps=n, truncated=not stopped and n > 0,
                    unknown_insts=unknown)


def capture_post_window(tracer: Path, workload: Path, end_sym_addr: int,
                        out_bin: Path, max_steps: int = 2_000_000) -> NativeTrace:
    """nativetrace from the kernel_end marker to process exit (end marker 0
    is never hit, so the tracer runs until the child exits — rc 1 with
    'child exited mid-window' is the expected clean outcome here)."""
    proc = subprocess.run(
        [str(tracer), str(out_bin), f"{end_sym_addr:x}", "0",
         str(max_steps), str(workload)],
        capture_output=True, text=True)
    if proc.returncode not in (0, 1) or not out_bin.exists():
        raise RuntimeError(f"post-window capture failed: {proc.stderr}")
    return read_nativetrace(out_bin)


def post_window_liveness(paths, clusters, build_dir: Path | None = None,
                         max_steps: int = 2_000_000,
                         allow_truncated: bool = False) -> Liveness:
    """Full pipeline: capture kernel_end→exit, decode, analyze.

    ``paths``: ingest.hostdiff.BuildPaths; ``clusters``: meta["clusters"]
    from the window lift ((lo, hi, word_off) triples).

    Raises on a truncated capture (max_steps hit before process exit)
    unless ``allow_truncated``: state the un-captured tail would have read
    stays UNKNOWN = treated dead, which silently under-reports SDC."""
    bd = build_dir or paths.workload.parent
    out_bin = bd / f"{paths.workload.name}_post.bin"
    nt = capture_post_window(paths.tracer, paths.workload, paths.end,
                             out_bin, max_steps)
    insts = static_decode(str(paths.workload))
    track = set()
    for lo, hi, _ in clusters:
        for a in range(lo & ~0x3, hi, 4):
            track.add(a)
    res = analyze(nt, insts, track32=track)
    if res.truncated and not allow_truncated:
        raise RuntimeError(
            f"post-window capture truncated at {res.steps} steps — raise "
            "max_steps (liveness from a truncated capture under-reports)")
    return res
