"""Restore + re-warm: checkpoint → replay-ready trace window.

gem5 checkpoints are architectural-only — O3 drains its pipeline before
serializing (``src/cpu/o3/cpu.cc:706-799``), so in-flight ROB/IQ/LSQ contents
never reach ``m5.cpt`` (SURVEY §5.4, hard part #3). The reference recovers
microarchitectural context by restoring arch state and running forward; this
module does the same on the framework side:

1. lift the snapshot's register values / memory image into the kernel's
   fixed-shape ``(nphys,)`` / ``(mem_words,)`` uint32 arrays,
2. advance ``warmup`` µops functionally (the scalar golden semantics —
   CheckerCPU analog) so the window starts from a warmed state,
3. emit a ``Trace`` whose window begins post-warmup.

Two window sources over the ingested state:

- ``window_from_snapshot_lifted`` — the REAL stream: the snapshot-seeded
  x86 emulator (ingest/emu.py) runs forward from the checkpoint PC over
  the checkpointed memory image, and the macro→µop lifter
  (ingest/lift.py) lifts that stream — restore-then-rewarm with the
  emulator standing in for the host CPU;
- ``window_from_snapshot`` — a synthetic stream over the snapshot state,
  for artifact-free runs (no binary available) and load benchmarks.
"""

from __future__ import annotations

import numpy as np

from shrewd_tpu.ingest.cpt import ArchSnapshot
from shrewd_tpu.trace import synth
from shrewd_tpu.trace.format import Trace


def window_from_snapshot_lifted(snap: ArchSnapshot, binary: str,
                                max_steps: int = 200_000,
                                max_uops: int | None = None
                                ) -> tuple[Trace, dict]:
    """Checkpoint → emulate forward from ``snap.pc`` → lift the real stream.

    Needs the checkpoint's region vaddrs (the config.json sidecar written
    by ``write_arch_snapshot``; the reference equivalently needs config.ini
    to place its stores).  Returns (trace, lift-meta); meta additionally
    records the emulator's stop point."""
    from shrewd_tpu.ingest.emu import emulate_window
    from shrewd_tpu.ingest.lift import lift, static_decode

    if not snap.regions:
        raise ValueError(
            "checkpoint lacks region vaddrs (config.json sidecar) — the "
            "lifted restore path cannot address the memory image; "
            "re-checkpoint via write_arch_snapshot or use the synthetic "
            "window_from_snapshot")
    if snap.int_regs.size < 16:
        raise ValueError(f"{snap.int_regs.size} integer registers in "
                         "checkpoint; need the 16 x86-64 GPRs")
    regions = []
    off = 0
    for vaddr, size in snap.regions:
        regions.append((int(vaddr), snap.mem[off:off + size].tobytes()))
        off += size
    insts = static_decode(binary)
    res = emulate_window(binary, snap.int_regs, regions, snap.pc, max_steps,
                         insts=insts)
    trace, meta = lift("<emu>", binary, max_uops=max_uops, nt=res.nt,
                       insts=insts)
    meta["emu_steps"] = res.steps
    meta["emu_stop_reason"] = res.stop_reason
    meta["emu_stop_pc"] = res.stop_pc
    return trace, meta


def lift_registers(snap: ArchSnapshot, nphys: int) -> np.ndarray:
    """Architectural uint64 regs → (nphys,) uint32 physical file.

    Low/high 32-bit halves interleave into consecutive entries (x86-64 arch
    values are 64-bit; the µop ISA is 32-bit). Physical registers beyond the
    architectural set start at a deterministic hash of (pc, index) — their
    true values are microarchitectural state a checkpoint cannot carry, and
    the warmup replay overwrites the ones that matter.
    """
    out = np.zeros(nphys, dtype=np.uint32)
    arch = snap.int_regs
    lo = (arch & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (arch >> np.uint64(32)).astype(np.uint32)
    inter = np.empty(2 * arch.size, dtype=np.uint32)
    inter[0::2], inter[1::2] = lo, hi
    if inter.size > nphys:
        raise ValueError(
            f"snapshot carries {arch.size} integer registers "
            f"({inter.size} uint32 halves) but nphys={nphys}; dropping "
            f"architectural state would silently corrupt the golden replay — "
            f"use nphys >= {inter.size}")
    n_arch = inter.size
    out[:n_arch] = inter
    if nphys > n_arch:
        idx = np.arange(n_arch, nphys, dtype=np.uint64)
        mix = (idx * np.uint64(0x9E3779B97F4A7C15)
               + np.uint64(snap.pc)) >> np.uint64(16)
        out[n_arch:] = mix.astype(np.uint32)
    return out


def lift_memory(snap: ArchSnapshot, mem_words: int,
                base_addr: int = 0) -> np.ndarray:
    """Physical image bytes → (mem_words,) little-endian uint32 words
    starting at ``base_addr`` (word-aligned); zero-fill past the image."""
    if base_addr % 4:
        raise ValueError("base_addr must be word-aligned")
    out = np.zeros(mem_words, dtype=np.uint32)
    raw = snap.mem[base_addr:base_addr + 4 * mem_words]
    usable = raw.size // 4
    if usable:
        out[:usable] = raw[:4 * usable].view("<u4")
    return out


def window_from_snapshot(snap: ArchSnapshot, cfg: synth.WorkloadConfig,
                         warmup: int = 0) -> Trace:
    """Build a replay window over ingested golden state.

    ``warmup`` µops are generated and *retired functionally* before the
    captured window starts (step 2 above); the returned trace's
    ``init_reg``/``init_mem`` is the post-warmup state.
    """
    full_cfg = type(cfg).from_dict({**cfg.to_dict(), "n": cfg.n + warmup})
    init_reg = lift_registers(snap, cfg.nphys)
    init_mem = lift_memory(snap, cfg.mem_words)
    if warmup == 0:
        return synth.generate(full_cfg, init_reg=init_reg, init_mem=init_mem)

    # the generator retires every µop as it goes; capture the post-warmup
    # state in-stream instead of replaying the prefix a second time
    full, reg, mem = synth.generate(full_cfg, init_reg=init_reg,
                                    init_mem=init_mem, capture_at=warmup)
    trace = Trace(opcode=full.opcode[warmup:], dst=full.dst[warmup:],
                  src1=full.src1[warmup:], src2=full.src2[warmup:],
                  imm=full.imm[warmup:], taken=full.taken[warmup:],
                  init_reg=reg, init_mem=mem)
    trace.validate()
    return trace
