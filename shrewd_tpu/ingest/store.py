"""Content-digest-keyed artifact store for the streaming ingest pipeline.

Every artifact the ingest pipeline produces — the raw capture, the lifted
full-window trace, liveness masks, BBV/SimPoint clusters, per-window
traces and their boundary goldens — is keyed by the CONTENT digest of the
submitted binary plus a canonical hash of the ingest axes (interval, k,
seed, max_steps).  A previously-seen ``(digest, axes)`` pair therefore
starts its campaign in O(1) from the shared store: no capture, no lift,
no emulation — the terminal ``plan`` document points at window traces
that are already durable.

Durability discipline (the same contract the WAL tier certifies):

- JSON documents go through ``resilience.write_json_atomic`` (tmp +
  fsync + rename + dir-fsync) and carry content checksums;
- binary payloads (captures, ``.npz`` windows) are committed via the
  same tmp/fsync/rename/dir-fsync sequence, and each owning document
  records the payload's sha256 — ``get_doc`` re-verifies every byte it
  vouches for, so a torn or rotted payload reads as a MISS (re-lift),
  never as silent corruption;
- a missing/torn/checksum-failed document is also just a miss.  The
  store never quarantines: "this artifact is unusable, recompute it" is
  a cache decision.  "this BINARY is not what its digest claims" is
  poison, and that verdict belongs to the pipeline/queue tier.

Single-flight: two concurrent submissions of the same ``(digest, axes)``
share one lift through an O_EXCL lock file under the object directory
(the ``ServerLock`` discipline: pid-stamped, stale locks reaped).  The
loser waits, then finds the winner's artifacts and warm-starts.

Import discipline: jax-free (pure host-side file work; the pipeline
that fills the store owns the heavy lifter/emulator imports).
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from shrewd_tpu import resilience as resil
from shrewd_tpu.utils import debug

debug.register_flag("Ingest", "streaming ingest pipeline / artifact store")

#: lock files held by THIS process (``_SingleFlight`` bookkeeping): a
#: lock on disk stamped with our pid but absent here is the residue of a
#: chaos kill that unwound without releasing — stale, reap it
_HELD: set = set()


def data_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def axes_key(axes: dict | None) -> str:
    """Canonical short key for an ingest-axes dict (sorted-key JSON →
    sha256 prefix): the second half of the store address."""
    blob = json.dumps(dict(axes or {}), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class _SingleFlight:
    """O_EXCL pid-stamped lock on one ``(digest, axes)`` object dir.

    Same reaping posture as ``service.queue.ServerLock``: a dead-pid or
    unreadable lock is stale and reaped; additionally a lock stamped
    with OUR pid that this process does not hold in ``_HELD`` is the
    residue of an in-process chaos kill (the raising ``kill_action``
    unwound past the release) and is reaped the same way."""

    def __init__(self, path: str, timeout_s: float = 120.0):
        self.path = path
        self.timeout_s = timeout_s
        self._owned = False

    def _stale(self) -> bool:
        try:
            with open(self.path) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            return True
        if pid == os.getpid():
            return self.path not in _HELD
        if pid <= 0:
            return True
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        except OSError:
            return False
        return False

    def __enter__(self) -> "_SingleFlight":
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if self._stale():
                    try:
                        os.unlink(self.path)
                    except OSError:
                        pass
                    continue
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{self.path}: single-flight lock held past "
                        f"{self.timeout_s}s")
                time.sleep(0.02)
                continue
            try:
                os.write(fd, f"{os.getpid()}\n".encode())
            finally:
                os.close(fd)
            self._owned = True
            _HELD.add(self.path)
            return self

    def __exit__(self, *exc) -> None:
        if not self._owned:
            return
        _HELD.discard(self.path)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self._owned = False


class ArtifactStore:
    """The digest-keyed store (see module doc).

    Layout::

        <root>/bin/<sha256>.elf                  submitted binaries
        <root>/obj/<sha256>/<axes>/<name>.json   checksummed stage docs
        <root>/obj/<sha256>/<axes>/<file>        payloads (sha in doc)
        <root>/obj/<sha256>/<axes>/.lock         single-flight guard
        <root>/exec/                             jax persistent
                                                 compilation cache
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.bin_dir = os.path.join(self.root, "bin")
        self.obj_root = os.path.join(self.root, "obj")
        os.makedirs(self.bin_dir, exist_ok=True)
        os.makedirs(self.obj_root, exist_ok=True)

    def exec_dir(self) -> str:
        """The cross-pod COMPILE-REUSE artifact kind: a directory for
        jax's persistent compilation cache
        (``exec_cache.enable_persistent_cache``), living beside the
        ingest objects so a federation that threads one store root
        through every pod also shares every compiled step — a cell
        compiled on pod0 is a cache hit on the pod an autoscaler spawned
        ten rounds later.  jax keys entries by content fingerprint of
        the computation + compile options + backend, so the store needs
        no extra addressing discipline here; entries are moved into
        place atomically by jax itself and a torn/absent entry is just a
        miss (recompile), never corruption — the same posture as every
        other artifact kind above."""
        d = os.path.join(self.root, "exec")
        os.makedirs(d, exist_ok=True)
        return d

    # --- submitted binaries ----------------------------------------------

    def binary_path(self, digest: str) -> str:
        return os.path.join(self.bin_dir, f"{digest}.elf")

    def put_binary(self, data: bytes) -> str:
        """Content-address one submitted binary; idempotent (a second
        submission of the same bytes is a no-op hit)."""
        digest = data_digest(data)
        path = self.binary_path(digest)
        if os.path.exists(path):
            return digest
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o755)
        os.replace(tmp, path)
        resil.fsync_dir(self.bin_dir)
        resil.notify_durability("rename", path, kind="store_binary")
        debug.dprintf("Ingest", "stored binary %s (%d bytes)",
                      digest[:12], len(data))
        return digest

    def verify_binary(self, digest: str) -> bool:
        """Does the stored binary still hash to its address?  False is
        POISON (rot/tamper), not a cache miss — the caller quarantines."""
        path = self.binary_path(digest)
        try:
            return file_digest(path) == digest
        except OSError:
            return False

    # --- object directories ----------------------------------------------

    def obj_dir(self, digest: str, key: str) -> str:
        d = os.path.join(self.obj_root, digest, key)
        os.makedirs(d, exist_ok=True)
        return d

    def payload_path(self, digest: str, key: str, filename: str) -> str:
        return os.path.join(self.obj_dir(digest, key), filename)

    def commit_payload(self, tmp_path: str, digest: str, key: str,
                       filename: str) -> str:
        """Durably move a finished scratch file into the store (fsync →
        rename → dir-fsync) and return its sha256 for the owning doc."""
        sha = file_digest(tmp_path)
        final = self.payload_path(digest, key, filename)
        with open(tmp_path, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp_path, final)
        resil.fsync_dir(os.path.dirname(final))
        resil.notify_durability("rename", final, kind="store_payload")
        return sha

    def write_payload(self, digest: str, key: str, filename: str,
                      data: bytes) -> str:
        tmp = self.payload_path(digest, key, filename) \
            + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        return self.commit_payload(tmp, digest, key, filename)

    # --- documents --------------------------------------------------------

    def put_doc(self, digest: str, key: str, name: str,
                doc: dict) -> None:
        """Persist one stage document (checksummed, atomic).  ``doc``
        may carry ``payloads: {filename: sha256}`` — ``get_doc``
        re-verifies each before vouching for the document."""
        resil.write_json_atomic(
            os.path.join(self.obj_dir(digest, key), f"{name}.json"),
            dict(doc))

    def get_doc(self, digest: str, key: str, name: str) -> dict | None:
        """Load + verify one stage document AND every payload it
        records.  ANY failure — missing file, torn JSON, checksum
        mismatch, rotted payload — is a miss (None): the pipeline
        recomputes, it never trusts a damaged artifact."""
        path = os.path.join(self.obj_root, digest, key, f"{name}.json")
        try:
            doc = resil.load_json_verified(path)
        except (OSError, ValueError):
            return None
        for filename, sha in (doc.get("payloads") or {}).items():
            ppath = os.path.join(self.obj_root, digest, key, filename)
            try:
                if file_digest(ppath) != sha:
                    debug.dprintf("Ingest", "payload %s rotted — miss",
                                  filename)
                    return None
            except OSError:
                return None
        return doc

    # --- array artifacts ---------------------------------------------------
    #
    # Generic SoA-array kind (the preprocessed chunk windows, ops/window.py):
    # one checksummed document owning one ``.npy`` payload per named array.
    # numpy is imported inside the methods — the module stays import-light
    # for the jax-free service tier.

    def put_arrays(self, digest: str, key: str, name: str, arrays: dict,
                   meta: dict | None = None) -> dict:
        """Persist ``{field: ndarray}`` as ``<name>.<field>.npy`` payloads
        plus the owning ``<name>.json`` doc (payload shas recorded, so
        ``get_doc``/``get_arrays`` re-verify every byte).  Returns the doc."""
        import numpy as np

        payloads = {}
        for field_name, arr in arrays.items():
            filename = f"{name}.{field_name}.npy"
            tmp = self.payload_path(digest, key, filename) \
                + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, np.ascontiguousarray(arr))
            payloads[filename] = self.commit_payload(
                tmp, digest, key, filename)
        doc = dict(meta or {})
        doc["fields"] = sorted(arrays)
        doc["payloads"] = payloads
        self.put_doc(digest, key, name, doc)
        debug.dprintf("Ingest", "stored %d arrays under %s/%s/%s",
                      len(arrays), digest[:12], key, name)
        return doc

    def get_arrays(self, digest: str, key: str, name: str,
                   mmap: bool = True):
        """Load one array artifact → ``(doc, {field: ndarray})`` or None
        (miss).  ``mmap=True`` maps payloads read-only — chunk windows
        materialize lazily, so a 26M-µop window opens in O(1)."""
        import numpy as np

        doc = self.get_doc(digest, key, name)
        if doc is None:
            return None
        arrays = {}
        for field_name in doc.get("fields") or []:
            path = self.payload_path(digest, key, f"{name}.{field_name}.npy")
            try:
                arrays[field_name] = np.load(
                    path, mmap_mode="r" if mmap else None)
            except (OSError, ValueError):
                return None
        return doc, arrays

    def lock(self, digest: str, key: str) -> _SingleFlight:
        return _SingleFlight(
            os.path.join(self.obj_dir(digest, key), ".lock"))
