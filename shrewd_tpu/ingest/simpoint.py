"""SimPoint methodology: BBV profiling + representative-window selection.

The reference profiles basic-block vectors with a probe on the simple CPU
(``/root/reference/src/cpu/simple/probes/simpoint.hh:82``) and picks
representative simulation windows offline (the classic SimPoint k-means
pipeline); campaigns then run only the representatives, weighted by cluster
size.  VERDICT r2 (missing #6) called out that this framework's windows
were marker slices with no representativeness story.

Here the dynamic pc stream comes from a capture (tools/nativetrace.cc) or
the bit-exact emulator (ingest/emu.py):

1. ``bbv_profile``   — split the stream into fixed-length intervals; each
   interval's BBV counts instructions per basic block (block = maximal
   run of sequential pcs, identified by its head pc — the probe's notion).
2. ``choose_simpoints`` — random-project the BBVs (the SimPoint paper's
   dimensionality reduction), k-means them (numpy Lloyd iterations with a
   deterministic seed), and return one representative interval per
   cluster with its weight (cluster population share).
3. ``simpoint_windows`` — end-to-end for a marker workload: capture, pick
   representatives, and build each representative's replay window by
   emulating to its start (exact, ingest/emu.py) and lifting the interval
   — so a campaign measures k windows instead of the whole stream and
   reports the weighted AVF.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class BBVProfile(NamedTuple):
    bbvs: np.ndarray          # float64[n_intervals, n_blocks] (instr counts)
    block_heads: np.ndarray   # uint64[n_blocks] head pc per block id
    interval: int


def bbv_profile(pcs: np.ndarray, interval: int,
                lengths: "np.ndarray | None" = None) -> BBVProfile:
    """Dynamic pc stream → per-interval basic-block vectors.

    ``lengths`` optionally gives each step's instruction length; block
    boundaries are where ``pc[i+1] != pc[i] + len(i)`` (taken control
    flow).  Without lengths, any non-monotonic-small step starts a block
    (a conservative approximation that still keys on control flow)."""
    pcs = np.asarray(pcs, dtype=np.uint64)
    n = len(pcs)
    if n == 0:
        raise ValueError("empty pc stream")
    if lengths is not None:
        seq = pcs[1:] == pcs[:-1] + np.asarray(lengths[:-1], np.uint64)
    else:
        delta = pcs[1:].astype(np.int64) - pcs[:-1].astype(np.int64)
        seq = (delta > 0) & (delta <= 16)
    # step i starts a new block iff the previous transition was taken
    starts = np.empty(n, dtype=bool)
    starts[0] = True
    starts[1:] = ~seq
    head_of_block = pcs[starts]
    # map every step to its block's head pc
    block_idx_per_step = np.cumsum(starts) - 1
    heads, inv = np.unique(head_of_block, return_inverse=True)
    step_block = inv[block_idx_per_step]

    n_iv = (n + interval - 1) // interval
    bbvs = np.zeros((n_iv, len(heads)), dtype=np.float64)
    iv = np.arange(n) // interval
    np.add.at(bbvs, (iv, step_block), 1.0)
    return BBVProfile(bbvs=bbvs, block_heads=heads, interval=interval)


class SimPoints(NamedTuple):
    intervals: np.ndarray     # int64[k] representative interval indices
    weights: np.ndarray       # float64[k] cluster population share
    labels: np.ndarray        # int64[n_intervals] cluster per interval


def choose_simpoints(profile: BBVProfile, k: int,
                     seed: int = 0, proj_dim: int = 16,
                     iters: int = 25) -> SimPoints:
    """Project → k-means → per-cluster representative (closest-to-centroid),
    deterministic under ``seed``."""
    x = profile.bbvs
    n_iv = x.shape[0]
    k = min(k, n_iv)
    # normalize per interval (instruction-count invariance), then project
    norm = x.sum(axis=1, keepdims=True)
    x = x / np.maximum(norm, 1.0)
    rng = np.random.default_rng(seed)
    if x.shape[1] > proj_dim:
        proj = rng.normal(size=(x.shape[1], proj_dim)) / np.sqrt(proj_dim)
        x = x @ proj
    # k-means++ style init: spread the seeds deterministically
    centers = [x[int(rng.integers(n_iv))]]
    for _ in range(k - 1):
        d2 = np.min(
            [((x - c) ** 2).sum(axis=1) for c in centers], axis=0)
        tot = float(d2.sum())
        if tot <= 0.0:
            # every interval coincides with an existing center (phase-
            # homogeneous workload): fewer clusters than requested
            break
        centers.append(x[int(rng.choice(n_iv, p=d2 / tot))])
    k = len(centers)
    c = np.stack(centers)
    labels = np.zeros(n_iv, dtype=np.int64)
    for _ in range(iters):
        d = ((x[:, None, :] - c[None, :, :]) ** 2).sum(axis=2)
        labels = d.argmin(axis=1)
        for j in range(k):
            sel = labels == j
            if sel.any():
                c[j] = x[sel].mean(axis=0)
    reps = np.zeros(k, dtype=np.int64)
    weights = np.zeros(k, dtype=np.float64)
    d = ((x[:, None, :] - c[None, :, :]) ** 2).sum(axis=2)
    for j in range(k):
        sel = np.nonzero(labels == j)[0]
        if len(sel) == 0:
            continue                  # dropped below (weight stays 0)
        reps[j] = sel[d[sel, j].argmin()]
        weights[j] = len(sel) / n_iv
    # drop empty clusters: a zero-weight representative contributes nothing
    # to the weighted AVF but would still cost an emulate+lift pass
    keep = np.nonzero(weights > 0)[0]
    remap = np.full(k, -1, dtype=np.int64)
    remap[keep] = np.arange(len(keep))
    reps, weights = reps[keep], weights[keep]
    labels = remap[labels]            # empty clusters had no members
    weights /= max(weights.sum(), 1e-12)
    return SimPoints(intervals=reps, weights=weights, labels=labels)


def simpoint_windows(paths, interval: int = 2000, k: int = 3,
                     max_steps: int = 2_000_000, seed: int = 0):
    """Marker workload → k representative lifted windows + weights.

    Each representative window's start state comes from emulating the
    captured program (bit-exact vs silicon, tests/test_emu.py) up to the
    interval boundary; the window itself is emulated then lifted —
    restore-then-rewarm without any checkpoint file in the loop."""
    import subprocess

    from shrewd_tpu.ingest.emu import Emulator, StopEmu, elf_regions
    from shrewd_tpu.ingest.lift import lift, read_nativetrace, static_decode

    bd = paths.workload.parent
    import os
    trace_bin = bd / f"{paths.workload.name}_sp.{os.getpid()}.bin"
    try:
        subprocess.run(
            [str(paths.tracer), str(trace_bin), f"{paths.begin:x}",
             f"{paths.end:x}", str(max_steps), str(paths.workload)],
            check=True, capture_output=True, text=True)
        nt = read_nativetrace(trace_bin)
    finally:
        trace_bin.unlink(missing_ok=True)
    steps = nt.steps[:-1]
    profile = bbv_profile(steps[:, 16], interval)
    sps = choose_simpoints(profile, k, seed=seed)

    insts = static_decode(str(paths.workload))
    regions = [(v, d) for v, d in nt.regions]
    regions += elf_regions(str(paths.workload))
    out = []
    for rep, weight in zip(sps.intervals, sps.weights):
        start = int(rep) * interval
        length = min(interval, len(steps) - start)
        emu = Emulator(insts, nt.steps[0][:16], regions,
                       int(nt.steps[0][16]), fs_base=nt.fs_base)
        try:
            for _ in range(start):
                emu.step()
        except StopEmu as e:       # pragma: no cover — capture covers this
            raise RuntimeError(f"emulation to window start failed: {e}")
        # snapshot the window-START memory image before the window runs
        # (Emulator.run hands back post-run buffers)
        snap_regions = [(r.vaddr, bytes(r.buf)) for r in emu.regions]
        res = emu.run(length)
        trace, meta = lift("<simpoint>", str(paths.workload),
                           nt=res.nt._replace(regions=snap_regions),
                           insts=insts)
        meta["simpoint_interval"] = int(rep)
        meta["simpoint_weight"] = float(weight)
        meta["simpoint_start_step"] = start
        out.append((trace, meta))
    return out, sps, profile
