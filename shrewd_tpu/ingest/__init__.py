"""Snapshot ingestion: golden-run artifacts → typed arrays.

The bridge from the reference's serial C++ campaign to device state
(SURVEY §7 build-order step 1): parse checkpoint (`m5.cpt` ini format,
reference ``src/sim/serialize.hh:68-85``), ``config.ini``/``config.json``
elaboration dumps (``src/python/m5/simulate.py:106-124``), and ``stats.txt``
(``src/base/stats/text.cc``), then lift architectural state into the replay
kernel's initial-state arrays.
"""

from shrewd_tpu.ingest.cpt import (ArchSnapshot, CheckpointIn, CheckpointOut,
                                   load_arch_snapshot, snapshot_from_capture,
                                   write_arch_snapshot)
from shrewd_tpu.ingest.pipeline import (DEFAULT_AXES, STAGES, IngestPipeline,
                                        IngestQuarantine, normalize_axes)
from shrewd_tpu.ingest.store import ArtifactStore, axes_key, data_digest
from shrewd_tpu.ingest.configfile import load_config_ini, load_config_json
from shrewd_tpu.ingest.statsfile import load_stats_txt
from shrewd_tpu.ingest.warm import (window_from_snapshot,
                                    window_from_snapshot_lifted)

__all__ = [
    "ArchSnapshot", "ArtifactStore", "CheckpointIn", "CheckpointOut",
    "DEFAULT_AXES", "IngestPipeline", "IngestQuarantine", "STAGES",
    "axes_key", "data_digest", "normalize_axes",
    "load_arch_snapshot", "snapshot_from_capture", "write_arch_snapshot",
    "load_config_ini", "load_config_json", "load_stats_txt",
    "window_from_snapshot", "window_from_snapshot_lifted",
]
