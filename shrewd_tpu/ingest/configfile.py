"""Elaboration-dump readers: ``config.ini`` / ``config.json``.

The reference dumps its fully-elaborated object tree at instantiate
(``src/python/m5/simulate.py:106-124``): an ini file with one section per
SimObject (dotted path, ``children=`` edge list) and a nested json. These
readers recover a nested dict so campaign tooling can pull machine parameters
(ROB size, cache geometry, FU pool shape) out of a golden run's output
directory without re-parsing gem5 Python.

They also read this framework's own ``ConfigObject.dump_ini/dump_json``
output (utils/config.py keeps the same shape on purpose).
"""

from __future__ import annotations

import json
import re

_SECTION_RE = re.compile(r"^\[(.+)\]$")


def parse_ini(f, what: str = "ini") -> dict[str, dict[str, str]]:
    """Shared ini-database parser (the IniFile analog) used for both
    ``config.ini`` and ``m5.cpt`` — one format, one parser."""
    sections: dict[str, dict[str, str]] = {}
    current: dict[str, str] | None = None
    for raw in f:
        line = raw.strip()
        if not line or line.startswith((";", "#")):
            continue
        m = _SECTION_RE.match(line)
        if m:
            current = sections.setdefault(m.group(1), {})
            continue
        if current is None or "=" not in line:
            raise ValueError(f"malformed {what} line: {raw!r}")
        key, _, value = line.partition("=")
        current[key.strip()] = value.strip()
    return sections


def load_config_ini(path: str) -> dict[str, dict[str, str]]:
    """Flat view: dotted-path section → {param: raw string}."""
    with open(path) as f:
        return parse_ini(f, "config.ini")


def tree_from_ini(sections: dict[str, dict[str, str]]) -> dict:
    """Re-nest a flat ini dump using the ``children=`` edges."""
    def build(path: str) -> dict:
        sec = dict(sections[path])
        node: dict = {k: v for k, v in sec.items() if k != "children"}
        for child in sec.get("children", "").split():
            child_path = f"{path}.{child}"
            if child_path in sections:
                node[child] = build(child_path)
        return node

    roots = [p for p in sections if "." not in p]
    return {r: build(r) for r in roots}


def load_config_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def find_params(tree: dict, name: str) -> list[tuple[str, object]]:
    """All (dotted.path, value) occurrences of a param name in a nested
    config tree — the `Parent.any` style lookup done offline."""
    out: list[tuple[str, object]] = []

    def walk(node: dict, prefix: str) -> None:
        for k, v in node.items():
            if isinstance(v, dict):
                walk(v, f"{prefix}.{k}" if prefix else k)
            elif k == name:
                out.append((f"{prefix}.{k}" if prefix else k, v))

    walk(tree, "")
    return out
