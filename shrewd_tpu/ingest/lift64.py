"""64-bit pair-lane lift: the full-width device datapath (VERDICT r3 #5).

The 32-bit lifter projects x86-64 state onto low-32 lanes, so the replay
kernel could only model fault bits [0,32) — the upper halves of the
reference's 64-bit ``PhysRegFile`` banks
(``/root/reference/src/cpu/o3/regfile.hh:65-99``) were out of reach of the
*device* and round 3 substituted the host emulator.  This module lifts the
same captures into **register pairs over the unchanged 32-bit µop ISA**:
architectural register ``r`` lives in phys ``r`` (bits 31:0) and phys
``r+32`` (bits 63:32), and 64-bit x86 semantics are expressed as short
carry/borrow µop sequences (the classic RV32-style lowering).  Nothing in
the dense/taint/Pallas kernels or the C++ golden changes — the TPU really
executes the 64-bit dataflow, and a REGFILE fault coordinate
``(reg, bit)`` with bit ∈ [0,64) maps to phys ``(reg + 32·(bit≥32),
bit mod 32)``.

Correctness authority: the per-macro-op self-check now compares the FULL
captured 64-bit register file (``_regs_match``), so any hi-lane semantics
this lifter gets wrong demote that macro-op to an opaque resync instead of
silently corrupting the golden — the same fail-closed discipline as the
32-bit lift.

Address faithfulness: replay addresses stay in the folded low-32 cluster
space, so a *hi-lane* deviation of an address register would otherwise be
invisible to the memory system.  Every memory access therefore carries a
hi-guard: the contributing registers' hi lanes are XORed against their
captured golden values and any deviation ORs a 2^30 poison into the
effective address, which throws it outside every mapped-region window of
the VA crash model (ops/replay.MemMap) — exactly the silicon outcome,
where any hi-bit pointer corruption faults.
"""

from __future__ import annotations

import numpy as np

from shrewd_tpu.ingest.lift import (M32, N_GPR, T0, T1, T2, T3, T4, T5, T6,
                                    T7, TCMP, ZERO, Inst, Lifter, NativeTrace,
                                    Operand, _JCC_SIGNED, _JCC_UNSIGNED,
                                    read_nativetrace, static_decode)
from shrewd_tpu.isa import uops as U

HI = 32                      # hi-lane offset: phys(hi(r)) = r + HI
G0, G1 = 26, 27              # guard scratch (lo-lane space, never arch)
NPHYS64 = 64
M64 = 0xFFFFFFFFFFFFFFFF


def hi(r: int) -> int:
    return r + HI


def _sx32(v: int) -> int:
    """x86 imm32 → imm64 sign extension (the default for every non-movabs
    64-bit immediate form)."""
    v &= M64
    if v & 0x80000000 and (v >> 32) == 0:
        v |= 0xFFFFFFFF00000000
    return v


class Lifter64(Lifter):
    """Pair-lane lifter: explicit 64-bit handlers first, then delegation
    to the 32-bit handlers with an architectural hi-zero fix for 32-bit
    register writes (x86 zeroes bits 63:32 on every 32-bit write,
    data-independently), all under full-width verification."""

    # phys 32..57 are the GPR/temp hi lanes here, so the base lifter's
    # FP bank (FX0=32..47) cannot coexist — xmm instructions demote in
    # 64-bit mode (use the 32-bit lift for FP campaigns)
    FP_BASE = None

    # mnemonics whose last operand is NOT a written register destination
    _NO_DEST = ("cmp", "test", "push", "bt", "j", "call", "ret", "nop")

    def __init__(self, nt: NativeTrace, insts: dict[int, Inst],
                 max_uops: int | None = None, elf_regs: list | None = None):
        super().__init__(nt, insts, max_uops=max_uops, elf_regs=elf_regs)
        self.reg = np.zeros(NPHYS64, dtype=np.uint64)

    # -- width hooks -------------------------------------------------------

    def _seed_regs(self, step0: np.ndarray) -> None:
        self.reg[:] = 0
        self.reg[:N_GPR] = step0[:N_GPR] & np.uint64(M32)
        self.reg[HI:HI + N_GPR] = step0[:N_GPR] >> np.uint64(32)

    def _full(self, r: int) -> int:
        return int(self.reg[r]) | (int(self.reg[hi(r)]) << 32)

    def _regs_match(self, next_full: np.ndarray) -> bool:
        got = self.reg[:N_GPR] | (self.reg[HI:HI + N_GPR] << np.uint64(32))
        return bool((got == next_full[:N_GPR]).all())

    def _resync_regs(self, next_full: np.ndarray) -> None:
        lo_want = next_full[:N_GPR] & np.uint64(M32)
        hi_want = next_full[:N_GPR] >> np.uint64(32)
        for r in np.nonzero(self.reg[:N_GPR] != lo_want)[0]:
            self._emit_resync(int(r), int(lo_want[r]))
        for r in np.nonzero(self.reg[HI:HI + N_GPR] != hi_want)[0]:
            self._emit_resync(hi(int(r)), int(hi_want[r]))

    def _final_reg_expect(self, vals: np.ndarray) -> list:
        return [int(x) for x in vals[:N_GPR]]

    # -- pair emission helpers --------------------------------------------

    def _const64(self, v: int, treg: int) -> int:
        self._emit(U.LUI, treg, ZERO, ZERO, v & M32)
        self._emit(U.LUI, hi(treg), ZERO, ZERO, (v >> 32) & M32)
        return treg

    def _mov64(self, d: int, s: int) -> None:
        if d == s:
            return
        self._emit(U.ADD, d, s, ZERO)
        self._emit(U.ADD, hi(d), hi(s), ZERO)

    def _add64(self, d: int, a: int, b: int) -> None:
        """d = a + b (64-bit, carry via SLTU; d may alias a or b, but b
        must not be the T7 scratch pair)."""
        assert b != T7, "T7 pair is _add64 scratch"
        self._emit(U.ADD, T7, a, b)             # lo sum
        self._emit(U.SLTU, hi(T7), T7, a)       # carry-out ⟺ sum < a
        self._emit(U.ADD, hi(d), hi(a), hi(b))
        self._emit(U.ADD, hi(d), hi(d), hi(T7))
        self._emit(U.ADD, d, T7, ZERO)

    def _addi64(self, d: int, a: int, imm: int) -> None:
        self._const64(imm & M64, T4)
        self._add64(d, a, T4)

    def _sub64(self, d: int, a: int, b: int) -> None:
        self._emit(U.SLTU, hi(T7), a, b)        # borrow
        self._emit(U.SUB, T7, a, b)
        self._emit(U.SUB, hi(d), hi(a), hi(b))
        self._emit(U.SUB, hi(d), hi(d), hi(T7))
        self._emit(U.ADD, d, T7, ZERO)

    def _logic64(self, op: int, d: int, a: int, b: int) -> None:
        self._emit(op, d, a, b)
        self._emit(op, hi(d), hi(a), hi(b))

    def _shl64_imm(self, d: int, a: int, c: int) -> None:
        c &= 63
        if c == 0:
            self._mov64(d, a)
            return
        self._emit(U.ADDI, T7, ZERO, ZERO, c & 31)
        if c < 32:
            self._emit(U.ADDI, hi(T7), ZERO, ZERO, 32 - c)
            self._emit(U.SLL, T4, hi(a), T7)
            self._emit(U.SRL, hi(T4), a, hi(T7))
            self._emit(U.OR, hi(d), T4, hi(T4))
            self._emit(U.SLL, d, a, T7)
        else:
            self._emit(U.SLL, hi(d), a, T7)     # shift amount (c-32)&31
            self._emit(U.LUI, d, ZERO, ZERO, 0)

    def _shr64_imm(self, d: int, a: int, c: int, arith: bool) -> None:
        c &= 63
        sh = U.SRA if arith else U.SRL
        if c == 0:
            self._mov64(d, a)
            return
        self._emit(U.ADDI, T7, ZERO, ZERO, c & 31)
        if c < 32:
            self._emit(U.ADDI, hi(T7), ZERO, ZERO, 32 - c)
            self._emit(U.SRL, T4, a, T7)
            self._emit(U.SLL, hi(T4), hi(a), hi(T7))
            self._emit(U.OR, d, T4, hi(T4))
            self._emit(sh, hi(d), hi(a), T7)
        else:
            self._emit(sh, d, hi(a), T7)        # amount (c-32)&31
            if arith:
                self._emit(U.ADDI, T7, ZERO, ZERO, 31)
                self._emit(U.SRA, hi(d), hi(a), T7)
            else:
                self._emit(U.LUI, hi(d), ZERO, ZERO, 0)

    def _ltu64(self, dst: int, alo: int, ahi: int, blo: int, bhi: int,
               signed: bool) -> None:
        """dst(lo) = (a < b) over the 64-bit pairs, 0/1."""
        self._emit(U.SLT if signed else U.SLTU, dst, ahi, bhi)
        self._emit(U.XOR, G1, ahi, bhi)
        self._emit(U.SLTU, G1, ZERO, G1)        # hi_neq
        self._emit(U.ADDI, hi(G1), ZERO, ZERO, 1)
        self._emit(U.SUB, G1, hi(G1), G1)       # hi_eq
        self._emit(U.SLTU, hi(G0), alo, blo)    # lo_lt
        self._emit(U.AND, G1, G1, hi(G0))
        self._emit(U.OR, dst, dst, G1)

    # -- address hi-guards -------------------------------------------------

    def _guard_regs(self, op: Operand) -> list[int]:
        return [x for x in (op.base, op.index)
                if isinstance(x, int) and 0 <= x < N_GPR]

    def _emit_guard(self, base_reg: int, regs: list[int]) -> int:
        """Poison the effective address when any contributing register's
        hi lane deviates from its captured golden value → the VA crash
        model traps, matching the silicon segfault for hi-bit pointer
        corruption.  Returns the guarded address register (G0)."""
        first = True
        for r in regs:
            ghi = int(self.reg[hi(r)])
            if first:
                self._emit(U.XORI, G0, hi(r), ZERO, ghi)
                first = False
            else:
                self._emit(U.XORI, G1, hi(r), ZERO, ghi)
                self._emit(U.OR, G0, G0, G1)
        self._emit(U.SLTU, G0, ZERO, G0)        # any deviation → 1
        self._emit(U.ADDI, G1, ZERO, ZERO, 30)
        self._emit(U.SLL, G0, G0, G1)           # 0 or 2^30 poison
        self._emit(U.ADD, G0, base_reg, G0)
        return G0

    def _addr_uops(self, op: Operand, pc: int, treg: int):
        r = super()._addr_uops(op, pc, treg)
        if r is None:
            return None
        base_reg, disp = r
        regs = self._guard_regs(op)
        if op.rip_rel or not regs:
            return r
        return self._emit_guard(base_reg, regs), disp

    def _subword_addr(self, op: Operand, pc: int, regs: np.ndarray,
                      width: int):
        r = super()._subword_addr(op, pc, regs, width)
        if r is None:
            return None
        word_r, sh_r = r
        gregs = self._guard_regs(op)
        if op.rip_rel or not gregs:
            return r
        return self._emit_guard(word_r, gregs), sh_r

    # -- stack helpers (2-word slots, rsp hi-guarded) ----------------------

    def _rsp_addr(self) -> int:
        """Guarded stack address register for the current rsp."""
        return self._emit_guard(4, [4])

    # -- the 64-bit handler layer ------------------------------------------

    # -- EVEX chain: pair-lane kmovq + 64-bit tzcnt ------------------------
    def _lift_vec_chain(self, m, ops, pc, regs):
        if m == "kmovq" and len(ops) == 2 and ops[0].kind == "kreg" \
                and ops[1].kind == "reg" and ops[1].reg >= 0:
            _, kmask = self._vec_state()
            st = kmask.get(ops[0].reg)
            dst = ops[1].reg
            if isinstance(st, self._KConcat):
                if not (self._kmask_live(st.lo, dst, regs)
                        and self._kmask_live(st.hi, dst, regs)):
                    return False
                return (self._materialize_kmask(st.lo, dst, regs)
                        and self._materialize_kmask(st.hi, hi(dst), regs))
            if isinstance(st, self._KMask) and st.width <= 32 \
                    and self._kmask_live(st, dst, regs):
                if not self._materialize_kmask(st, dst, regs):
                    return False
                self._emit(U.LUI, hi(dst), ZERO, ZERO, 0)
                return True
            return False
        if m == "tzcnt" and len(ops) == 2 \
                and all(o.kind == "reg" and o.reg >= 0
                        and abs(o.width) == 64 for o in ops):
            src, dst = ops[0].reg, ops[1].reg
            # ctz64 = ctz32(lo) + (lo==0 ? ctz32(hi) : 0) — ctz32 already
            # returns 32 for a zero input, so the sum is 64 for src==0
            self._emit_ctz32(src, T0)
            self._emit_ctz32(hi(src), T1)
            self._emit(U.ADDI, T2, ZERO, ZERO, 5)
            self._emit(U.SRL, T2, T0, T2)            # 1 iff ctz_lo == 32
            self._emit(U.ANDI, T2, T2, ZERO, 1)
            self._emit(U.SUB, T3, ZERO, T2)          # 0 or all-ones
            self._emit(U.AND, T3, T1, T3)
            self._emit(U.ADD, dst, T0, T3)
            self._emit(U.LUI, hi(dst), ZERO, ZERO, 0)
            self.flags_src = ("res64", dst)
            return True
        return super()._lift_vec_chain(m, ops, pc, regs)

    # -- string-op primitives: pair-lane widening + hi-guards --------------
    def _inc_strreg(self, r: int, v: int) -> None:
        self._addi64(r, r, v)

    def _str_copy_word(self, sdelta: int, ddelta: int, w: int) -> None:
        s = self._emit_guard(self._RSI, [self._RSI])
        self._emit(U.LOAD, T6, s, ZERO, sdelta)
        if w == 8:
            self._emit(U.LOAD, T7, s, ZERO, (sdelta + 4) & M32)
        d = self._emit_guard(self._RDI, [self._RDI])
        self._emit(U.STORE, 0, d, T6, ddelta)
        if w == 8:
            self._emit(U.STORE, 0, d, T7, (ddelta + 4) & M32)

    def _str_store_reg(self, reg: int, ddelta: int, w: int,
                       hi_imm: int = 0) -> None:
        # hi_imm unused: the pair-lane datapath has the live hi lane
        d = self._emit_guard(self._RDI, [self._RDI])
        self._emit(U.STORE, 0, d, reg, ddelta)
        if w == 8:
            self._emit(U.STORE, 0, d, hi(reg), (ddelta + 4) & M32)

    def _lift_one(self, i: int, inst: Inst, regs: np.ndarray,
                  next_regs: np.ndarray, next_pc: int) -> bool:
        if self._lift_one64(i, inst, regs, next_pc):
            return True
        # 64-kind flags must never reach the 32-bit flag consumers — the
        # tuple shapes coincide and they would silently compute on lo
        # lanes; demote instead (fail-closed)
        m0 = inst.mnemonic.split()[0]
        m0 = {"jz": "je", "jnz": "jne"}.get(m0, m0)
        if self.flags_src is not None \
                and self.flags_src[0] in ("cmp64", "res64") \
                and (m0 in _JCC_SIGNED or m0 in _JCC_UNSIGNED
                     or m0.startswith(("set", "cmov"))):
            return False
        # 64-bit-WIDTH flag producers must never delegate either: the base
        # handlers would compare/test lo lanes only, and the golden-
        # consistent result would hide hi-lane fault propagation — the
        # exact coordinates device64 mode exists to cover
        if m0.startswith(("cmp", "test")) \
                and self._w64(m0, inst, inst.operands):
            return False
        if not super()._lift_one(i, inst, regs, next_regs, next_pc):
            return False
        self._fix_hi_lanes(inst, m0)
        return True

    # implicit 32-bit destinations of delegated handlers: one-operand
    # mul/div write edx:eax; cdq/cltd write edx — all with hi-zeroing
    _IMPLICIT_HI_ZERO = {"cdq": (2,), "cltd": (2,)}

    def _fix_hi_lanes(self, inst: Inst, m: str) -> None:
        """Architectural hi-zero for delegated 32-bit handlers: every
        32-bit register write clears bits 63:32 regardless of data."""
        ops = inst.operands
        if m in ("div", "idiv", "mul", "imul") and len(ops) == 1:
            if ops[0].kind == "reg" and abs(ops[0].width) == 32:
                self._emit(U.LUI, hi(0), ZERO, ZERO, 0)   # eax
                self._emit(U.LUI, hi(2), ZERO, ZERO, 0)   # edx
            return
        if m in self._IMPLICIT_HI_ZERO:
            for r in self._IMPLICIT_HI_ZERO[m]:
                self._emit(U.LUI, hi(r), ZERO, ZERO, 0)
            return
        if m.startswith(self._NO_DEST) or not ops:
            return
        dst = ops[-1]
        if dst.kind == "reg" and dst.reg >= 0 and dst.width == 32:
            self._emit(U.LUI, hi(dst.reg), ZERO, ZERO, 0)
        if m.startswith("xchg"):
            o0 = ops[0]
            if o0.kind == "reg" and o0.reg >= 0 and o0.width == 32:
                self._emit(U.LUI, hi(o0.reg), ZERO, ZERO, 0)

    def _is64(self, o: Operand) -> bool:
        return o.kind == "reg" and o.reg >= 0 and abs(o.width) == 64

    def _w64(self, m: str, inst: Inst, ops: list) -> bool:
        """True when the operation's width is 64 bits: q suffix, a 64-bit
        register operand, or an 8-byte memory operand."""
        if m.endswith("q"):
            return True
        if any(self._is64(o) for o in ops):
            return True
        return any(o.kind == "mem" and self._mem_width(inst, o) == 8
                   for o in ops)

    def _lift_one64(self, i: int, inst: Inst, regs: np.ndarray,
                    next_pc: int) -> bool:
        m = inst.mnemonic
        ops = inst.operands
        pc = int(regs[16])
        mark = len(self.opcode)
        try:
            done = self._dispatch64(m, ops, pc, inst, next_pc)
        except Exception:  # noqa: BLE001 — any surprise demotes, fail-closed
            self._rollback(mark)
            return False
        if not done:
            self._rollback(mark)
        return done

    def _dispatch64(self, m: str, ops: list, pc: int, inst: Inst,
                    next_pc: int) -> bool:
        m = {"jz": "je", "jnz": "jne"}.get(m, m)
        # --- moves -------------------------------------------------------
        if m in ("mov", "movq", "movabs", "movabsq") and len(ops) == 2:
            src, dst = ops

            def imm64(v: int) -> int:
                if m in ("movabs", "movabsq"):
                    return v & M64              # full 64-bit immediate
                return _sx32(v)

            if self._is64(dst):
                if src.kind == "imm":
                    self._const64(imm64(src.imm), dst.reg)
                    return True
                if self._is64(src):
                    self._mov64(dst.reg, src.reg)
                    return True
                if src.kind == "mem":
                    a = self._addr_uops(src, pc, T0)
                    if a is None:
                        return False
                    self._emit(U.LOAD, dst.reg, a[0], ZERO, a[1])
                    self._emit(U.LOAD, hi(dst.reg), a[0], ZERO,
                               (a[1] + 4) & M32)
                    return True
                return False
            if dst.kind == "mem" and (self._is64(src)
                                      or (src.kind == "imm"
                                          and m in ("movq",))):
                a = self._addr_uops(dst, pc, T0)
                if a is None:
                    return False
                if src.kind == "imm":
                    self._const64(imm64(src.imm), T1)
                    sreg = T1
                else:
                    sreg = src.reg
                self._emit(U.STORE, 0, a[0], sreg, a[1])
                self._emit(U.STORE, 0, a[0], hi(sreg), (a[1] + 4) & M32)
                return True
            return False
        if m in ("movslq", "movsxd") and len(ops) == 2:
            src, dst = ops
            if not self._is64(dst):
                return False
            if src.kind == "reg" and src.reg >= 0:
                self._emit(U.ADD, dst.reg, src.reg, ZERO)
            elif src.kind == "mem":
                a = self._addr_uops(src, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, dst.reg, a[0], ZERO, a[1])
            else:
                return False
            self._emit(U.ADDI, T7, ZERO, ZERO, 31)
            self._emit(U.SRA, hi(dst.reg), dst.reg, T7)
            return True
        # --- lea (64-bit address arithmetic into a register) -------------
        if m in ("lea", "leaq") and len(ops) == 2:
            src, dst = ops
            if not self._is64(dst) or src.kind != "mem" or src.seg:
                return False
            if src.rip_rel:
                self._const64(src.disp & M64, dst.reg)
                return True
            if src.base < 0 and src.index < 0:
                self._const64(src.disp & M64, dst.reg)
                return True
            parts = []
            if src.index >= 0:
                if src.scale > 1:
                    self._shl64_imm(T2, src.index,
                                    src.scale.bit_length() - 1)
                else:
                    self._mov64(T2, src.index)
                parts.append(T2)
            if src.base >= 0:
                if parts:
                    self._add64(T2, T2, src.base)
                else:
                    self._mov64(T2, src.base)
            self._addi64(dst.reg, T2 if (src.base >= 0 or parts)
                         else ZERO, src.disp)
            return True
        # --- 64-bit ALU ---------------------------------------------------
        alu64 = {"add": "add", "addq": "add", "sub": "sub", "subq": "sub",
                 "and": "and", "andq": "and", "or": "or", "orq": "or",
                 "xor": "xor", "xorq": "xor"}
        if m in alu64 and len(ops) == 2:
            src, dst = ops
            if not self._is64(dst):
                return False
            kind = alu64[m]
            if src.kind == "imm":
                sreg = self._const64(_sx32(src.imm), T1)
            elif self._is64(src):
                sreg = src.reg
            elif src.kind == "mem":
                a = self._addr_uops(src, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T1, a[0], ZERO, a[1])
                self._emit(U.LOAD, hi(T1), a[0], ZERO, (a[1] + 4) & M32)
                sreg = T1
            else:
                return False
            if kind == "add":
                self._add64(dst.reg, dst.reg, sreg)
            elif kind == "sub":
                self._sub64(dst.reg, dst.reg, sreg)
            else:
                opmap = {"and": U.AND, "or": U.OR, "xor": U.XOR}
                self._logic64(opmap[kind], dst.reg, dst.reg, sreg)
            self.flags_src = ("res64", dst.reg)
            return True
        if m in ("inc", "incq", "dec", "decq") and len(ops) == 1 \
                and self._is64(ops[0]):
            d = ops[0].reg
            self._addi64(d, d, 1 if m.startswith("inc") else M64)
            self.flags_src = ("res64", d)       # CF unchanged; ZF/SF ok
            return True
        if m in ("neg", "negq") and len(ops) == 1 and self._is64(ops[0]):
            d = ops[0].reg
            self._emit(U.SLTU, hi(T7), ZERO, d)  # borrow from 0 - lo
            self._emit(U.SUB, d, ZERO, d)
            self._emit(U.SUB, hi(d), ZERO, hi(d))
            self._emit(U.SUB, hi(d), hi(d), hi(T7))
            self.flags_src = ("res64", d)
            return True
        if m in ("not", "notq") and len(ops) == 1 and self._is64(ops[0]):
            d = ops[0].reg
            self._emit(U.XORI, d, d, ZERO, M32)
            self._emit(U.XORI, hi(d), hi(d), ZERO, M32)
            return True
        # --- shifts by immediate -----------------------------------------
        if m in ("shl", "shlq", "sal", "salq", "shr", "shrq",
                 "sar", "sarq") and len(ops) in (1, 2):
            dst = ops[-1]
            if not self._is64(dst):
                return False
            if len(ops) == 2:
                if ops[0].kind != "imm":
                    return False                # variable count: demote
                c = ops[0].imm & 63
            else:
                c = 1
            if m.startswith(("shl", "sal")):
                self._shl64_imm(dst.reg, dst.reg, c)
            else:
                self._shr64_imm(dst.reg, dst.reg, c,
                                arith=m.startswith("sar"))
            self.flags_src = ("res64", dst.reg)
            return True
        # --- compares / tests --------------------------------------------
        if m in ("cmp", "cmpq") and len(ops) == 2 \
                and self._w64(m, inst, ops):
            src, dst = ops
            if src.kind == "imm":
                b = self._const64(_sx32(src.imm), TCMP)
            elif self._is64(src):
                b = src.reg
            else:
                return False
            if self._is64(dst):
                a = dst.reg
            elif dst.kind == "mem":
                aa = self._addr_uops(dst, pc, T0)
                if aa is None:
                    return False
                self._emit(U.LOAD, T2, aa[0], ZERO, aa[1])
                self._emit(U.LOAD, hi(T2), aa[0], ZERO, (aa[1] + 4) & M32)
                a = T2
            else:
                return False
            self.flags_src = ("cmp64", a, b)
            return True
        if m in ("test", "testq") and len(ops) == 2 \
                and self._w64(m, inst, ops):
            if self._is64(ops[0]) and self._is64(ops[1]):
                a, b = ops[0].reg, ops[1].reg
                if a == b:
                    self.flags_src = ("res64", a)
                    return True
            elif ops[0].kind == "imm" and self._is64(ops[1]):
                a = self._const64(_sx32(ops[0].imm), TCMP)
                b = ops[1].reg
            else:
                return False
            self._emit(U.AND, T2, a, b)
            self._emit(U.AND, hi(T2), hi(a), hi(b))
            self.flags_src = ("res64", T2)
            return True
        # --- jcc consuming 64-bit flags ----------------------------------
        if (m in _JCC_SIGNED or m in _JCC_UNSIGNED) \
                and self.flags_src is not None \
                and self.flags_src[0] in ("cmp64", "res64"):
            self.stats.branches += 1
            taken = 1 if next_pc != (pc + inst.length) else 0
            ok = self._jcc64(m, taken)
            if ok:
                self.stats.branches_lifted += 1
            else:
                self.stats.branches_dropped += 1
            return ok
        # --- stack -------------------------------------------------------
        if m in ("push", "pushq") and len(ops) == 1 and self._is64(ops[0]):
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            s = ops[0].reg
            self._emit(U.ADDI, 4, 4, ZERO, (-8) & M32)
            areg = self._rsp_addr()
            self._emit(U.STORE, 0, areg, s, delta)
            self._emit(U.STORE, 0, areg, hi(s), (delta + 4) & M32)
            return True
        if m in ("pop", "popq") and len(ops) == 1 and self._is64(ops[0]):
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            d = ops[0].reg
            areg = self._rsp_addr()
            self._emit(U.LOAD, d, areg, ZERO, delta)
            self._emit(U.LOAD, hi(d), areg, ZERO, (delta + 4) & M32)
            self._emit(U.ADDI, 4, 4, ZERO, 8)
            return True
        if m in ("call", "callq"):
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            ra = (pc + inst.length) & M64
            self._const64(ra, T1)
            self._emit(U.ADDI, 4, 4, ZERO, (-8) & M32)
            areg = self._rsp_addr()
            self._emit(U.STORE, 0, areg, T1, delta)
            self._emit(U.STORE, 0, areg, hi(T1), (delta + 4) & M32)
            return True
        if m in ("ret", "retq"):
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            addr = (int(self.reg[4]) + delta) & M32
            if (addr & 3) or (addr >> 2) >= self.mem_words or \
                    int(self.mem[addr >> 2]) != (next_pc & M32):
                return False
            areg = self._rsp_addr()
            self._emit(U.LOAD, T1, areg, ZERO, delta)
            self._emit(U.LOAD, hi(T1), areg, ZERO, (delta + 4) & M32)
            self._emit(U.ADDI, 4, 4, ZERO, 8)
            # full-width return-address integrity: lo must equal the
            # captured target, hi must be zero (static text < 4 GiB)
            self._emit(U.LUI, T2, ZERO, ZERO, next_pc & M32)
            self._emit(U.XOR, T2, T1, T2)
            self._emit(U.OR, T2, T2, hi(T1))
            self._emit(U.BEQ, 0, T2, ZERO, taken=1)
            return True
        return False

    def _jcc64(self, m: str, taken: int) -> bool:
        kind = self.flags_src[0]
        mark = len(self.opcode)
        if kind == "cmp64":
            _, a, b = self.flags_src
            alo, ahi, blo, bhi = a, hi(a), b, hi(b)
        else:                                   # res64: flags of r vs 0
            r = self.flags_src[1]
            alo, ahi, blo, bhi = r, hi(r), ZERO, ZERO
        sense = None
        if m in ("je", "jz", "jne", "jnz"):
            self._emit(U.XOR, T3, alo, blo)
            self._emit(U.XOR, hi(T3), ahi, bhi)
            self._emit(U.OR, T3, T3, hi(T3))
            sense = m in ("jne", "jnz")         # True: taken ⟺ T3 != 0
        elif m in ("js", "jns"):
            if kind != "res64":
                self._rollback(mark)
                return False
            self._emit(U.ADDI, T3, ZERO, ZERO, 31)
            self._emit(U.SRL, T3, ahi, T3)      # sign bit of the result
            sense = m == "js"
        elif m in _JCC_UNSIGNED:
            mode = _JCC_UNSIGNED[m]
            if mode in (False, True):           # jb/jnae (F) · jae/jnb (T)
                self._ltu64(T3, alo, ahi, blo, bhi, signed=False)
                sense = mode is False           # jb taken ⟺ a < b
            else:                               # ja ("swap_b") · jbe
                self._ltu64(T3, blo, bhi, alo, ahi, signed=False)
                sense = mode == "swap_b"        # ja taken ⟺ b < a
        elif m in _JCC_SIGNED:
            cond = _JCC_SIGNED[m][0]
            if cond in ("lt", "ge"):            # jl · jge: a <s b
                self._ltu64(T3, alo, ahi, blo, bhi, signed=True)
                sense = cond == "lt"
            elif cond in ("swap_lt", "swap_ge"):  # jg · jle: b <s a
                self._ltu64(T3, blo, bhi, alo, ahi, signed=True)
                sense = cond == "swap_lt"
            else:
                self._rollback(mark)
                return False
        else:
            self._rollback(mark)
            return False
        golden = int(self.reg[T3])
        cond_now = (golden != 0) if sense else (golden == 0)
        if int(cond_now) != taken:
            self._rollback(mark)
            return False
        self._emit(U.BNE if sense else U.BEQ, 0, T3, ZERO, taken=taken)
        return True


def lift64(trace_path: str, binary: str, max_uops: int | None = None,
           nt: NativeTrace | None = None,
           insts: "dict[int, Inst] | None" = None):
    """nativetrace capture + binary → (Trace, metadata), 64-bit pair-lane
    datapath (nphys=64; REGFILE coordinate (reg, bit<64) ↦ phys
    (reg + 32·(bit≥32), bit mod 32))."""
    if nt is None:
        nt = read_nativetrace(trace_path)
    if insts is None:
        insts = static_decode(binary)
    try:
        from shrewd_tpu.ingest.emu import elf_regions
        elf_regs = elf_regions(binary)
    except Exception:  # noqa: BLE001
        elf_regs = []
    trace, meta = Lifter64(nt, insts, max_uops=max_uops,
                           elf_regs=elf_regs).run()
    meta["width"] = 64
    return trace, meta
