"""``stats.txt`` reader.

Parses the reference's text stat dumps (``src/base/stats/text.cc`` layout:
``name  value  # desc`` rows between ``Begin``/``End`` marker lines; one
block per dump/reset epoch) into a list of ``{name: value}`` dicts — the
ingestion side of the golden-diff test pattern (MatchStdout analog,
``tests/gem5/verifier.py:158``). Reads this framework's own
``stats.dump_text`` output too (same layout by construction).
"""

from __future__ import annotations

_BEGIN = "---------- Begin Simulation Statistics ----------"
_END = "---------- End Simulation Statistics   ----------"


def _parse_value(tok: str) -> float | str:
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok  # e.g. 'nan' parses above; symbolic values stay strings


def load_stats_txt(path_or_file) -> list[dict[str, float]]:
    """All dump blocks in file order. A file with no Begin markers is read
    as a single block (some tools strip them)."""
    if hasattr(path_or_file, "read"):
        lines = path_or_file.read().splitlines()
    else:
        with open(path_or_file) as f:
            lines = f.read().splitlines()

    blocks: list[dict[str, float]] = []
    current: dict[str, float] | None = None
    saw_marker = False
    for line in lines:
        stripped = line.strip()
        if stripped.startswith(_BEGIN):
            saw_marker = True
            current = {}
            blocks.append(current)
            continue
        if stripped.startswith(_END):
            current = None
            continue
        if not stripped:
            continue
        if current is None:
            if saw_marker:
                continue  # prose between blocks
            current = {}
            blocks.append(current)
        payload = stripped.split("#", 1)[0].strip()  # drop desc comment
        if not payload:
            continue
        parts = payload.split()
        if len(parts) < 2:
            continue  # tolerate prose lines (simulation banners)
        name, raw = parts[0], parts[1]
        current[name] = _parse_value(raw)
    return [b for b in blocks if b]


def diff_stats(a: dict[str, float], b: dict[str, float],
               rel_tol: float = 0.0,
               ignore: tuple[str, ...] = ()) -> list[str]:
    """Names whose values differ beyond rel_tol, plus one-sided keys —
    the MatchStdoutNoPerf-style masked comparison
    (reference ``tests/gem5/verifier.py:181``)."""
    bad: list[str] = []
    keys = set(a) | set(b)
    for k in sorted(keys):
        if any(k.startswith(p) for p in ignore):
            continue
        if k not in a or k not in b:
            bad.append(k)
            continue
        va, vb = a[k], b[k]
        if isinstance(va, str) or isinstance(vb, str):
            if str(va) != str(vb):
                bad.append(k)
            continue
        a_nan = isinstance(va, float) and va != va
        b_nan = isinstance(vb, float) and vb != vb
        if a_nan or b_nan:
            if a_nan != b_nan:   # nan on one side only is always a diff
                bad.append(k)
            continue
        if va != vb:
            denom = max(abs(va), abs(vb))
            if denom == 0 or abs(va - vb) / denom > rel_tol:
                bad.append(k)
    return bad
