"""Differential AVF: TPU replay kernel vs the real host CPU (hostsfi).

Closes the round-1 self-referentiality gap (VERDICT missing #2): the TPU
campaign's outcomes are compared trial-by-trial against a ground truth that
shares **no code** with the framework — the host x86 CPU itself, perturbed
through ptrace exactly the way the reference's SFI campaigns perturb a
simulated core through ``ThreadContext::setReg``
(``src/cpu/thread_context.hh:190-207``) and classified by program output
like the reference's golden-stdout verifiers (``tests/gem5/verifier.py``
MatchStdout).

Pairing: the SAME (step, reg, bit) coordinates drive both sides.  The host
flips bit *b* of GPR *r* after *step* dynamic instructions inside the
window; the TPU kernel injects KIND_REGFILE at cycle ``uop_start[step]``,
entry *r*, bit *b* on the lifted trace (ingest/lift.py maps macro steps to
µop indices).

Classification-scope caveat (inherent to windowed SFI): the host classifies
at *program end*, the replay kernel at *window end*.  The replay side
therefore compares memory plus the ABI live-out registers only — rsp, rbp,
rbx, r12–r15 are the registers the post-window code may legally read
(callee-saved, SysV ABI); caller-saved registers are dead at the
kernel_end call boundary, so their window-end corruption must not count.
Agreement is reported both per-class and binarized (vulnerable vs masked),
with Wilson CIs on both AVFs.
"""

from __future__ import annotations

import json
import os
import subprocess
from pathlib import Path
from typing import NamedTuple

import numpy as np

REPO = Path(__file__).resolve().parent.parent.parent

# SysV AMD64 callee-saved registers (+ the stack pointer) in the canonical
# encoding order of tools/ptrace_common.h — the ABI-live-out comparison set.
LIVE_OUT_REGS = (3, 4, 5, 12, 13, 14, 15)     # rbx, rsp, rbp, r12..r15

HOST_OUTCOME = {"masked": 0, "sdc": 1, "due": 2}


class BuildPaths(NamedTuple):
    workload: Path
    tracer: Path
    hostsfi: Path
    begin: int
    end: int


def _build(out: Path, cmd: list[str]) -> None:
    """mtime-idempotent compile: skip when the output is newer than every
    source in the command line."""
    src_mtimes = [Path(c).stat().st_mtime for c in cmd if
                  c.endswith((".c", ".cc"))]
    if out.exists() and all(out.stat().st_mtime >= m for m in src_mtimes):
        return
    subprocess.run(cmd + ["-o", str(out)], check=True,
                   capture_output=True, text=True)


def build_tracer(build_dir: Path | None = None) -> Path:
    """Compile the ptrace capture tool alone (idempotent) — the entry
    the ingest pipeline uses for SUBMITTED binaries, which arrive as ELF
    bytes with no workload source to build."""
    bd = build_dir or (REPO / "tests" / "_build")
    bd.mkdir(parents=True, exist_ok=True)
    tracer = bd / "nativetrace"
    _build(tracer, ["g++", "-O2", "-std=c++17",
                    str(REPO / "tools" / "nativetrace.cc")])
    return tracer


def elf_markers(binary) -> tuple[int, int]:
    """``(kernel_begin, kernel_end)`` marker addresses via ``nm``.

    Raises ``ValueError`` when the file is not a parseable ELF or lacks
    the marker symbols — the ingest pipeline's unparseable-submission
    quarantine trigger, kept loud and typed so the capture stage can
    tell poison (quarantine) from environment trouble (retry)."""
    try:
        nm = subprocess.run(["nm", str(binary)], check=True,
                            capture_output=True, text=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise ValueError(
            f"{binary}: not a parseable ELF ({detail.strip()})")
    syms = {p[2]: int(p[0], 16) for p in
            (ln.split() for ln in nm.splitlines()) if len(p) == 3}
    if "kernel_begin" not in syms or "kernel_end" not in syms:
        raise ValueError(
            f"{binary}: no kernel_begin/kernel_end marker symbols")
    return syms["kernel_begin"], syms["kernel_end"]


def build_tools(workload_c: str = "workloads/sort.c",
                build_dir: Path | None = None) -> BuildPaths:
    """Compile the guest workload and both ptrace tools (idempotent)."""
    bd = build_dir or (REPO / "tests" / "_build")
    bd.mkdir(parents=True, exist_ok=True)
    wl_src = REPO / workload_c
    wl = bd / wl_src.stem
    sfi = bd / "hostsfi"
    _build(wl, ["gcc", "-O1", "-static", "-fno-pie", "-no-pie", str(wl_src)])
    tracer = build_tracer(bd)
    _build(sfi, ["g++", "-O2", "-std=c++17",
                 str(REPO / "tools" / "hostsfi.cc")])
    begin, end = elf_markers(wl)
    return BuildPaths(wl, tracer, sfi, begin, end)


def _capture(paths: BuildPaths, suffix: str, consume,
             build_dir: Path | None = None, max_steps: int = 2_000_000):
    """Run the ptrace capture tool into a temp file and hand the file to
    ``consume`` (deleted afterwards) — the one place that knows the tracer
    CLI contract."""
    bd = build_dir or (REPO / "tests" / "_build")
    trace_bin = bd / f"{paths.workload.name}_{suffix}.{os.getpid()}.bin"
    try:
        subprocess.run([str(paths.tracer), str(trace_bin),
                        f"{paths.begin:x}", f"{paths.end:x}",
                        str(max_steps), str(paths.workload)],
                       check=True, capture_output=True, text=True)
        return consume(trace_bin)
    finally:
        trace_bin.unlink(missing_ok=True)


def capture_and_lift(paths: BuildPaths, build_dir: Path | None = None,
                     max_steps: int = 2_000_000):
    from shrewd_tpu.ingest.lift import lift
    return _capture(paths, "trace",
                    lambda p: lift(str(p), str(paths.workload)),
                    build_dir, max_steps)


def capture_window_macro_ops(paths: BuildPaths,
                             build_dir: Path | None = None,
                             max_steps: int = 2_000_000) -> int:
    """Marker-to-marker macro-op count from a raw capture — no lift.

    The emu64 mode replays the raw capture itself, so paying the full
    operand-parse + dataflow-lift + self-check pass of ``lift()`` just to
    learn the window length wasted the dominant share of its setup time."""
    from shrewd_tpu.ingest.lift import read_nativetrace

    def count(p):
        # the trailing record is state-at-end, not an executed step (the
        # same convention lift() uses: n_macro = len(steps) - 1)
        return max(len(read_nativetrace(p).steps) - 1, 0)

    return _capture(paths, "win", count, build_dir, max_steps)


def capture_and_lift_to_output(paths: BuildPaths,
                               build_dir: Path | None = None,
                               max_steps: int = 2_000_000,
                               lifter=None):
    """Capture and lift the *extended* window: kernel_begin → process exit.

    The replay then runs through the workload's own output stage (checksum
    + write syscall + exit), so classification can compare exactly the
    program-visible bytes — the same quantity the host oracle hashes from
    stdout (tools/hostsfi.cc; reference: MatchStdout,
    /root/reference/tests/gem5/verifier.py:158).  Adds to meta:

    - ``window_macro_ops``: macro steps inside [kernel_begin, kernel_end)
      — the fault-injection window (hostsfi injects only there);
    - ``output_words``: replay-memory word indices covering every byte the
      program passes to write(2) on stdout, at syscall time;
    - ``output_syscalls``: count of stdout writes found.
    """
    from shrewd_tpu.ingest.lift import (M32, lift, read_nativetrace,
                                        static_decode)
    bd = build_dir or (REPO / "tests" / "_build")
    trace_bin = bd / f"{paths.workload.name}_full.{os.getpid()}.bin"
    try:
        proc = subprocess.run(
            [str(paths.tracer), str(trace_bin), f"{paths.begin:x}", "0",
             str(max_steps), str(paths.workload)],
            capture_output=True, text=True)
        # rc 1 ("child exited mid-window") is the clean outcome with end=0
        if proc.returncode not in (0, 1) or not trace_bin.exists():
            raise RuntimeError(f"full capture failed: {proc.stderr}")
        nt = read_nativetrace(trace_bin)
        insts = static_decode(str(paths.workload))
        trace, meta = (lifter or lift)(str(trace_bin), str(paths.workload),
                                       nt=nt, insts=insts)
    finally:
        trace_bin.unlink(missing_ok=True)
    # executed steps only — the trailing record is state-at-end, not a step
    steps = nt.steps[:-1] if len(nt.steps) else nt.steps
    ends = np.nonzero(steps[:, 16] == np.uint64(paths.end))[0]
    if len(ends) == 0:
        raise RuntimeError("kernel_end marker never reached in full capture")
    window_end = int(ends[0])
    out_events = []                      # (macro_step, rsi, rdx)
    cand = np.nonzero((steps[:, 0] == 1) & (steps[:, 7] == 1))[0]
    for i in cand:
        inst = insts.get(int(steps[i][16]))
        if inst is not None and inst.mnemonic == "syscall":
            out_events.append((int(i), int(steps[i][6]), int(steps[i][2])))

    def words_of(a: int, ln: int) -> dict:
        """Replay word index → byte mask for the written range [a, a+ln).
        Byte-granular: an unaligned head/tail must not drag the dead bytes
        sharing its word into the comparison.  Raises on bytes outside
        every replay cluster — dropping them would silently under-report
        SDC on exactly the bytes the host oracle hashes."""
        masks: dict[int, int] = {}
        for b in range(a, a + ln):
            b32 = b & M32
            waddr = b32 & ~0x3
            for lo, hi, word_off in meta["clusters"]:
                if lo <= waddr < hi:
                    w = word_off + (waddr - lo) // 4
                    masks[w] = masks.get(w, 0) | (0xFF << (8 * (b32 & 3)))
                    break
            else:
                raise RuntimeError(
                    f"output byte {b32:#x} not in any replay cluster — the "
                    "write(2) buffer was never touched by a lifted store")
        return masks

    # Each output event is compared AT ITS SYSCALL µOP, not at window end:
    # the exit path reuses the stack frames that held the output buffer, so
    # the bytes at trace end are unrelated to what the kernel wrote out
    # (pushes of fault-corrupted callee-saved registers were landing on the
    # dead buffer and reading back as false SDC).
    uop_start = meta["uop_start"]
    meta["output_events"] = []
    for m, a, ln in out_events:
        masks = words_of(a, ln)
        ws = sorted(masks)
        meta["output_events"].append(
            {"macro": m, "cut_uop": int(uop_start[m]), "words": ws,
             "byte_masks": [masks[w] for w in ws]})
    meta["window_macro_ops"] = window_end
    meta["output_words"] = sorted(
        {w for ev in meta["output_events"] for w in ev["words"]})
    meta["output_syscalls"] = len(out_events)
    return trace, meta


def sample_coords(n_trials: int, window: int, seed: int = 0,
                  bit_range: int = 32, n_regs: int = 16) -> np.ndarray:
    """(step, reg, bit) samples.  ``bit_range=32`` restricts to the low
    half (the TPU replay's 32-bit projection); ``bit_range=64`` samples
    the full register, for the emu64 whole-program re-execution path."""
    rng = np.random.default_rng(seed)
    return np.stack([
        rng.integers(0, window, n_trials),
        rng.integers(0, n_regs, n_trials),
        rng.integers(0, bit_range, n_trials),
    ], axis=1).astype(np.int64)


def run_host(paths: BuildPaths, coords: np.ndarray,
             build_dir: Path | None = None) -> np.ndarray:
    """hostsfi over the coordinate list → outcome classes int32[n].

    Coordinate/result files are run-scoped (pid-suffixed): two concurrent
    campaigns sharing a build dir must not truncate each other's open
    results stream."""
    bd = build_dir or (REPO / "tests" / "_build")
    cpath = bd / f"coords.{os.getpid()}.txt"
    rpath = bd / f"host_results.{os.getpid()}.jsonl"
    try:
        np.savetxt(cpath, coords, fmt="%d")
        subprocess.run([str(paths.hostsfi), str(cpath), str(rpath),
                        f"{paths.begin:x}", f"{paths.end:x}",
                        str(paths.workload)],
                       check=True, capture_output=True, text=True)
        out = np.full(len(coords), -1, dtype=np.int32)
        with open(rpath) as f:
            for line in f:
                r = json.loads(line)
                out[r["trial"]] = HOST_OUTCOME[r["outcome"]]
        if (out < 0).any():
            raise RuntimeError("missing host trial results")
        return out
    finally:
        for p in (cpath, rpath):
            p.unlink(missing_ok=True)


def memmap_from_meta(meta: dict, cut: int | None = None):
    """ops.replay.MemMap device arrays from lift metadata, or None when
    the lift predates the VA crash model (no mem_cluster/map_regions)."""
    import jax.numpy as jnp

    from shrewd_tpu.ops.replay import MemMap

    mc = np.asarray(meta.get("mem_cluster", []), dtype=np.int32)
    regions = meta.get("map_regions") or []
    clusters = meta.get("clusters") or []
    if mc.size == 0 or not regions or not clusters:
        return None
    if cut is not None:
        mc = mc[:cut]
    cl = np.asarray(clusters, dtype=np.int64)          # (k, 3) lo, hi, off
    ld = [(lo, span) for lo, span, _w in regions]
    st = [(lo, span) for lo, span, w in regions if w] or [(0, 0)]
    ld_lo, ld_span = (np.asarray(x, dtype=np.uint32) for x in zip(*ld))
    st_lo, st_span = (np.asarray(x, dtype=np.uint32) for x in zip(*st))
    return MemMap(
        uop_cluster=jnp.asarray(mc),
        cl_lo=jnp.asarray(cl[:, 0].astype(np.uint32)),
        cl_span=jnp.asarray((cl[:, 1] - cl[:, 0]).astype(np.uint32)),
        cl_word_off=jnp.asarray(cl[:, 2].astype(np.int32)),
        ld_lo=jnp.asarray(ld_lo), ld_span=jnp.asarray(ld_span),
        st_lo=jnp.asarray(st_lo), st_span=jnp.asarray(st_span))


def _coords_to_phys(meta: dict, reg: np.ndarray,
                    bit: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """The ONE coords→phys-register mapping (shared by fault construction
    and the severed test): 64-bit hi lanes at +32, xmm lanes at fp_bank."""
    if int(meta.get("width", 32)) == 64:
        return reg + 32 * (bit >= 32), bit % 32
    if meta.get("fp_bank") is not None:
        fb = int(meta["fp_bank"])
        return np.where(reg >= 16, fb + (reg - 16), reg), bit
    return reg, bit


def _demoted_exposed(trace, meta: dict, coords: np.ndarray) -> np.ndarray:
    """bool[n_coords]: faults in a register some LATER demoted instruction
    READS on silicon, while the fault is still live in the replay.  The
    replay never models a demoted instruction's register consumption —
    e.g. a demoted ymm load through a corrupted base pointer is silicon's
    crash channel but invisible to the replay (the r4 strmix due→masked
    cell) — so those coordinates escalate to the whole-program emulator
    oracle alongside the diverged set.  A replayed WRITE to the faulted
    phys lane before the demoted step kills the fault on both executors
    (the lift models partial-width writes), so such coords stay
    on-device."""
    from shrewd_tpu.isa import uops as U

    dr = meta.get("demoted_reads") or []
    out = np.zeros(len(coords), dtype=bool)
    if not dr:
        return out
    uop_start = np.asarray(meta["uop_start"], dtype=np.int64)
    n = trace.n
    dst = np.asarray(trace.dst)
    opcode = np.asarray(trace.opcode)
    src1 = np.asarray(trace.src1)
    src2 = np.asarray(trace.src2)
    wd = np.asarray(U.writes_dest(opcode))
    u1 = np.asarray(U.uses_src1(opcode))
    u2 = np.asarray(U.uses_src2(opcode))
    step, reg, bit = coords.T
    phys, _ = _coords_to_phys(meta, reg, bit)
    # demoted steps per arch read-reg (sorted by construction)
    by_reg: dict[int, list[int]] = {}
    wild: list[int] = []
    for s, regs in dr:
        for r in regs:
            (wild if r == -1 else by_reg.setdefault(r, [])).append(s)
    merged: dict[int, np.ndarray] = {}      # arch reg → sorted demoted steps
    kills: dict[int, np.ndarray] = {}       # phys → killing-write µop idxs
    for i in range(len(coords)):
        a = int(reg[i])
        if a not in merged:
            merged[a] = np.asarray(sorted(by_reg.get(a, []) + wild),
                                   dtype=np.int64)
        dsteps = merged[a]
        pos = np.searchsorted(dsteps, int(step[i]))
        if pos >= len(dsteps):
            continue
        d_uop = uop_start[min(int(dsteps[pos]), len(uop_start) - 1)]
        # a killing write replaces the whole lane WITHOUT reading it — a
        # read-modify-write (sub-word merge) keeps the fault live in both
        # executors and must not suppress the escalation
        p = int(phys[i])
        if p not in kills:
            kills[p] = np.nonzero((dst == p) & wd
                                  & ~((src1 == p) & u1)
                                  & ~((src2 == p) & u2))[0]
        writes = kills[p]
        w = np.searchsorted(writes, uop_start[step[i]])
        first_write = writes[w] if w < writes.size else n
        out[i] = d_uop <= first_write
    return out


def _resync_severed(trace, meta: dict, coords: np.ndarray) -> np.ndarray:
    """bool[n_coords]: faults whose struck phys register's first touch at
    or after the landing cycle is a demotion-resync LUI — severed in the
    replay (the constant overwrites the flip) but alive on silicon."""
    from shrewd_tpu.isa import uops as U

    resync = meta.get("resync_uops") or []
    if not resync:
        return np.zeros(len(coords), dtype=bool)
    n = trace.n
    opcode = np.asarray(trace.opcode)
    src1 = np.asarray(trace.src1)
    src2 = np.asarray(trace.src2)
    dst = np.asarray(trace.dst)
    u1 = np.asarray(U.uses_src1(opcode))
    u2 = np.asarray(U.uses_src2(opcode))
    wd = np.asarray(U.writes_dest(opcode))
    is_resync = np.zeros(n, dtype=bool)
    is_resync[np.asarray(resync, dtype=np.int64)] = True

    uop_start = np.asarray(meta["uop_start"], dtype=np.int64)
    step, reg, bit = coords.T
    reg, _ = _coords_to_phys(meta, reg, bit)
    out = np.zeros(len(coords), dtype=bool)
    for r in np.unique(reg):
        touch = np.nonzero(((src1 == r) & u1) | ((src2 == r) & u2)
                           | ((dst == r) & wd))[0]
        if touch.size == 0:
            continue
        sel = np.nonzero(reg == r)[0]
        pos = np.searchsorted(touch, uop_start[step[sel]], side="left")
        has = pos < touch.size
        first = touch[np.minimum(pos, touch.size - 1)]
        out[sel] = has & is_resync[first]
    return out


def run_device(trace, meta: dict, coords: np.ndarray,
               liveness=None, paths: BuildPaths | None = None,
               resolve_diverged: bool = True,
               report: dict | None = None) -> np.ndarray:
    """The same trials on the replay kernel → outcome classes int32[n].

    Dense kernel, no shadow detection (the host has no shadow FUs).  With a
    measured ``liveness`` (ingest.liveness.Liveness from the post-window
    capture), comparison is restricted to the registers and memory words the
    post-window code actually reads before writing — the program-visible
    state.  Without one, falls back to the static ABI heuristic
    (callee-saved registers + all memory), which over-reports SDC for state
    that is dead at the output boundary (VERDICT r2 measured 25 points of
    inflation on sort.c from exactly this)."""
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.models.o3 import Fault, KIND_REGFILE, O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel

    k = TrialKernel(trace, O3Config(enable_shrewd=False),
                    memmap=memmap_from_meta(meta))
    uop_start = np.asarray(meta["uop_start"], dtype=np.int64)
    step, reg, bit = coords.T
    # pair-lane hi lanes / FP bank — the same mapping _resync_severed uses
    reg, bit = _coords_to_phys(meta, reg, bit)
    faults = Fault(
        kind=jnp.full(len(coords), KIND_REGFILE, dtype=jnp.int32),
        cycle=jnp.asarray(uop_start[step], dtype=jnp.int32),
        entry=jnp.asarray(reg, dtype=jnp.int32),
        bit=jnp.asarray(bit, dtype=jnp.int32),
        shadow_u=jnp.ones(len(coords), dtype=jnp.float32))

    if "output_events" in meta:
        # Extended-window ("output") mode — exact host-oracle semantics:
        #   SDC  ⇔ the bytes passed to write(2) differ AT SYSCALL TIME
        #          (truncated replay per output event; the exit path reuses
        #          the buffer's stack frames, so window-end state is dead),
        #          or the exit status (low 8 bits of rdi at exit_group)
        #          differs, or control flow diverged (conservative),
        #   DUE  ⇔ the replay trapped anywhere up to process exit.
        # NOTE: one truncated TrialKernel (fresh XLA compile) per output
        # event — fine under the workload contract of a single batched
        # write(2); a printf-per-line workload would recompile per line.
        rfull = jax.jit(jax.vmap(k._replay_one))(faults)
        sdc = np.asarray(rfull.diverged).copy()
        for ev in meta["output_events"]:
            cut = ev["cut_uop"]
            words = np.asarray(ev["words"], dtype=np.int64)
            if len(words) == 0 or cut == 0:
                continue
            tr_cut = trace.__class__(
                opcode=trace.opcode[:cut], dst=trace.dst[:cut],
                src1=trace.src1[:cut], src2=trace.src2[:cut],
                imm=trace.imm[:cut], taken=trace.taken[:cut],
                init_reg=trace.init_reg, init_mem=trace.init_mem)
            k_cut = TrialKernel(tr_cut, O3Config(enable_shrewd=False),
                                memmap=memmap_from_meta(meta, cut=cut))
            rcut = jax.jit(jax.vmap(k_cut._replay_one))(faults)
            gold_w = np.asarray(k_cut.golden.mem)[words]
            bmask = np.asarray(ev["byte_masks"], dtype=np.uint32)
            delta = (np.asarray(rcut.mem)[:, words] ^ gold_w[None, :])
            sdc |= ((delta & bmask[None, :]) != 0).any(1)
        exit_diff = ((np.asarray(rfull.reg)[:, 7]
                      ^ np.asarray(k.golden.reg)[7]) & 0xFF) != 0
        sdc |= exit_diff
        trapped = np.asarray(rfull.trapped)
        detected = np.asarray(rfull.detected)
        out = np.full(len(coords), C.OUTCOME_MASKED, dtype=np.int32)
        out[sdc] = C.OUTCOME_SDC
        out[trapped] = C.OUTCOME_DUE
        out[detected] = C.OUTCOME_DETECTED
        # Diverged-trial escalation: a wrong branch direction leaves the
        # captured window's dataflow entirely — the replay cannot follow
        # the wrong path, and calling every divergence SDC mislabeled
        # 1,100/1,785 host-DUEs in r3 (on silicon the wrong path usually
        # dies on a bad pointer).  Hand exactly those trials to the
        # whole-program emulator oracle, which executes the actual wrong
        # path to its real outcome (segfault → DUE / output diff → SDC /
        # re-convergence → masked).  masked/sdc/due class codes coincide
        # between HOST_OUTCOME and ops.classify.
        # Resync-severed coordinates: the struck register's first touch
        # after the landing cycle is a demotion-resync LUI, so the replay
        # provably drops a corruption silicon keeps — escalate those to
        # the oracle along with the diverged trials (the low-lift-rate
        # workloads' dominant disagreement channel).
        sev = _resync_severed(trace, meta, coords) \
            | _demoted_exposed(trace, meta, coords)
        div_only = np.asarray(rfull.diverged) & ~trapped & ~detected
        # severed/exposed trials escalate even when the replay trapped:
        # when silicon's behavior ran through a demoted instruction the
        # replay's own trap can be spurious (the emulator executes the
        # real path); plain traps stay DUE on-device
        div = (div_only | sev) & ~detected
        if report is not None:
            # device_diverged keeps its r04-artifact meaning (the
            # diverged escalation set); resync_severed counts the trials
            # the severed/exposed tests ADD to it (incl. trapped ones —
            # those escalate too); escalated_total = device_diverged +
            # resync_severed = the oracle's input size, so the buckets
            # reconcile with diverged_resolved
            report["device_diverged"] = int(div_only.sum())
            report["resync_severed"] = int((sev & ~div_only
                                            & ~detected).sum())
            report["escalated_total"] = int(div.sum())
            report["device_memmap"] = k.memmap is not None
        if resolve_diverged and paths is not None and div.any():
            try:
                oracle = Emu64Oracle(paths)
                resolved = oracle.classify(coords[div])
            except Exception as e:  # noqa: BLE001 — a workload the
                # emulator cannot run whole-program must degrade to the
                # conservative diverged→SDC labeling, not lose the report
                if report is not None:
                    report["diverged_resolution_failed"] = \
                        f"{type(e).__name__}: {e}"[:200]
            else:
                out[div] = resolved
                if report is not None:
                    report["diverged_resolved"] = {
                        name: int((resolved == code).sum())
                        for name, code in HOST_OUTCOME.items()}
        return out

    mask = np.zeros(trace.nphys, dtype=bool)
    mem_mask = None
    if liveness is not None:
        mask[:len(liveness.reg_live)] = liveness.reg_live
        mem_mask = jnp.asarray(liveness.mem_word_mask(
            meta["clusters"], trace.mem_words))
    else:
        mask[list(LIVE_OUT_REGS)] = True

    @jax.jit
    def outcomes(faults):
        results = jax.vmap(k._replay_one)(faults)
        return jax.vmap(lambda r: C.classify(
            r, k.golden, compare_regs=True,
            reg_mask=jnp.asarray(mask), mem_mask=mem_mask))(results)

    return np.asarray(outcomes(faults))


class Emu64Oracle:
    """Perturbed whole-program re-execution on the snapshot-seeded 64-bit
    emulator (ingest/emu.py run_program), classified by the host oracle's
    own criteria (stdout + exit status).  Covers the upper register halves
    and real wrong-path execution — the two things the 32-bit window
    replay cannot track.  Built once (snapshot capture + golden run), then
    ``classify`` maps any coordinate subset — the escalation tier the
    replay kernel hands its *diverged* trials to (run_device)."""

    def __init__(self, paths: BuildPaths, max_steps: int = 4_000_000):
        import subprocess

        from shrewd_tpu.ingest.emu import elf_regions, run_program
        from shrewd_tpu.ingest.lift import read_nativetrace, static_decode

        self._run_program = run_program
        self.max_steps = max_steps
        bd = paths.workload.parent
        trace_bin = bd / f"{paths.workload.name}_emu64.{os.getpid()}.bin"
        try:
            proc = subprocess.run(
                [str(paths.tracer), str(trace_bin), f"{paths.begin:x}", "0",
                 "1", str(paths.workload)],     # 1 step: snapshot only
                capture_output=True, text=True)
            if proc.returncode not in (0, 1) or not trace_bin.exists():
                raise RuntimeError(f"snapshot capture failed: {proc.stderr}")
            nt = read_nativetrace(trace_bin)
        finally:
            trace_bin.unlink(missing_ok=True)
        self.insts = static_decode(str(paths.workload))
        self.regs0 = nt.steps[0][:16]
        # snapshot regions first (writable, current values — they win on
        # overlap), then ALL of the binary's segments as fallback:
        # text/rodata plus the RELRO slice the writable-only snapshot
        # cannot see
        self.regions = [(v, d) for v, d in nt.regions]
        self.regions += elf_regions(str(paths.workload))
        self.pc0 = int(nt.steps[0][16])
        self.fs_base = nt.fs_base

        self.golden = run_program(self.insts, self.regs0, self.regions,
                                  self.pc0, max_steps, fs_base=self.fs_base)
        if self.golden.kind != "exit" or self.golden.exit_code != 0:
            raise RuntimeError(f"golden emu run failed: {self.golden.kind}")

    def classify_one(self, step: int, reg: int, bit: int) -> int:
        r = self._run_program(self.insts, self.regs0, self.regions,
                              self.pc0, self.max_steps,
                              fault=(int(step), int(reg), int(bit)),
                              fs_base=self.fs_base)
        if r.kind != "exit" or r.exit_code != 0:
            return HOST_OUTCOME["due"]
        if r.stdout != self.golden.stdout:
            return HOST_OUTCOME["sdc"]
        return HOST_OUTCOME["masked"]

    def classify(self, coords: np.ndarray) -> np.ndarray:
        out = np.zeros(len(coords), dtype=np.int32)
        for i, (step, reg, bit) in enumerate(coords):
            out[i] = self.classify_one(step, reg, bit)
        return out


def run_device_emu64(paths: BuildPaths, coords: np.ndarray,
                     max_steps: int = 4_000_000) -> np.ndarray:
    """The 64-bit classification path over a coordinate list — see
    Emu64Oracle."""
    return Emu64Oracle(paths, max_steps).classify(coords)


def wilson(successes: int, n: int, confidence: float = 0.95):
    from shrewd_tpu.parallel.stopping import wilson as _w
    return _w(successes, n, confidence)


def compare(host: np.ndarray, dev: np.ndarray) -> dict:
    n = len(host)
    host_v = host != 0
    dev_v = dev != 0
    h_avf = wilson(int(host_v.sum()), n)
    d_avf = wilson(int(dev_v.sum()), n)
    conf = np.zeros((3, 4), dtype=int)      # host class × device class
    for h, d in zip(host, dev):
        conf[h, d] += 1
    return {
        "trials": n,
        "host_tally": {"masked": int((host == 0).sum()),
                       "sdc": int((host == 1).sum()),
                       "due": int((host == 2).sum())},
        "device_tally": {"masked": int((dev == 0).sum()),
                         "sdc": int((dev == 1).sum()),
                         "due": int((dev == 2).sum()),
                         "detected": int((dev == 3).sum())},
        "host_avf": float(host_v.mean()),
        "host_avf_ci": [h_avf.lo, h_avf.hi],
        "device_avf": float(dev_v.mean()),
        "device_avf_ci": [d_avf.lo, d_avf.hi],
        "avf_abs_err": abs(float(host_v.mean()) - float(dev_v.mean())),
        "agreement_exact": float((host == dev).mean()),
        "agreement_vulnerable": float((host_v == dev_v).mean()),
        "confusion_host_x_device": conf.tolist(),
        "cis_overlap": bool(h_avf.lo <= d_avf.hi and d_avf.lo <= h_avf.hi),
    }


def run_diff(n_trials: int = 500, seed: int = 0,
             workload_c: str = "workloads/sort.c",
             mode: str = "output", max_steps: int = 2_000_000) -> dict:
    """Paired host-vs-device differential AVF.

    ``mode``:
      - "output" (default): extended-window replay to process exit,
        classification on the written stdout bytes + exit code — the exact
        host-oracle semantics;
      - "liveness": [kernel_begin, kernel_end) window with measured
        post-window first-access liveness masks (ingest/liveness.py);
      - "abi": static callee-saved-register heuristic (the r2 baseline,
        kept for comparison — known to over-report);
      - "emu64": perturbed whole-program re-execution on the 64-bit
        emulator, sampling the FULL bit range [0,64) — upper register
        halves and wrong paths included;
      - "device64": the pair-lane 64-bit lift (ingest/lift64.py) on the
        replay KERNEL, sampling bits [0,64) — the device column is
        computed on-device, with the emulator serving only as the
        diverged-trial escalation tier.
    """
    from shrewd_tpu.ingest.lift import GPR_NAMES_64

    paths = build_tools(workload_c)
    lv = None
    meta = None
    if mode == "emu64":
        # the emulator replays the raw capture — only the marker-window
        # *length* is needed, not a full lift of the window
        window = capture_window_macro_ops(paths, max_steps=max_steps)
        coords = sample_coords(n_trials, window, seed, bit_range=64)
        host = run_host(paths, coords)
        dev = run_device_emu64(paths, coords)
    else:
        bit_range = 32
        n_regs = 16
        if mode == "fp":
            # GPR + xmm fault space: regs 0..15 GPRs, 16..31 xmm low
            # lanes (hostsfi flips the latter via PTRACE_SETFPREGS)
            trace, meta = capture_and_lift_to_output(paths)
            window = meta["window_macro_ops"]
            n_regs = 32
        elif mode == "device64":
            from shrewd_tpu.ingest.lift64 import lift64
            trace, meta = capture_and_lift_to_output(paths, lifter=lift64,
                                                     max_steps=max_steps)
            window = meta["window_macro_ops"]
            bit_range = 64
        elif mode == "output":
            trace, meta = capture_and_lift_to_output(paths,
                                                     max_steps=max_steps)
            window = meta["window_macro_ops"]
        else:
            trace, meta = capture_and_lift(paths, max_steps=max_steps)
            window = meta["macro_ops"]
            if mode == "liveness":
                from shrewd_tpu.ingest.liveness import post_window_liveness
                lv = post_window_liveness(paths, meta["clusters"])
        coords = sample_coords(n_trials, window, seed,
                               bit_range=bit_range, n_regs=n_regs)
        host = run_host(paths, coords)
        dev_report: dict = {}
        dev = run_device(trace, meta, coords, liveness=lv, paths=paths,
                         report=dev_report)
    rep = compare(host, dev)
    if mode not in ("emu64",):
        rep.update(dev_report)
    rep["workload"] = workload_c
    rep["seed"] = seed
    rep["mode"] = mode
    if meta is not None:
        rep["lift_stats"] = meta["stats"]
    rep["window_macro_ops_sampled"] = window
    if mode == "output":
        rep["window_macro_ops"] = window
        rep["output_words"] = len(meta["output_words"])
        rep["output_syscalls"] = meta["output_syscalls"]
    if lv is not None:
        rep["liveness"] = {
            "live_regs": [GPR_NAMES_64[i] for i in
                          np.nonzero(lv.reg_live)[0]],
            "live_mem_words": int(lv.mem_word_mask(
                meta["clusters"], trace.mem_words).sum()),
            "post_window_steps": lv.steps,
            "truncated": lv.truncated,
            "unknown_insts": lv.unknown_insts,
        }
    return rep


if __name__ == "__main__":
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workload", default="workloads/sort.c")
    ap.add_argument("--mode", default="output",
                    choices=("output", "liveness", "abi", "emu64", "device64", "fp"))
    ap.add_argument("--out", default=str(REPO / "DIFF_AVF.json"))
    a = ap.parse_args()
    rep = run_diff(a.trials, a.seed, a.workload, mode=a.mode)
    with open(a.out, "w") as f:
        json.dump(rep, f, indent=1)
    print(json.dumps({k: rep[k] for k in
                      ("trials", "host_avf", "device_avf", "avf_abs_err",
                       "agreement_exact", "agreement_vulnerable",
                       "cis_overlap")}))
    sys.exit(0)
