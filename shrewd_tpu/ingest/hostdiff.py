"""Differential AVF: TPU replay kernel vs the real host CPU (hostsfi).

Closes the round-1 self-referentiality gap (VERDICT missing #2): the TPU
campaign's outcomes are compared trial-by-trial against a ground truth that
shares **no code** with the framework — the host x86 CPU itself, perturbed
through ptrace exactly the way the reference's SFI campaigns perturb a
simulated core through ``ThreadContext::setReg``
(``src/cpu/thread_context.hh:190-207``) and classified by program output
like the reference's golden-stdout verifiers (``tests/gem5/verifier.py``
MatchStdout).

Pairing: the SAME (step, reg, bit) coordinates drive both sides.  The host
flips bit *b* of GPR *r* after *step* dynamic instructions inside the
window; the TPU kernel injects KIND_REGFILE at cycle ``uop_start[step]``,
entry *r*, bit *b* on the lifted trace (ingest/lift.py maps macro steps to
µop indices).

Classification-scope caveat (inherent to windowed SFI): the host classifies
at *program end*, the replay kernel at *window end*.  The replay side
therefore compares memory plus the ABI live-out registers only — rsp, rbp,
rbx, r12–r15 are the registers the post-window code may legally read
(callee-saved, SysV ABI); caller-saved registers are dead at the
kernel_end call boundary, so their window-end corruption must not count.
Agreement is reported both per-class and binarized (vulnerable vs masked),
with Wilson CIs on both AVFs.
"""

from __future__ import annotations

import json
import subprocess
from pathlib import Path
from typing import NamedTuple

import numpy as np

REPO = Path(__file__).resolve().parent.parent.parent

# SysV AMD64 callee-saved registers (+ the stack pointer) in the canonical
# encoding order of tools/ptrace_common.h — the ABI-live-out comparison set.
LIVE_OUT_REGS = (3, 4, 5, 12, 13, 14, 15)     # rbx, rsp, rbp, r12..r15

HOST_OUTCOME = {"masked": 0, "sdc": 1, "due": 2}


class BuildPaths(NamedTuple):
    workload: Path
    tracer: Path
    hostsfi: Path
    begin: int
    end: int


def build_tools(workload_c: str = "workloads/sort.c",
                build_dir: Path | None = None) -> BuildPaths:
    """Compile the guest workload and both ptrace tools (idempotent)."""
    bd = build_dir or (REPO / "tests" / "_build")
    bd.mkdir(parents=True, exist_ok=True)
    wl_src = REPO / workload_c
    wl = bd / wl_src.stem
    tracer = bd / "nativetrace"
    sfi = bd / "hostsfi"

    def _build(out: Path, cmd: list[str]) -> None:
        src_mtimes = [Path(c).stat().st_mtime for c in cmd if
                      c.endswith((".c", ".cc"))]
        if out.exists() and all(out.stat().st_mtime >= m for m in src_mtimes):
            return
        subprocess.run(cmd + ["-o", str(out)], check=True,
                       capture_output=True, text=True)

    _build(wl, ["gcc", "-O1", "-static", "-fno-pie", "-no-pie", str(wl_src)])
    _build(tracer, ["g++", "-O2", "-std=c++17",
                    str(REPO / "tools" / "nativetrace.cc")])
    _build(sfi, ["g++", "-O2", "-std=c++17",
                 str(REPO / "tools" / "hostsfi.cc")])
    nm = subprocess.run(["nm", str(wl)], check=True, capture_output=True,
                        text=True).stdout
    syms = {p[2]: int(p[0], 16) for p in
            (ln.split() for ln in nm.splitlines()) if len(p) == 3}
    return BuildPaths(wl, tracer, sfi, syms["kernel_begin"],
                      syms["kernel_end"])


def capture_and_lift(paths: BuildPaths, build_dir: Path | None = None,
                     max_steps: int = 2_000_000):
    from shrewd_tpu.ingest.lift import lift
    bd = build_dir or (REPO / "tests" / "_build")
    trace_bin = bd / f"{paths.workload.name}_trace.bin"
    subprocess.run([str(paths.tracer), str(trace_bin), f"{paths.begin:x}",
                    f"{paths.end:x}", str(max_steps), str(paths.workload)],
                   check=True, capture_output=True, text=True)
    return lift(str(trace_bin), str(paths.workload))


def sample_coords(n_trials: int, window: int, seed: int = 0) -> np.ndarray:
    """(step, reg, bit) samples — bits restricted to the low 32 (the replay
    datapath's 32-bit projection tracks no higher bits)."""
    rng = np.random.default_rng(seed)
    return np.stack([
        rng.integers(0, window, n_trials),
        rng.integers(0, 16, n_trials),
        rng.integers(0, 32, n_trials),
    ], axis=1).astype(np.int64)


def run_host(paths: BuildPaths, coords: np.ndarray,
             build_dir: Path | None = None) -> np.ndarray:
    """hostsfi over the coordinate list → outcome classes int32[n]."""
    bd = build_dir or (REPO / "tests" / "_build")
    cpath = bd / "coords.txt"
    rpath = bd / "host_results.jsonl"
    np.savetxt(cpath, coords, fmt="%d")
    subprocess.run([str(paths.hostsfi), str(cpath), str(rpath),
                    f"{paths.begin:x}", f"{paths.end:x}",
                    str(paths.workload)],
                   check=True, capture_output=True, text=True)
    out = np.full(len(coords), -1, dtype=np.int32)
    with open(rpath) as f:
        for line in f:
            r = json.loads(line)
            out[r["trial"]] = HOST_OUTCOME[r["outcome"]]
    if (out < 0).any():
        raise RuntimeError("missing host trial results")
    return out


def run_device(trace, meta: dict, coords: np.ndarray) -> np.ndarray:
    """The same trials on the replay kernel → outcome classes int32[n].

    Dense kernel, no shadow detection (the host has no shadow FUs), memory
    plus ABI-live-out registers compared (see module docstring)."""
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.models.o3 import Fault, KIND_REGFILE, O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel

    k = TrialKernel(trace, O3Config(enable_shrewd=False))
    uop_start = np.asarray(meta["uop_start"], dtype=np.int64)
    step, reg, bit = coords.T
    faults = Fault(
        kind=jnp.full(len(coords), KIND_REGFILE, dtype=jnp.int32),
        cycle=jnp.asarray(uop_start[step], dtype=jnp.int32),
        entry=jnp.asarray(reg, dtype=jnp.int32),
        bit=jnp.asarray(bit, dtype=jnp.int32),
        shadow_u=jnp.ones(len(coords), dtype=jnp.float32))
    mask = np.zeros(trace.nphys, dtype=bool)
    mask[list(LIVE_OUT_REGS)] = True

    @jax.jit
    def outcomes(faults):
        results = jax.vmap(k._replay_one)(faults)
        return jax.vmap(lambda r: C.classify(
            r, k.golden, compare_regs=True,
            reg_mask=jnp.asarray(mask)))(results)

    return np.asarray(outcomes(faults))


def wilson(successes: int, n: int, confidence: float = 0.95):
    from shrewd_tpu.parallel.stopping import wilson as _w
    return _w(successes, n, confidence)


def compare(host: np.ndarray, dev: np.ndarray) -> dict:
    n = len(host)
    host_v = host != 0
    dev_v = dev != 0
    h_avf = wilson(int(host_v.sum()), n)
    d_avf = wilson(int(dev_v.sum()), n)
    conf = np.zeros((3, 4), dtype=int)      # host class × device class
    for h, d in zip(host, dev):
        conf[h, d] += 1
    return {
        "trials": n,
        "host_tally": {"masked": int((host == 0).sum()),
                       "sdc": int((host == 1).sum()),
                       "due": int((host == 2).sum())},
        "device_tally": {"masked": int((dev == 0).sum()),
                         "sdc": int((dev == 1).sum()),
                         "due": int((dev == 2).sum()),
                         "detected": int((dev == 3).sum())},
        "host_avf": float(host_v.mean()),
        "host_avf_ci": [h_avf.lo, h_avf.hi],
        "device_avf": float(dev_v.mean()),
        "device_avf_ci": [d_avf.lo, d_avf.hi],
        "avf_abs_err": abs(float(host_v.mean()) - float(dev_v.mean())),
        "agreement_exact": float((host == dev).mean()),
        "agreement_vulnerable": float((host_v == dev_v).mean()),
        "confusion_host_x_device": conf.tolist(),
        "cis_overlap": bool(h_avf.lo <= d_avf.hi and d_avf.lo <= h_avf.hi),
    }


def run_diff(n_trials: int = 500, seed: int = 0,
             workload_c: str = "workloads/sort.c") -> dict:
    paths = build_tools(workload_c)
    trace, meta = capture_and_lift(paths)
    coords = sample_coords(n_trials, meta["macro_ops"], seed)
    host = run_host(paths, coords)
    dev = run_device(trace, meta, coords)
    rep = compare(host, dev)
    rep["workload"] = workload_c
    rep["seed"] = seed
    rep["lift_stats"] = meta["stats"]
    return rep


if __name__ == "__main__":
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workload", default="workloads/sort.c")
    ap.add_argument("--out", default=str(REPO / "DIFF_AVF.json"))
    a = ap.parse_args()
    rep = run_diff(a.trials, a.seed, a.workload)
    with open(a.out, "w") as f:
        json.dump(rep, f, indent=1)
    print(json.dumps({k: rep[k] for k in
                      ("trials", "host_avf", "device_avf", "avf_abs_err",
                       "agreement_exact", "agreement_vulnerable",
                       "cis_overlap")}))
    sys.exit(0)
