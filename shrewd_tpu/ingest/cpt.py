"""gem5 checkpoint (`m5.cpt`) reader/writer.

Implements the reference's checkpoint container format from its observed
behavior (none of this is a code translation):

- ini database: ``[dotted.object.path]`` sections, ``name=value`` entries
  (``sim/serialize.hh:68-85``: ``CheckpointIn`` wraps ``IniFile``; section
  header written by ``Serializable::ScopedCheckpointSection`` as
  ``\\n[path]\\n``).
- arrays are space-separated scalars on one line (``arrayParamOut``); byte
  arrays print each byte as an unsigned int (``ShowParam<unsigned char>``,
  ``sim/serialize_handlers.hh:133-146``); bools print ``true``/``false``
  (``:148``).
- ``[Globals]`` holds ``curTick`` and the space-separated ``version_tags``
  set (``sim/globals.cc:59-87``).
- thread contexts serialize one flattened byte array per register class,
  keyed ``regs.<class>`` (free function ``serialize(const ThreadContext&)``,
  ``src/cpu/thread_context.cc``), and the PC state as ``_pc``/``_upc``
  (+ ``_npc``/``_nupc`` on delayed-slot ISAs,
  ``src/arch/generic/pcstate.hh:143-151``).
- physical memory stores write ``store_id``/``filename``/``range_size``
  entries and a gzipped raw image next to ``m5.cpt``
  (``PhysicalMemory::serializeStore``, ``src/mem/physical.cc:364-405``).

The writer emits the same shape so the golden gem5 binary can restore state
this framework produces (differential-testing in both directions).
"""

from __future__ import annotations

import gzip
import json
import os
import re
from typing import Iterator, NamedTuple

import numpy as np

from shrewd_tpu.ingest import configfile


def _numeric_aware_key(name: str) -> tuple:
    """Sort key splitting digit runs so cpu2 < cpu10 and store2 < store10
    (plain lexicographic sort would misorder indices ≥ 10)."""
    return tuple(int(tok) if tok.isdigit() else tok
                 for tok in re.split(r"(\d+)", name))


class CheckpointIn:
    """Parsed checkpoint database + directory for sibling store files."""

    def __init__(self, cpt_dir: str):
        self.cpt_dir = cpt_dir
        path = os.path.join(cpt_dir, "m5.cpt")
        with open(path) as f:
            self._db: dict[str, dict[str, str]] = configfile.parse_ini(
                f, "m5.cpt")

    # --- CheckpointIn API shape (sim/serialize.hh:86-93) ---

    def sections(self) -> list[str]:
        return list(self._db)

    def section_exists(self, section: str) -> bool:
        return section in self._db

    def entry_exists(self, section: str, entry: str) -> bool:
        return entry in self._db.get(section, {})

    def find(self, section: str, entry: str) -> str:
        try:
            return self._db[section][entry]
        except KeyError:
            raise KeyError(f"checkpoint has no [{section}] {entry}=") from None

    # --- typed getters ---

    def get_int(self, section: str, entry: str) -> int:
        return int(self.find(section, entry), 0)

    def get_bool(self, section: str, entry: str) -> bool:
        v = self.find(section, entry)
        if v not in ("true", "false"):
            raise ValueError(f"[{section}] {entry}={v!r} is not a cpt bool")
        return v == "true"

    def get_array(self, section: str, entry: str, dtype=np.uint64) -> np.ndarray:
        text = self.find(section, entry)
        vals = [int(x, 0) for x in text.split()] if text else []
        return np.array(vals, dtype=dtype)

    def get_bytes(self, section: str, entry: str) -> np.ndarray:
        return self.get_array(section, entry, dtype=np.uint8)

    def find_sections(self, pattern: str) -> Iterator[str]:
        """Sections whose dotted path matches `pattern` (regex, full match)."""
        rx = re.compile(pattern)
        for name in self._db:
            if rx.fullmatch(name):
                yield name

    # --- memory stores ---

    def load_store(self, section: str) -> tuple[int, np.ndarray]:
        """One physical-memory store → (range_size, bytes)."""
        filename = self.find(section, "filename")
        range_size = self.get_int(section, "range_size")
        path = os.path.join(self.cpt_dir, filename)
        with gzip.open(path, "rb") as f:
            data = np.frombuffer(f.read(), dtype=np.uint8)
        if data.size != range_size:
            raise ValueError(
                f"store {filename}: {data.size} bytes != range_size {range_size}")
        return range_size, data


class CheckpointOut:
    """Checkpoint writer mirroring the reference's on-disk shape."""

    def __init__(self, cpt_dir: str):
        self.cpt_dir = cpt_dir
        os.makedirs(cpt_dir, exist_ok=True)
        self._lines: list[str] = []

    def begin_section(self, name: str) -> None:
        self._lines.append(f"\n[{name}]")

    def param(self, name: str, value) -> None:
        if isinstance(value, bool):
            value = "true" if value else "false"
        self._lines.append(f"{name}={value}")

    def array(self, name: str, values) -> None:
        if isinstance(values, np.ndarray):
            values = values.ravel().tolist()
        self._lines.append(
            f"{name}={' '.join(str(v) for v in values)}")

    def store(self, name: str, store_id: int, data: np.ndarray) -> str:
        """Write a gzipped memory image + its section entries; returns the
        store filename (`<name>.store<id>.pmem`, physical.cc:368-369)."""
        filename = f"{name}.store{store_id}.pmem"
        self.param("store_id", store_id)
        self.param("filename", filename)
        self.param("range_size", int(data.size))
        with gzip.open(os.path.join(self.cpt_dir, filename), "wb") as f:
            f.write(np.asarray(data, dtype=np.uint8).tobytes())
        return filename

    def close(self) -> None:
        with open(os.path.join(self.cpt_dir, "m5.cpt"), "w") as f:
            f.write("\n".join(self._lines).lstrip("\n") + "\n")


class ArchSnapshot(NamedTuple):
    """Architectural state lifted from a checkpoint — the capture side of
    SURVEY §5.4: checkpoints hold *architectural* state only (O3 drains its
    pipeline before serializing, ``src/cpu/o3/cpu.cc:706-799``), so this is
    the restore+re-warm input, not a live pipeline image."""

    cur_tick: int
    version_tags: tuple[str, ...]
    pc: int
    int_regs: np.ndarray      # uint64[n_int]
    float_regs: np.ndarray    # uint64[n_float]
    mem: np.ndarray           # uint8 — stores concatenated in section order
    thread_section: str
    # (section, size) per memory store. The cpt format records no base
    # address per store (the reference restores by object identity,
    # physical.cc:442-449; address ranges live in config.ini) — so for
    # multi-store checkpoints, flat offsets into `mem` are per-store
    # offsets plus the preceding stores' sizes, NOT physical addresses.
    store_layout: tuple[tuple[str, int], ...] = ()
    # (vaddr, size) per store, from the checkpoint dir's config.json
    # sidecar when present.  The m5.cpt format itself records no base
    # addresses (the reference keeps address ranges in config.ini,
    # physical.cc:442-449); the sidecar plays config.ini's role so
    # snapshot-seeded emulation (ingest/emu.py) can address the image.
    regions: tuple = ()


def _thread_sections(cpt: CheckpointIn) -> list[str]:
    return sorted((s for s, entries in cpt._db.items()
                   if "regs.integer" in entries), key=_numeric_aware_key)


def load_arch_snapshot(cpt_dir: str, thread: int = 0) -> ArchSnapshot:
    """Lift one thread's architectural state + the physical memory image.

    Multi-store checkpoints concatenate store images in numeric section
    order; see ``ArchSnapshot.store_layout`` for the boundaries (the cpt
    format itself carries no per-store base address).
    """
    cpt = CheckpointIn(cpt_dir)
    threads = _thread_sections(cpt)
    if not threads:
        raise ValueError(f"{cpt_dir}: no thread context (regs.integer) found")
    if not 0 <= thread < len(threads):
        raise ValueError(
            f"{cpt_dir}: thread index {thread} out of range — checkpoint has "
            f"{len(threads)} thread context(s): {threads}")
    tsec = threads[thread]

    def regs(entry: str) -> np.ndarray:
        arr = cpt.get_bytes(tsec, entry)
        if arr.size % 8:
            raise ValueError(f"[{tsec}] {entry}: {arr.size} bytes "
                             f"is not a whole uint64 count")
        return arr

    int_regs = regs("regs.integer")
    float_regs = (regs("regs.floating_point")
                  if cpt.entry_exists(tsec, "regs.floating_point")
                  else np.zeros(0, np.uint8))

    stores = sorted((s for s, e in cpt._db.items() if "filename" in e
                     and "range_size" in e), key=_numeric_aware_key)
    images = [cpt.load_store(s)[1] for s in stores]
    mem = (np.concatenate(images) if images else np.zeros(0, np.uint8))
    layout = tuple((s, int(img.size)) for s, img in zip(stores, images))

    regions: tuple = ()
    side = os.path.join(cpt_dir, "config.json")
    if os.path.exists(side):
        with open(side) as f:
            cfg = json.load(f)
        by_sec = {e["section"]: int(e["vaddr"])
                  for e in cfg.get("stores", [])}
        if all(s in by_sec for s in stores):
            regions = tuple((by_sec[s], int(img.size))
                            for s, img in zip(stores, images))

    return ArchSnapshot(
        cur_tick=cpt.get_int("Globals", "curTick"),
        version_tags=tuple(cpt.find("Globals", "version_tags").split()),
        pc=cpt.get_int(tsec, "_pc"),
        int_regs=int_regs.view(np.uint64),
        float_regs=(float_regs.view(np.uint64) if float_regs.size else
                    np.zeros(0, np.uint64)),
        mem=mem,
        thread_section=tsec,
        store_layout=layout,
        regions=regions,
    )


VERSION_TAGS = ("shrewd-tpu-v1",)


def write_arch_snapshot(cpt_dir: str, snap: ArchSnapshot,
                        system: str = "system") -> None:
    """Emit an m5.cpt-shaped checkpoint from typed arrays (round-trip and
    golden-restore support)."""
    out = CheckpointOut(cpt_dir)
    out.begin_section("Globals")
    out.param("curTick", snap.cur_tick)
    out.array("version_tags", list(snap.version_tags or VERSION_TAGS))

    tsec = snap.thread_section or f"{system}.cpu.xc.0"
    out.begin_section(tsec)
    out.array("regs.integer", snap.int_regs.view(np.uint8))
    if snap.float_regs.size:
        out.array("regs.floating_point", snap.float_regs.view(np.uint8))
    out.param("_pc", snap.pc)
    out.param("_upc", 0)

    sidecar_stores = []
    if snap.regions:
        # one store per region + a config.json sidecar carrying the vaddrs
        # (the role config.ini plays in the reference)
        off = 0
        for sid, (vaddr, size) in enumerate(snap.regions):
            sec = f"{system}.physmem.store{sid}"
            out.begin_section(sec)
            out.store(f"{system}.physmem", sid,
                      snap.mem[off:off + size])
            sidecar_stores.append({"section": sec, "vaddr": int(vaddr),
                                   "size": int(size)})
            off += size
    elif snap.mem.size:
        out.begin_section(f"{system}.physmem.store0")
        out.store(f"{system}.physmem", 0, snap.mem)
    out.close()
    if sidecar_stores:
        with open(os.path.join(cpt_dir, "config.json"), "w") as f:
            json.dump({"stores": sidecar_stores}, f, indent=1)


def snapshot_from_capture(nt, cur_tick: int = 0) -> ArchSnapshot:
    """A nativetrace capture's initial state → ArchSnapshot.

    The capture already holds exactly what a drained checkpoint holds —
    architectural registers + memory image at the window boundary (SURVEY
    §5.4) — so the tracer doubles as the framework's SE-mode checkpointing
    tool; ``write_arch_snapshot`` of this result produces an m5.cpt that
    ``CheckpointSpec`` restores without the original process."""
    step0 = nt.steps[0]
    return ArchSnapshot(
        cur_tick=cur_tick,
        version_tags=VERSION_TAGS,
        pc=int(step0[16]),
        int_regs=np.asarray(step0[:16], dtype=np.uint64),
        float_regs=np.zeros(0, np.uint64),
        mem=np.concatenate([np.frombuffer(d, dtype=np.uint8)
                            for _, d in nt.regions])
        if nt.regions else np.zeros(0, np.uint8),
        thread_section="system.cpu.xc.0",
        store_layout=tuple(
            (f"system.physmem.store{i}", len(d))
            for i, (_, d) in enumerate(nt.regions)),
        regions=tuple((int(v), len(d)) for v, d in nt.regions),
    )
