"""Macro-op → µop lifter: real x86-64 dynamic streams → replayable Traces.

This replaces synthetic workloads (VERDICT r1 missing #1) with *real* dynamic
instruction streams captured from the host CPU by ``tools/nativetrace.cc``
(the NativeTrace/statetrace pattern, reference ``src/cpu/nativetrace.cc``,
``util/statetrace``).  The lifter plays the role of the reference's
macro→µop expansion (x86 ``Decoder`` + ``MicrocodeRom``,
``src/arch/x86/decoder.hh:57,75``, µop definitions under
``src/arch/x86/isa/microops/``), retargeted at the framework's 23-op
dataflow ISA (``isa/uops.py``) instead of gem5's µop ISA.

Design
------
- **32-bit projection.** The replay datapath is uint32; every x86-64 value
  is tracked as its low 32 bits.  64-bit adds/subs/logic/left-shifts project
  exactly; anything that does not (right shifts, partial-register writes,
  byte memory ops) is demoted per-instance by the self-check below.
- **Self-validating lift.** The lifter *simulates* each candidate µop
  sequence against the µop ISA semantics and compares all 16 GPRs with the
  captured next-step register state.  On mismatch the sequence is rolled
  back and replaced by an *opaque* lift — ``LUI rd, observed`` per changed
  register — which breaks dataflow through that one macro-op but re-syncs
  the register file to ground truth, so error never accumulates.  The
  fraction of opaque lifts is the fidelity metric (``LiftStats``).
- **Folded-affine memory remap.** Touched addresses cluster into a few
  dense regions (data/bss, live stack).  A pre-pass computes every dynamic
  effective address from the captured registers; each *static* instruction
  that always hits one cluster gets that cluster's remap constant folded
  into its displacement (the common case: array bases and rsp-relative
  slots are cluster-stable), so the remap costs zero µops.  A faulted
  address that leaves the cluster maps out of range and traps (DUE) — the
  wild-pointer-segfault reading, the software analog of the reference's
  page-table walk faults (``arch/x86/pagetable_walker.cc``).
- **Branch lifting with self-check.** cmp/test + jcc pairs lift to the µop
  branch set (BEQ/BNE/BLT/BGE, with SLTU for unsigned conditions); the
  lifted condition evaluated under the simulated golden state must equal
  the captured direction, else the branch is dropped (counted).  Return
  addresses are checked with an explicit BEQ against the captured target,
  so stack-slot corruption of a return address becomes a detected
  divergence.

The output ``Trace`` is bit-for-bit replayable by ops/replay.py: the golden
replay reproduces the captured register stream in its low 32 bits at every
non-opaque macro-op boundary (tests/test_lift.py).
"""

from __future__ import annotations

import re
import struct
import subprocess
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from shrewd_tpu.isa import uops as U
from shrewd_tpu.isa import semantics
from shrewd_tpu.trace.format import Trace

# canonical register order (tools/ptrace_common.h): x86-64 encoding order
GPR_NAMES_64 = ["rax", "rcx", "rdx", "rbx", "rsp", "rbp", "rsi", "rdi",
                "r8", "r9", "r10", "r11", "r12", "r13", "r14", "r15"]
GPR_NAMES_32 = ["eax", "ecx", "edx", "ebx", "esp", "ebp", "esi", "edi"] + \
    [f"r{i}d" for i in range(8, 16)]
GPR_NAMES_16 = ["ax", "cx", "dx", "bx", "sp", "bp", "si", "di"] + \
    [f"r{i}w" for i in range(8, 16)]
GPR_NAMES_8 = ["al", "cl", "dl", "bl", "spl", "bpl", "sil", "dil"] + \
    [f"r{i}b" for i in range(8, 16)]

_REGMAP: dict[str, tuple[int, int]] = {}    # name -> (index, width_bits)
for _i, _n in enumerate(GPR_NAMES_64):
    _REGMAP[_n] = (_i, 64)
for _i, _n in enumerate(GPR_NAMES_32):
    _REGMAP[_n] = (_i, 32)
for _i, _n in enumerate(GPR_NAMES_16):
    _REGMAP[_n] = (_i, 16)
for _i, _n in enumerate(GPR_NAMES_8):
    _REGMAP[_n] = (_i, 8)
# high-byte registers: unliftable partial writes; sources demote via self-check
for _i, _n in enumerate(["ah", "ch", "dh", "bh"]):
    _REGMAP[_n] = (_i, -8)

N_GPR = 16
RCX_ARCH = 1       # x86 encoding order: rcx is the shift/rotate count reg
# physical register layout of the lifted trace
ZERO = 16          # always-0 register (never written)
TCMP = 17          # cmp-immediate staging (live cmp → jcc only)
T0, T1, T2, T3 = 18, 19, 20, 21
T4, T5 = 22, 23    # sub-word expansion / cmov scratch
T6, T7 = 24, 25    # flags-preserving-instruction scratch
FX0 = 32           # xmm bank: phys FX0+k holds xmm{k}'s low 32 bits (f32)
FT0, FT1 = 48, 49  # FP-lift scratch (loaded operands, compare keys)
HSH = 50           # hi-half shadow of the last 64-bit imul (peephole)
# Register discipline: flags_src may reference T1/T2/TCMP between the
# flag-setting instruction and its consumer (jcc/cmov), and x86 mov/cmov/
# string/push do NOT write EFLAGS — so every lift of a flags-PRESERVING
# instruction must keep its scratch to T0/T3..T7 and never write T1/T2/TCMP.
NPHYS = 64

M32 = 0xFFFFFFFF


class NativeTrace(NamedTuple):
    """Parsed tools/nativetrace.cc capture."""

    begin: int
    end: int
    steps: np.ndarray           # uint64[n_steps+1, 18] (last = state at end)
    regions: list               # [(vaddr, bytes)] memory snapshot at begin
    fs_base: int = 0            # TLS base (SHTRACE2+); 0 if unrecorded


def read_nativetrace(path) -> NativeTrace:
    with open(path, "rb") as f:
        magic = f.read(8)
        if magic not in (b"SHTRACE1", b"SHTRACE2", b"SHTRACE3"):
            raise ValueError(f"bad magic {magic!r}")
        begin, end, n_steps, n_regions = struct.unpack("<4Q", f.read(32))
        fs_base = (struct.unpack("<Q", f.read(8))[0]
                   if magic != b"SHTRACE1" else 0)
        regions = []
        for _ in range(n_regions):
            vaddr, size = struct.unpack("<2Q", f.read(16))
            regions.append((vaddr, f.read(size)))
        data = f.read()
    # SHTRACE3 appends 8 u64 per step: the 16 xmm low lanes (f32 bit
    # patterns) packed two per word — columns 18..25
    cols = 26 if magic == b"SHTRACE3" else 18
    rec = cols * 8
    n_rec = len(data) // rec
    steps = np.frombuffer(data[:n_rec * rec], dtype=np.uint64).reshape(
        n_rec, cols)
    if n_rec not in (n_steps, n_steps + 1):
        raise ValueError(f"step records {n_rec} != n_steps {n_steps}(+1)")
    return NativeTrace(begin, end, steps, regions, fs_base)


# --- static decode via objdump --------------------------------------------

class Inst(NamedTuple):
    pc: int
    length: int
    mnemonic: str
    operands: list              # parsed Operand list (AT&T order)
    comment_addr: int | None    # resolved rip-relative target, if any


@dataclass
class Operand:
    kind: str                   # "reg" | "imm" | "mem"
    reg: int = -1               # arch index (reg kind)
    width: int = 0
    imm: int = 0
    # mem fields
    base: int = -1              # arch index or -1
    index: int = -1
    scale: int = 1
    disp: int = 0
    rip_rel: bool = False
    seg: str = ""               # segment override: "fs"/"gs" ("" = none)


_LINE_RE = re.compile(
    r"^\s*([0-9a-f]+):\s+((?:[0-9a-f]{2}\s)+)\s*(\S+)\s*(.*)$")
_MEM_RE = re.compile(
    r"^(-?0x[0-9a-f]+|-?\d+)?\((%\w+)?(?:,(%\w+),(\d+))?\)$")


def _parse_operand(tok: str, comment_addr: int | None) -> Operand | None:
    tok = tok.strip()
    if not tok:
        return None
    if tok.startswith("$"):
        return Operand("imm", imm=int(tok[1:], 0))
    if tok.startswith("%"):
        name = tok[1:]
        if name in _REGMAP:
            idx, width = _REGMAP[name]
            return Operand("reg", reg=idx, width=width)
        if name == "rip":
            return None
        if name.startswith(("ds:", "es:", "ss:", "cs:")):
            # zero-base segments in 64-bit mode (string-op operands print
            # as "%ds:(%rsi)" / "%es:(%rdi)"): parse the inner form plain
            return _parse_operand(name[3:], comment_addr)
        if name.startswith(("fs:", "gs:")):
            # Segment-relative absolute ("%fs:0x30"): base=-4 marks an
            # fs-relative address — unmappable for the lifter (demote) but
            # emulable against the captured fs_base (ingest/emu.py).
            # %gs: gets its OWN code (-5): no gs_base is captured, and
            # resolving it against fs_base would silently read the wrong
            # TLS block — the emulator stops loudly instead.
            segname = name[:2]
            rest = name[3:]
            try:
                return Operand("mem",
                               base=-4 if segname == "fs" else -5,
                               disp=int(rest, 0))
            except ValueError:
                pass
            # register-indirect segment forms ("%fs:(%rax)",
            # "%fs:0x10(,%rbx,4)"): parse the inner mem operand and mark
            # the override — the emulator adds fs_base to the computed ea
            # (gs still stops loudly); the lifter demotes like the
            # absolute forms
            inner = _parse_operand(rest, comment_addr)
            if inner is not None and inner.kind == "mem" \
                    and inner.base != -3:
                inner.seg = segname
                return inner
            return Operand("mem", base=-3)
        if re.fullmatch(r"k[0-7]", name):
            return Operand("kreg", reg=int(name[1]))
        if re.fullmatch(r"[xyz]mm(\d+)", name):
            idx = int(name[3:])
            if idx < 32:
                return Operand("xmm", reg=idx,
                               width={"x": 128, "y": 256,
                                      "z": 512}[name[0]])
        return Operand("reg", reg=-2)           # non-GPR (seg, x87, ...)
    if tok.startswith("*"):
        # indirect target: "*%rax", "*(%rip)", "*0x0(%rbp,%rbx,8)" — parse
        # the inner operand (the emulator executes these; the lifter's
        # call/jmp handling never needs the target, control follows the
        # captured stream)
        inner = _parse_operand(tok[1:], comment_addr)
        if inner is not None and inner.kind in ("mem", "reg"):
            return inner
        return Operand("mem", base=-3)
    m = _MEM_RE.match(tok)
    if m:
        disp = int(m.group(1), 0) if m.group(1) else 0
        base = -1
        rip_rel = False
        if m.group(2):
            bname = m.group(2)[1:]
            if bname == "rip":
                rip_rel = True
                if comment_addr is not None:
                    disp = comment_addr
            elif bname in _REGMAP:
                base = _REGMAP[bname][0]
            else:
                return Operand("mem", base=-3)
        index = -1
        scale = 1
        if m.group(3):
            iname = m.group(3)[1:]
            if iname not in _REGMAP:
                return Operand("mem", base=-3)
            index = _REGMAP[iname][0]
            scale = int(m.group(4))
        return Operand("mem", base=base, index=index, scale=scale,
                       disp=disp, rip_rel=rip_rel)
    # bare address (jump/call target or absolute mem)
    try:
        return Operand("imm", imm=int(tok, 16))
    except ValueError:
        return None


def _split_operands(s: str) -> list[str]:
    """Split on commas not inside parens."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def static_decode(binary: str) -> dict[int, Inst]:
    """objdump -d → {pc: Inst}.  The static half of the decode; the dynamic
    PC stream selects which of these execute (and in what order)."""
    txt = subprocess.run(["objdump", "-d", binary], capture_output=True,
                         text=True, check=True).stdout
    out: dict[int, Inst] = {}
    last_pc: int | None = None
    hexpair = re.compile(r"^[0-9a-f]{2}$")
    for line in txt.splitlines():
        # objdump wraps long encodings onto bytes-only continuation lines;
        # fold their byte count into the previous instruction's length (a
        # short length corrupts every pc+len computation: fall-through
        # targets, call return addresses).  A continuation line is exactly
        # "pc:" + 2-hex-char byte tokens — a real mnemonic token ("fadd")
        # is longer than a byte pair, so it cannot be mistaken for one.
        toks = line.split()
        if (last_pc is not None and len(toks) >= 2 and toks[0].endswith(":")
                and all(hexpair.match(t) for t in toks[1:])):
            prev = out[last_pc]
            out[last_pc] = prev._replace(length=prev.length + len(toks) - 1)
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        pc = int(m.group(1), 16)
        length = len(m.group(2).split())
        rest = m.group(4)
        comment_addr = None
        if "#" in rest:
            rest, comment = rest.split("#", 1)
            cm = re.match(r"\s*([0-9a-f]+)", comment)
            if cm:
                comment_addr = int(cm.group(1), 16)
        rest = rest.split("<")[0].strip()      # drop symbol annotations
        mnem = m.group(3)
        # objdump tokenizes prefix bytes as the mnemonic ("lock decl …");
        # fold ignorable-here prefixes into the real instruction (lock is
        # meaningless to a single-context interpretation; its atomicity is
        # what the reference's MemChecker polices, not dataflow)
        while mnem in ("lock", "bnd", "notrack", "data16") and rest:
            parts = rest.split(None, 1)
            mnem = parts[0]
            rest = parts[1] if len(parts) > 1 else ""
        if mnem in ("rep", "repz", "repe", "repnz", "repne") and rest:
            parts = rest.split(None, 1)
            mnem = f"{mnem} {parts[0]}"
            rest = parts[1] if len(parts) > 1 else ""
        ops = [o for o in (_parse_operand(t, comment_addr)
                           for t in _split_operands(rest)) if o is not None]
        out[pc] = Inst(pc, length, mnem, ops, comment_addr)
        last_pc = pc
    return out


# --- lift statistics -------------------------------------------------------

@dataclass
class LiftStats:
    macro_ops: int = 0
    lifted: int = 0             # exact dataflow lift, self-check passed
    opaque: int = 0             # demoted to observed-value resync
    branches: int = 0
    branches_lifted: int = 0
    branches_dropped: int = 0
    mem_accesses: int = 0
    mem_dropped: int = 0        # byte/unmappable accesses skipped
    clusters_dropped: int = 0   # low-32-colliding / wrapping clusters
    uops: int = 0
    opaque_mnemonics: dict = field(default_factory=dict)

    @property
    def lift_rate(self) -> float:
        return self.lifted / max(self.macro_ops, 1)

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "macro_ops", "lifted", "opaque", "branches", "branches_lifted",
            "branches_dropped", "mem_accesses", "mem_dropped",
            "clusters_dropped", "uops")}
        d["lift_rate"] = self.lift_rate
        d["opaque_mnemonics"] = dict(sorted(
            self.opaque_mnemonics.items(), key=lambda kv: -kv[1])[:12])
        return d


# --- the lifter ------------------------------------------------------------

_ALU2 = {  # mnemonic stem -> opcode for reg/reg (dst = dst OP src)
    "add": U.ADD, "sub": U.SUB, "and": U.AND, "or": U.OR, "xor": U.XOR,
    "imul": U.MUL,
}


def stem_of(m: str, *tables) -> str | None:
    """objdump size-suffix stripping, shared by the lifter and the
    emulator: strip at most ONE trailing b/w/l/q, and only when the
    remainder is in one of ``tables``.  ``rstrip("bwlq")`` eats stem
    letters — "subl" → "su", "roll" → "ro", "imulq" → "imu" — silently
    demoting suffixed memory-operand forms."""
    for t in tables:
        if m in t:
            return m
    if len(m) > 1 and m[-1] in "bwlq":
        c = m[:-1]
        for t in tables:
            if c in t:
                return c
    return None
_SHIFTS = {"shl": U.SLL, "sal": U.SLL, "shr": U.SRL, "sar": U.SRA}

_JCC_SIGNED = {  # cond after cmp(src=b, dst=a): flags of a-b
    "je": ("eq",), "jne": ("ne",), "jl": ("lt",), "jge": ("ge",),
    "jg": ("swap_lt",), "jle": ("swap_ge",),
    "js": ("sign",), "jns": ("nsign",),
}
_JCC_UNSIGNED = {"jb": False, "jnae": False, "jae": True, "jnb": True,
                 "ja": "swap_b", "jbe": "swap_ae"}

_CMOV = {"cmove": "eq", "cmovz": "eq", "cmovne": "ne", "cmovnz": "ne",
         "cmovl": "lt", "cmovge": "ge", "cmovg": "swap_lt",
         "cmovle": "swap_ge", "cmovs": "sign", "cmovns": "nsign",
         "cmovb": "ub", "cmovnae": "ub", "cmovae": "uae", "cmovnb": "uae",
         "cmova": "ua", "cmovnbe": "ua", "cmovbe": "ube", "cmovna": "ube"}


class Cluster(NamedTuple):
    lo: int                     # low-32 virtual address (inclusive)
    hi: int                     # low-32 virtual address (exclusive)
    word_off: int               # word offset in the flat replay memory


# --- x86 string ops (the erms memcpy/memset loops glibc leans on) ----------
# Single-stepping a rep-prefixed instruction traps once per ITERATION with
# rip unchanged, so each captured step is exactly one element move — the
# lifter emits that one element's dataflow and the register self-check
# validates it (direction-flag-reversed or otherwise odd iterations demote).

_STR_W = {"b": 1, "w": 2, "l": 4, "d": 4, "q": 8}


def _is_movs(inst: Inst) -> bool:
    m = inst.mnemonic.split()[-1]
    return (m[:-1] == "movs" and m[-1] in _STR_W
            and len(inst.operands) == 2
            and all(o.kind == "mem" for o in inst.operands))


def _is_stos(inst: Inst) -> bool:
    m = inst.mnemonic.split()[-1]
    return ((m == "stos" or (m[:-1] == "stos" and m[-1] in _STR_W))
            and len(inst.operands) == 2
            and inst.operands[0].kind == "reg"
            and inst.operands[1].kind == "mem")


def _str_width(inst: Inst) -> int:
    for o in inst.operands:
        if o.kind == "reg" and o.reg >= 0 and o.width:
            return abs(o.width) // 8
    return _STR_W.get(inst.mnemonic.split()[-1][-1], 8)


class Lifter:
    """One nativetrace capture + static decode → Trace + metadata."""

    # phys index of xmm0's low lane; None disables the FP lift (lift64
    # reuses 32..57 as GPR hi lanes)
    FP_BASE: "int | None" = FX0

    def __init__(self, nt: NativeTrace, insts: dict[int, Inst],
                 max_uops: int | None = None, elf_regs: list | None = None):
        self.nt = nt
        self.insts = insts
        self.max_uops = max_uops
        self.elf_regs = elf_regs or []      # (vaddr, bytes, ro) PT_LOADs
        self.stats = LiftStats()
        # emitted µop columns
        self.opcode: list[int] = []
        self.dst: list[int] = []
        self.src1: list[int] = []
        self.src2: list[int] = []
        self.imm: list[int] = []
        self.taken: list[int] = []
        self.mem_cluster: list[int] = []    # per-µop cluster idx (-1: none)
        self.resync_uops: list[int] = []    # LUIs emitted by demotions
        # (macro step, arch regs the demoted inst READS): a fault in one
        # of those registers flows into silicon behavior the replay never
        # models (e.g. a demoted ymm load's address crash channel) — the
        # host-diff harness escalates exactly those coords (hostdiff.py
        # _demoted_exposed)
        self.demoted_reads: list[tuple[int, list[int]]] = []
        self.uop_start: list[int] = []      # macro step -> first µop index
        # golden simulation state (the self-check oracle)
        self.reg = np.zeros(NPHYS, dtype=np.uint64)   # low-32 values (u64 buf)
        self.mem: np.ndarray | None = None  # uint32[mem_words]
        self.clusters: list[Cluster] = []
        self.mem_words = 0
        self.flags_src: tuple | None = None  # ('cmp'|'test'|'res', a, b)
        # (reg, macro_idx) after `imul r64, r64` whose true operands fit
        # u32: HSH holds high32 of the product, consumed by an adjacent
        # `shr $c, reg` with c >= 32 — the unsigned divide-by-constant
        # idiom (magic multiply + wide shift) every compiler emits
        self._hi_shadow: "tuple | None" = None

    # -- memory clustering (pre-pass) --------------------------------------

    def _ea_of(self, op: Operand, regs: np.ndarray) -> int | None:
        """Full-64-bit effective address from captured registers."""
        if op.base in (-3, -4, -5) or op.seg:
            return None
        ea = op.disp
        if op.rip_rel:
            return op.disp          # already resolved absolute
        if op.base >= 0:
            ea += int(regs[op.base])
        if op.index >= 0:
            ea += int(regs[op.index]) * op.scale
        return ea & 0xFFFFFFFFFFFFFFFF

    def _mem_width(self, inst: Inst, op: Operand) -> int:
        """Access width in bytes, from the register operand or suffix."""
        for o in inst.operands:
            if o.kind == "reg" and o.reg >= 0:
                return abs(o.width) // 8
        sfx = inst.mnemonic[-1]
        return {"b": 1, "w": 2, "l": 4, "q": 8}.get(sfx, 8)

    def build_memory_map(self) -> None:
        """Pre-pass: every dynamic EA → clusters → flat word layout, plus a
        per-static-pc cluster assignment (folded-affine remap)."""
        touched: dict[int, set[int]] = {}       # pc -> set of EAs
        steps = self.nt.steps
        n = len(steps) - 1
        for i in range(n):
            pc = int(steps[i][16])
            inst = self.insts.get(pc)
            if inst is None:
                continue
            if inst.mnemonic in ("call", "callq"):
                # implicit push of the return address
                touched.setdefault(pc, set()).add(
                    (int(steps[i][4]) - 8) & 0xFFFFFFFFFFFFFFFF)
            if inst.mnemonic in ("ret", "retq", "push", "pushq"):
                rsp = int(steps[i][4])
                ea = rsp - 8 if inst.mnemonic.startswith("push") else rsp
                touched.setdefault(pc, set()).add(ea & 0xFFFFFFFFFFFFFFFF)
            if inst.mnemonic in ("pop", "popq"):
                touched.setdefault(pc, set()).add(int(steps[i][4]))
            if _is_movs(inst):
                # two independent memory streams at one static pc: keyed
                # (pc, "s")/(pc, "d") so each gets its own cluster binding
                # (the plain pc key would demand a single shared cluster)
                for op, tag in zip(inst.operands, ("s", "d")):
                    ea = self._ea_of(op, steps[i])
                    if ea is not None:
                        touched.setdefault((pc, tag), set()).add(ea)
                continue
            for op in inst.operands:
                if op.kind != "mem" or op.base in (-3, -4, -5) or op.seg:
                    continue
                ea = self._ea_of(op, steps[i])
                if ea is not None:
                    touched.setdefault(pc, set()).add(ea)
        all_eas = sorted({ea for s in touched.values() for ea in s})
        if not all_eas:
            self.clusters = []
            self.mem_words = 64
            self.mem = np.zeros(64, dtype=np.uint32)
            self.pc_cluster = {}
            return
        # cluster EAs with gaps > 64 KiB separating clusters
        clusters_raw: list[list[int]] = [[all_eas[0]]]
        for ea in all_eas[1:]:
            if ea - clusters_raw[-1][-1] > 65536:
                clusters_raw.append([ea])
            else:
                clusters_raw[-1].append(ea)
        # Layout: each cluster padded, word-aligned, 16-word margin.  The
        # replay address space is the low-32 projection, so clusters whose
        # projected ranges collide cannot coexist — keep the heaviest
        # (most-touched) clusters and DROP the rest: a dropped cluster's
        # accesses demote to opaque via pc_cluster=None (stray one-off EAs
        # in the libc exit tail were colliding and failing whole lifts).
        weights = [len(c) for c in clusters_raw]
        order = sorted(range(len(clusters_raw)), key=lambda i: -weights[i])
        kept: list[tuple[int, int]] = []       # (lo32, hi32) accepted
        kept_idx = []
        for ci in order:
            c = clusters_raw[ci]
            lo = (c[0] & ~0x3F)                  # 64-byte align down
            hi = ((c[-1] + 8 + 0x3F) & ~0x3F) + 64
            lo32, hi32 = lo & M32, hi & M32
            if hi32 < lo32:                      # wraps the 32-bit space
                self.stats.clusters_dropped += 1
                continue
            if any(lo32 < h and ll < hi32 for ll, h in kept):
                self.stats.clusters_dropped += 1
                continue
            kept.append((lo32, hi32))
            kept_idx.append(ci)
        word_off = 0
        self.clusters = []
        for ci in sorted(kept_idx):
            c = clusters_raw[ci]
            lo = (c[0] & ~0x3F)
            hi = ((c[-1] + 8 + 0x3F) & ~0x3F) + 64
            self.clusters.append(Cluster(lo & M32, hi & M32, word_off))
            word_off += (hi - lo) // 4
        # +1: the replay kernel's VA crash model absorbs mapped-but-
        # untracked accesses at mem_words-1, which must lie outside every
        # cluster (and so outside every liveness comparison mask)
        self.mem_words = 1 << int(np.ceil(np.log2(max(word_off + 1, 64))))
        self.mem = np.zeros(self.mem_words, dtype=np.uint32)
        # Fill from the snapshot regions.  Reverse order so that on
        # overlap the EARLIEST region wins (its write lands last) — the
        # same first-match precedence the emulator uses, where live
        # snapshot regions precede read-only ELF fallback segments.
        for cl in self.clusters:
            for vaddr, data in reversed(self.nt.regions):
                va32 = vaddr & M32
                end32 = va32 + len(data)
                lo = max(cl.lo, va32)
                hi = min(cl.hi, end32)
                if lo >= hi:
                    continue
                src = data[lo - va32: hi - va32]
                nw = len(src) // 4
                w0 = cl.word_off + (lo - cl.lo) // 4
                self.mem[w0:w0 + nw] = np.frombuffer(
                    src[:nw * 4], dtype="<u4")
        # per-static-pc cluster: must be unique for the folded-affine remap
        self.pc_cluster: dict[int, Cluster | None] = {}
        for pc, eas in touched.items():
            cls = {self._cluster_of(ea & M32) for ea in eas}
            # None (an EA in a DROPPED cluster) must demote the pc, not be
            # discarded: folding a kept cluster's remap into a dropped
            # cluster's EA would store through a wrong replay word
            self.pc_cluster[pc] = (cls.pop() if len(cls) == 1
                                   and None not in cls else None)

    STACK_GROW = 4 << 20

    def map_regions(self) -> list:
        """Silicon-mapped address windows for the replay kernel's VA crash
        model: (lo32, span_bytes, writable).  Snapshot regions are the live
        writable map; ELF PT_LOAD segments add text/rodata (a store into a
        read-only one is a SIGSEGV on silicon).  The region holding the
        initial stack pointer extends downward by STACK_GROW — Linux grows
        the main-thread stack on demand, so an address landing shortly
        below the mapped stack does NOT fault on real hardware."""
        rsp0 = int(self.nt.steps[0][4])
        out = []
        for vaddr, data in self.nt.regions:
            lo, span = int(vaddr), len(data)
            if lo <= rsp0 < lo + span:
                lo -= self.STACK_GROW
                span += self.STACK_GROW
            out.append((lo & M32, int(span), True))
        for vaddr, data, ro in self.elf_regs:
            out.append((int(vaddr) & M32, len(data), not ro))
        return out

    def _cluster_of(self, ea32: int) -> Cluster | None:
        for cl in self.clusters:
            if cl.lo <= ea32 < cl.hi:
                return cl
        return None

    def _remap_const(self, cl: Cluster) -> int:
        """byte-address delta folded into a displacement: replay address =
        real_low32 + delta = 4*(word_off) + (real - lo)."""
        return (4 * cl.word_off - cl.lo) & M32

    # -- µop emission + simulation -----------------------------------------

    def _emit(self, op: int, dst: int, src1: int, src2: int, imm: int = 0,
              taken: int = 0) -> None:
        self.opcode.append(op)
        self.dst.append(dst)
        self.src1.append(src1)
        self.src2.append(src2)
        self.imm.append(imm & M32)
        self.taken.append(taken)
        # per-µop cluster for the replay kernel's VA-space crash model:
        # derived from the *golden* replay address (cluster-stable by the
        # folded-affine invariant), so every emission site gets it free
        if op in (U.LOAD, U.STORE):
            addr = (int(self.reg[src1]) + (imm & M32)) & M32
            self.mem_cluster.append(self._replay_cluster_idx(addr))
        else:
            self.mem_cluster.append(-1)
        self._sim_apply(op, dst, src1, src2, imm & M32)

    def _replay_cluster_idx(self, replay_addr: int) -> int:
        """Cluster index owning a flat replay byte-address, or -1."""
        w = replay_addr >> 2
        for i, cl in enumerate(self.clusters):
            if cl.word_off <= w < cl.word_off + (cl.hi - cl.lo) // 4:
                return i
        return -1

    def _sim_apply(self, op, dst, src1, src2, imm) -> None:
        r = self.reg
        a = int(r[src1]) & M32
        b = int(r[src2]) & M32
        sh = b & 31
        res = None
        if op == U.ADD:
            res = a + b
        elif op == U.SUB:
            res = a - b
        elif op == U.AND:
            res = a & b
        elif op == U.OR:
            res = a | b
        elif op == U.XOR:
            res = a ^ b
        elif op == U.SLL:
            res = a << sh
        elif op == U.SRL:
            res = a >> sh
        elif op == U.SRA:
            res = (a - (1 << 32) if a >= (1 << 31) else a) >> sh
        elif op == U.ADDI:
            res = a + imm
        elif op == U.ANDI:
            res = a & imm
        elif op == U.ORI:
            res = a | imm
        elif op == U.XORI:
            res = a ^ imm
        elif op == U.LUI:
            res = imm
        elif op == U.MUL:
            res = a * b
        elif op == U.SLT:
            res = int(self._s32(a) < self._s32(b))
        elif op == U.SLTU:
            res = int(a < b)
        elif op in (U.DIV, U.REM, U.DIVU, U.REMU, U.MULHU,
                    U.FADD, U.FSUB, U.FMUL, U.FDIV):
            res = semantics.alu(op, a, b, imm)
        elif op == U.LOAD:
            addr = (a + imm) & M32
            res = int(self.mem[(addr >> 2) & (self.mem_words - 1)]) \
                if (addr & 3) == 0 and (addr >> 2) < self.mem_words else 0
        elif op == U.STORE:
            addr = (a + imm) & M32
            if (addr & 3) == 0 and (addr >> 2) < self.mem_words:
                self.mem[addr >> 2] = b
            return
        else:                    # NOP / branches: no register effect
            return
        r[dst] = res & M32

    @staticmethod
    def _s32(v: int) -> int:
        return v - (1 << 32) if v & 0x80000000 else v

    def _const(self, value: int, treg: int) -> int:
        """Materialize a 32-bit constant (one ADDI off ZERO)."""
        self._emit(U.ADDI, treg, ZERO, ZERO, value & M32)
        return treg

    # -- per-macro-op lifting ----------------------------------------------

    def _addr_uops(self, op: Operand, pc: int, treg: int
                   ) -> tuple[int, int] | None:
        """µops computing the access address → (reg, folded_imm), or None
        if unmappable.  The cluster remap constant is folded into the
        displacement (zero-cost translation; see module docstring)."""
        cl = self.pc_cluster.get(pc)
        self.stats.mem_accesses += 1
        if cl is None:
            self.stats.mem_dropped += 1
            return None
        delta = self._remap_const(cl)
        if op.rip_rel or op.base < 0 and op.index < 0:
            base_reg = ZERO
            disp = op.disp
        elif op.index >= 0:
            if op.scale > 1:
                sh = self._const(op.scale.bit_length() - 1, T3)
                self._emit(U.SLL, treg, op.index, sh)
            else:
                self._emit(U.ADD, treg, op.index, ZERO)
            if op.base >= 0:
                self._emit(U.ADD, treg, treg, op.base)
            base_reg = treg
            disp = op.disp
        else:
            base_reg = op.base
            disp = op.disp
        return base_reg, (disp + delta) & M32

    # -- EVEX strlen chain -------------------------------------------------
    # glibc's __strlen_evex head is vpxorq zmmZ (zero) → vpcmpeqb
    # (mem),ymmZ,k → kmovd k,r32 → tzcnt: everything between memory bytes
    # and the GPR mask is vector state the 32-bit datapath cannot hold.
    # Tracked symbolically instead: a known-zero vector register set and a
    # per-k-register "byte==0 mask of W bytes at [base+disp]" record; at
    # kmovd the mask is MATERIALIZED as byte-compare µops against replay
    # memory, restoring fault propagation from string bytes to the length
    # (the r3/r4 strmix disagreement channel).  The register self-check
    # validates every materialized mask against the captured GPR, and any
    # unrecognized vector/k write invalidates the touched state
    # (fail-closed: unknown k at kmovd demotes exactly as before).

    class _VRegion(NamedTuple):
        pc: int            # the referencing instruction (cluster key)
        base: int          # address base register (canonical index)
        base_val: int      # captured base value at reference time (low 32)
        disp: int

    class _KMask(NamedTuple):
        regions: tuple     # _VRegion tuple; mask bit b = OR over regions
        width: int         # compared bytes (ymm: 32)

    class _KConcat(NamedTuple):
        lo: "Lifter._KMask"    # bits [0,32)  (kunpckdq src2)
        hi: "Lifter._KMask"    # bits [32,64) (kunpckdq src1)

    def _vec_state(self):
        if not hasattr(self, "_vzero"):
            self._vzero: set[int] = set()
            self._kmask: dict[int, Lifter._KMask | None] = {}
            # vector regs holding loaded/min-combined byte blocks: reg ->
            # (regions tuple, width); min(a,b)==0 iff a==0 or b==0, so a
            # vpminub chain is exactly a region-set union for the later
            # ==0 compare
            self._vreg: dict[int, tuple] = {}
        return self._vzero, self._kmask

    def _vec_reset(self) -> None:
        if hasattr(self, "_vzero"):
            self._vzero.clear()
            self._kmask.clear()
            self._vreg.clear()

    def _vregion_of(self, mem: "Operand", pc: int, regs: np.ndarray):
        if mem.base < 0 or mem.index >= 0 or mem.rip_rel or mem.seg:
            return None
        # the symbolic region record carries no µops, so a corrupted base
        # register would otherwise never influence the replay even though
        # the HARDWARE load through it is silicon's crash channel (strmix
        # r4: hi-bit rdi flips → silicon segfault, replay masked).  Emit a
        # word-aligned probe LOAD through the live/guarded address path:
        # the golden lane reads a golden word (dead value), a deviated
        # base trap/escapes exactly like any other access.
        r = self._addr_uops(mem, pc, T3)
        if r is None:
            return None                    # dropped cluster → demote
        base_r, disp = r
        self._emit(U.ADDI, T3, base_r, ZERO, disp)
        self._emit(U.ANDI, T3, T3, ZERO, 0xFFFFFFFC)
        self._emit(U.LOAD, T6, T3, ZERO, 0)
        return self._VRegion(pc, mem.base, int(regs[mem.base]) & M32,
                             mem.disp)

    def _lift_vec_chain(self, m: str, ops: list, pc: int,
                        regs: np.ndarray):
        """True/False when this instruction was consumed (lifted/demoted);
        None to fall through to the ordinary handlers."""
        touches_vec = any(o.kind in ("xmm", "kreg") for o in ops)
        if not touches_vec and m not in ("tzcnt",):
            return None
        vzero, kmask = self._vec_state()
        vreg = self._vreg
        # conservative pre-invalidation of the destination (AT&T: last op)
        # — except flags-only instructions (last operand is a source) and
        # kunpck, whose dst may alias a source (its handler re-writes it)
        if touches_vec and ops and not m.startswith(("kortest", "ktest",
                                                     "vptest", "kunpckdq")):
            d = ops[-1]
            if d.kind == "xmm":
                vzero.discard(d.reg)
                vreg.pop(d.reg, None)
            elif d.kind == "kreg":
                kmask[d.reg] = None

        if m in ("vpxor", "vpxord", "vpxorq", "xorps", "xorpd", "pxor") \
                and len(ops) in (2, 3) \
                and all(o.kind == "xmm" and o.reg == ops[0].reg
                        for o in ops):
            vzero.add(ops[0].reg)
            # FP-modeled low xmm regs: the scalar-SSE lift must still zero
            # the modeled lane (consuming here left it stale and demoted
            # every gcc pxor-zeroing idiom) — record and fall through
            if (self.FP_BASE is not None
                    and getattr(self, "_has_xmm", False)
                    and 0 <= ops[0].reg < 16 and abs(ops[0].width) <= 128):
                return None
            return True                      # architecturally GPR-silent

        if m in ("vmovdqa64", "vmovdqu64", "vmovdqa", "vmovdqu") \
                and len(ops) == 2 and ops[0].kind == "mem" \
                and ops[1].kind == "xmm":
            r = self._vregion_of(ops[0], pc, regs)
            if r is not None:
                vreg[ops[1].reg] = ((r,), abs(ops[1].width) // 8)
                return True                  # GPR-silent block load
            return False

        if m in ("vpminub",) and len(ops) == 3 and ops[2].kind == "xmm":
            # unsigned byte min: min(a,b)==0 iff a==0 or b==0 — the ==0
            # compare downstream sees the union of the source regions
            a, b, d = ops
            regions = []
            for o in (a, b):
                if o.kind == "mem":
                    r = self._vregion_of(o, pc, regs)
                    if r is None:
                        return False
                    regions.append(r)
                elif o.kind == "xmm" and o.reg in vreg:
                    regions.extend(vreg[o.reg][0])
                else:
                    return False
            if len(regions) > 4:
                return False
            vreg[d.reg] = (tuple(regions), abs(d.width) // 8)
            return True

        if m in ("vpcmpeqb",) and len(ops) == 3 \
                and ops[1].kind == "xmm" and ops[2].kind == "kreg":
            src, z, k = ops
            if z.reg not in vzero:
                return False
            w = abs(z.width) // 8
            if src.kind == "mem":
                r = self._vregion_of(src, pc, regs)
                if r is not None:
                    kmask[k.reg] = self._KMask((r,), w)
                    return True
                return False
            if src.kind == "xmm" and src.reg in vreg:
                kmask[k.reg] = self._KMask(vreg[src.reg][0], w)
                return True
            return False                     # unknown compare → opaque

        if m in ("kmovd",) and len(ops) == 2 and ops[0].kind == "kreg" \
                and ops[1].kind == "reg" and ops[1].reg >= 0:
            st = kmask.get(ops[0].reg)
            dst = ops[1].reg
            if not isinstance(st, self._KMask) or st.width > 32 \
                    or not self._kmask_live(st, dst, regs):
                return False
            return self._materialize_kmask(st, dst, regs)

        if m in ("kunpckdq",) and len(ops) == 3 \
                and all(o.kind == "kreg" for o in ops):
            # AT&T (src2, src1, dst): dst[31:0]=src2, dst[63:32]=src1
            lo_st, hi_st = kmask.get(ops[0].reg), kmask.get(ops[1].reg)
            if not isinstance(lo_st, self._KMask) \
                    or not isinstance(hi_st, self._KMask):
                kmask[ops[2].reg] = None
                return False
            kmask[ops[2].reg] = self._KConcat(lo_st, hi_st)
            return True                      # GPR-silent

        if m in ("kmovq",) and len(ops) == 2 and ops[0].kind == "kreg" \
                and ops[1].kind == "reg" and ops[1].reg >= 0:
            st = kmask.get(ops[0].reg)
            dst = ops[1].reg
            if isinstance(st, self._KConcat):
                # 32-bit projection: only the low half is tracked (the
                # pair-lane lifter overrides with the hi lane too)
                if not self._kmask_live(st.lo, dst, regs):
                    return False
                return self._materialize_kmask(st.lo, dst, regs)
            if st is not None and st.width <= 32 \
                    and self._kmask_live(st, dst, regs):
                return self._materialize_kmask(st, dst, regs)
            return False

        if m in ("kortestd",) and len(ops) == 2 \
                and all(o.kind == "kreg" for o in ops):
            # flags = (k0 | k1) == 0; OR of masks = union of regions.
            # No GPR is written, so the register self-check cannot vet
            # this — the BRANCH self-check (captured direction vs lifted
            # condition) is the net instead.
            sts = [kmask.get(o.reg) for o in ops]
            if any(not isinstance(s, self._KMask) or s.width > 32
                   or not self._kmask_live(s, TCMP, regs) for s in sts):
                return False
            if sts[0].width != sts[1].width:
                # differing compare widths: the narrower mask's high bits
                # are architecturally zero, but a region-union would
                # materialize phantom byte-compares there — demote
                return False
            merged = self._KMask(sts[0].regions + sts[1].regions,
                                 sts[0].width)
            if len(merged.regions) > 8 \
                    or not self._materialize_kmask(merged, TCMP, regs):
                return False
            self.flags_src = ("res", TCMP)
            return True

        if m == "tzcnt" and len(ops) == 2 \
                and all(o.kind == "reg" and o.reg >= 0
                        and abs(o.width) == 32 for o in ops):
            self._emit_ctz32(ops[0].reg, ops[1].reg)
            self.flags_src = ("res", ops[1].reg)
            return True

        # fall through: the scalar-SSE FP lift (and the generic demotion
        # path) still see the instruction; state was already invalidated
        return None

    def _kmask_live(self, st: "_KMask", dst: int, regs: np.ndarray) -> bool:
        """The materialization addresses through the live base registers,
        so none may be the destination.  A base that moved since the
        compare (the strlen 4× loop bumps rdi before kortest) is fine —
        the golden drift folds into the displacement."""
        return all(r.base != dst for r in st.regions)

    def _materialize_kmask(self, st: "_KMask", dst: int,
                           regs: np.ndarray) -> bool:
        """dst = bitmask over st.width bytes: bit b set iff byte b == 0 in
        ANY region (single region: the vpcmpeqb-vs-zero result; several:
        the vpminub-combined compare) — recomputed from replay memory so
        corrupted string bytes reach the mask."""
        deltas = []
        for r in st.regions:
            cl = self.pc_cluster.get(r.pc)
            self.stats.mem_accesses += 1
            if cl is None:
                self.stats.mem_dropped += 1
                return False
            # golden drift of the base register since the compare: on the
            # golden path base_now + (disp − drift) == base_then + disp;
            # off-path a corrupted base shifts the window, as on hardware
            drift = (int(regs[r.base]) - r.base_val) & M32
            deltas.append((r, (r.disp + self._remap_const(cl) - drift)
                           & M32))
        # cost note: ~11 µops/byte/region (354 per 32-byte single-region
        # kmovd).  Bounded in practice — strmix's materializations total
        # ≈ 25k µops, a few % of the largest lifted windows — and every
        # µop is validated by the register self-check, so the simple
        # per-byte form is kept over a load-each-word-once variant (~22%
        # fewer µops, more edge cases).
        self._emit(U.LUI, dst, ZERO, ZERO, 0)
        self._emit(U.ADDI, T3, ZERO, ZERO, 3)         # byte→bit shift ×8
        for i in range(st.width):
            first = True
            for r, delta in deltas:
                # pointers are NOT word-aligned: per-byte address with an
                # aligned word load + dynamic in-word shift
                self._emit(U.ADDI, T2, r.base, ZERO, (delta + i) & M32)
                self._emit(U.ANDI, T6, T2, ZERO, (~3) & M32)
                self._emit(U.LOAD, T6, T6, ZERO, 0)
                self._emit(U.ANDI, T4, T2, ZERO, 3)
                self._emit(U.SLL, T4, T4, T3)
                self._emit(U.SRL, T5, T6, T4)
                self._emit(U.ANDI, T5, T5, ZERO, 0xFF)
                self._emit(U.SLTU, T5, ZERO, T5)
                self._emit(U.XORI, T5, T5, ZERO, 1)
                if first:
                    self._emit(U.ADD, T7, T5, ZERO)
                    first = False
                else:
                    self._emit(U.OR, T7, T7, T5)
            self._emit(U.ADDI, T4, ZERO, ZERO, i)
            self._emit(U.SLL, T5, T7, T4)
            self._emit(U.OR, dst, dst, T5)
        return True

    def _emit_ctz32(self, src: int, dst: int) -> None:
        """Branchless count-trailing-zeros (tzcnt semantics: 32 for 0)."""
        self._emit(U.ADD, T5, src, ZERO)
        self._emit(U.LUI, T6, ZERO, ZERO, 0)
        for msk, log in ((0xFFFF, 4), (0xFF, 3), (0xF, 2), (0x3, 1),
                         (0x1, 0)):
            self._emit(U.ANDI, T4, T5, ZERO, msk)
            self._emit(U.SLTU, T4, ZERO, T4)
            self._emit(U.XORI, T4, T4, ZERO, 1)       # low part all-zero?
            self._emit(U.ADDI, T3, ZERO, ZERO, log)
            self._emit(U.SLL, T4, T4, T3)             # 0 or 2^log
            self._emit(U.ADD, T6, T6, T4)
            self._emit(U.SRL, T5, T5, T4)
        self._emit(U.ANDI, T4, T5, ZERO, 1)
        self._emit(U.XORI, T4, T4, ZERO, 1)
        self._emit(U.ADD, T6, T6, T4)                 # src==0 → 32
        self._emit(U.ADD, dst, T6, ZERO)

    # -- x86 string ops ----------------------------------------------------
    # Canonical indices of the implicit string registers.
    _RSI, _RDI, _RCX = 6, 7, 1

    def _lift_movs(self, inst: Inst, pc: int, regs: np.ndarray) -> bool:
        """One movs iteration: [rdi] <- [rsi], rsi/rdi advance, rep
        decrements rcx.  DF=1 (backward) iterations fail the register
        self-check and demote — fail-closed."""
        w = _str_width(inst)
        scl = self.pc_cluster.get((pc, "s"))
        dcl = self.pc_cluster.get((pc, "d"))
        self.stats.mem_accesses += 2
        if scl is None or dcl is None or w < 4:
            self.stats.mem_dropped += 2
            return False
        self._str_copy_word(self._remap_const(scl), self._remap_const(dcl),
                            w)
        self._inc_strreg(self._RSI, w)
        self._inc_strreg(self._RDI, w)
        if inst.mnemonic.startswith("rep"):
            self._inc_strreg(self._RCX, -1)
        return True

    def _stos_hi_imm(self, src_reg: int, regs: np.ndarray) -> int:
        """High word a qword stos writes: the 32-bit projection tracks
        only the low lane, so the high half is golden-frozen from the
        captured register (lift64 overrides with the live hi lane)."""
        return (int(regs[src_reg]) >> 32) & M32

    def _lift_stos(self, inst: Inst, pc: int, regs: np.ndarray) -> bool:
        """One stos iteration: [rdi] <- rax/eax/al, rdi advances, rep
        decrements rcx (the erms memset loop)."""
        w = _str_width(inst)
        src, dst = inst.operands
        if w >= 4:
            cl = self.pc_cluster.get(pc)
            self.stats.mem_accesses += 1
            if cl is None:
                self.stats.mem_dropped += 1
                return False
            self._str_store_reg(src.reg, self._remap_const(cl), w,
                                self._stos_hi_imm(src.reg, regs))
        elif not self._subword_store(dst, pc, regs, w, src_reg=src.reg):
            return False
        self._inc_strreg(self._RDI, w)
        if inst.mnemonic.startswith("rep"):
            self._inc_strreg(self._RCX, -1)
        return True

    # overridable string-op primitives (Lifter64 widens them to pair lanes)
    def _str_copy_word(self, sdelta: int, ddelta: int, w: int) -> None:
        self._emit(U.LOAD, T6, self._RSI, ZERO, sdelta)
        self._emit(U.STORE, 0, self._RDI, T6, ddelta)
        if w == 8:
            # both halves move memory→memory: exact even in the 32-bit
            # projection, and replay memory stays byte-faithful for later
            # byte readers (the EVEX mask materialization reads it)
            self._emit(U.LOAD, T7, self._RSI, ZERO, (sdelta + 4) & M32)
            self._emit(U.STORE, 0, self._RDI, T7, (ddelta + 4) & M32)

    def _str_store_reg(self, reg: int, ddelta: int, w: int,
                       hi_imm: int = 0) -> None:
        self._emit(U.STORE, 0, self._RDI, reg, ddelta)
        if w == 8:
            self._emit(U.LUI, T7, ZERO, ZERO, hi_imm)
            self._emit(U.STORE, 0, self._RDI, T7, (ddelta + 4) & M32)

    def _inc_strreg(self, r: int, v: int) -> None:
        self._emit(U.ADDI, r, r, ZERO, v & M32)

    # -- sub-word (byte/halfword) memory access expansion ------------------
    #
    # The replay µop ISA is word-only (LOAD/STORE trap on addr&3 != 0, the
    # reference analog being x86's own alignment machinery); byte accesses
    # are expanded to word load + shift/mask/merge sequences whose shift
    # amount is computed *dynamically* from the effective address, so a
    # fault-corrupted base register still selects the right byte of the
    # right word (mirrors how x86 µcode slices sub-word accesses,
    # /root/reference/src/arch/x86/isa/microops/ldstop.isa).

    def _subword_addr(self, op: Operand, pc: int, regs: np.ndarray,
                      width: int):
        """µops leaving word address in T0 and bit-shift (=8×byte-offset)
        in T3 → (T0, T3); None demotes (unmappable or straddling word)."""
        ea = self._ea_of(op, regs)
        if ea is None or (ea & 3) + width > 4:
            return None
        a = self._addr_uops(op, pc, T0)
        if a is None:
            return None
        self._emit(U.ADDI, T0, a[0], ZERO, a[1])        # byte EA (remapped)
        c3 = self._const(3, T4)
        self._emit(U.ANDI, T3, T0, ZERO, 3)
        self._emit(U.SLL, T3, T3, c3)                   # (ea & 3) * 8
        self._emit(U.ANDI, T0, T0, ZERO, 0xFFFFFFFC)
        return T0, T3

    def _subword_load_value(self, src: Operand, pc: int, regs: np.ndarray,
                            width: int, signed: bool, out_reg: int) -> bool:
        """Load byte/halfword → zero/sign-extended value in ``out_reg``."""
        wa = self._subword_addr(src, pc, regs, width)
        if wa is None:
            return False
        word_r, sh_r = wa
        self._emit(U.LOAD, T6, word_r, ZERO, 0)
        self._emit(U.SRL, T6, T6, sh_r)
        msk = 0xFF if width == 1 else 0xFFFF
        self._emit(U.ANDI, out_reg, T6, ZERO, msk)
        if signed:
            sbit = msk ^ (msk >> 1)
            self._emit(U.XORI, out_reg, out_reg, ZERO, sbit)
            self._emit(U.ADDI, out_reg, out_reg, ZERO, (-sbit) & M32)
        return True

    def _subword_store(self, dst: Operand, pc: int, regs: np.ndarray,
                       width: int, src_reg: int | None = None,
                       src_imm: int | None = None) -> bool:
        """Store the low byte/halfword of a register (or an immediate)."""
        wa = self._subword_addr(dst, pc, regs, width)
        if wa is None:
            return False
        word_r, sh_r = wa
        msk = 0xFF if width == 1 else 0xFFFF
        self._emit(U.LOAD, T6, word_r, ZERO, 0)
        self._emit(U.LUI, T7, ZERO, ZERO, msk)
        self._emit(U.SLL, T7, T7, sh_r)
        self._emit(U.XORI, T7, T7, ZERO, M32)           # ~(msk << sh)
        self._emit(U.AND, T6, T6, T7)
        if src_imm is not None:
            self._emit(U.LUI, T5, ZERO, ZERO, src_imm & msk)
        else:
            self._emit(U.ANDI, T5, src_reg, ZERO, msk)
        self._emit(U.SLL, T5, T5, sh_r)
        self._emit(U.OR, T6, T6, T5)
        self._emit(U.STORE, 0, word_r, T6, 0)
        return True

    def _extend_reg(self, src_reg: int, width: int, signed: bool,
                    out_reg: int) -> None:
        """out = zero/sign-extended low byte/halfword of src."""
        msk = 0xFF if width == 1 else 0xFFFF
        self._emit(U.ANDI, out_reg, src_reg, ZERO, msk)
        if signed:
            sbit = msk ^ (msk >> 1)
            self._emit(U.XORI, out_reg, out_reg, ZERO, sbit)
            self._emit(U.ADDI, out_reg, out_reg, ZERO, (-sbit) & M32)

    def _cond_bool(self, cond: str, out_reg: int) -> int | None:
        """Materialize a flag condition as 0/1 in ``out_reg`` (for cmov),
        from the recorded flags_src — same condition algebra as _lift_jcc
        but branch-free (the select must stay value-faithful under faults,
        so no control flow)."""
        if self.flags_src is None:
            return None
        k = self.flags_src[0]
        if k == "fcmp":
            # float compare keys: only unordered-style conditions map to
            # SLTU/equality on the keys (as in _lift_jcc); everything
            # else demotes fail-closed
            if cond not in ("eq", "ne", "ub", "uae", "ua", "ube"):
                return None
            k = "cmp"
        if k in ("cmp", "cmpb"):
            a, b = self.flags_src[1], self.flags_src[2]
        else:
            a, b = self.flags_src[1], ZERO
        neg = False
        if cond in ("eq", "ne"):
            self._emit(U.XOR, out_reg, a, b)
            self._emit(U.SLTU, out_reg, ZERO, out_reg)      # != 0
            neg = cond == "eq"
        elif cond in ("lt", "ge"):
            self._emit(U.SLT, out_reg, a, b)
            neg = cond == "ge"
        elif cond in ("swap_lt", "swap_ge"):                # gt / le
            self._emit(U.SLT, out_reg, b, a)
            neg = cond == "swap_ge"
        elif cond in ("sign", "nsign"):
            if k == "cmpb":
                return None      # sub-word SF not reproducible (overflow)
            if k == "cmp":
                self._emit(U.SUB, out_reg, a, b)
                self._emit(U.SLT, out_reg, out_reg, ZERO)
            else:
                self._emit(U.SLT, out_reg, a, ZERO)
            neg = cond == "nsign"
        elif cond in ("ub", "uae"):                         # b / ae
            self._emit(U.SLTU, out_reg, a, b)
            neg = cond == "uae"
        elif cond in ("ua", "ube"):                         # a / be
            self._emit(U.SLTU, out_reg, b, a)
            neg = cond == "ube"
        else:
            return None
        if neg:
            self._emit(U.XORI, out_reg, out_reg, ZERO, 1)
        return out_reg

    def _subword_alu(self, opcode: int, src: Operand, dst: Operand,
                     pc: int, regs: np.ndarray, width: int) -> bool:
        """Byte/halfword ALU with a register destination: compute on
        sign-extended operands (bitwise low bits coincide; add/sub wrap at
        merge), merge into dst's low byte/word, and record sub-word flags
        — SUB keeps exact ("cmpb") compare flags, the rest expose ZF/SF of
        the sign-extended result."""
        if dst.kind != "reg" or dst.reg < 0 or opcode == U.MUL:
            return False
        msk = 0xFF if width == 1 else 0xFFFF
        sbit = msk ^ (msk >> 1)
        self._extend_reg(dst.reg, width, True, T2)
        if src.kind == "imm":
            v = src.imm & msk
            v = v - (msk + 1) if v & sbit else v
            self._const(v & M32, TCMP)
        elif src.kind == "reg" and src.reg >= 0:
            self._extend_reg(src.reg, width, True, TCMP)
        elif src.kind == "mem":
            if not self._subword_load_value(src, pc, regs, width, True,
                                            TCMP):
                return False
        else:
            return False
        self._emit(opcode, T5, T2, TCMP)
        self._emit(U.ANDI, T6, T5, ZERO, msk)
        self._emit(U.ANDI, dst.reg, dst.reg, ZERO, (~msk) & M32)
        self._emit(U.OR, dst.reg, dst.reg, T6)
        if opcode == U.SUB:
            self.flags_src = ("cmpb", T2, TCMP)
        else:
            self._extend_reg(T5, width, True, T1)
            self.flags_src = ("res", T1)
        return True

    def _lift_one(self, i: int, inst: Inst, regs: np.ndarray,
                  next_regs: np.ndarray, next_pc: int) -> bool:
        """Emit µops for macro-op i; returns False to request opaque demotion
        (caller rolls back).  Self-check against next_regs happens in the
        caller for all paths."""
        m = inst.mnemonic
        ops = inst.operands
        pc = inst.pc

        # --- EVEX strlen chain (vpxorq / vpcmpeqb→k / kmovd / tzcnt) ---
        handled = self._lift_vec_chain(m, ops, pc, regs)
        if handled is not None:
            return handled

        # --- scalar-SSE float (xmm low lanes → FADD..FDIV µops) ---
        if any(o.kind == "xmm" for o in ops):
            if self.FP_BASE is None or not getattr(self, "_has_xmm", False):
                # no captured xmm lanes (SHTRACE1/2) → the FP bank would
                # be unverifiable; demote rather than fail open
                return False
            return self._lift_fp(m, ops, pc, regs)

        # --- x86 string ops (one captured iteration per step) ---
        if _is_movs(inst):
            return self._lift_movs(inst, pc, regs)
        if _is_stos(inst):
            return self._lift_stos(inst, pc, regs)

        # --- moves ---
        if m in ("mov", "movq", "movl", "movb", "movw", "movabs", "movslq",
                 "movsxd", "cltq", "cdqe"):
            if m in ("cltq", "cdqe"):            # sign-extend eax→rax: low32 id
                return True                       # no-op in projection
            if len(ops) != 2:
                return False
            src, dst = ops
            width = {"movb": 1, "movw": 2}.get(m)
            if width is None:
                rws = [abs(o.width) // 8 for o in ops
                       if o.kind == "reg" and o.reg >= 0 and o.width]
                width = min(rws) if rws else 4
            if any(o.kind == "reg" and o.reg >= 0 and o.width < 0
                   for o in ops):
                return False      # %ah-family: not the low byte — demote
                                  # (a store writes no GPR, so the register
                                  # self-check could NOT catch this)
            if width < 4:
                # sub-word: byte/halfword stores, loads with partial-reg
                # merge, and partial-reg register moves
                msk = 0xFF if width == 1 else 0xFFFF
                if dst.kind == "mem":
                    if src.kind == "imm":
                        return self._subword_store(dst, pc, regs, width,
                                                   src_imm=src.imm)
                    if src.kind == "reg" and src.reg >= 0:
                        return self._subword_store(dst, pc, regs, width,
                                                   src_reg=src.reg)
                    return False
                if dst.kind == "reg" and dst.reg >= 0:
                    if src.kind == "imm":
                        self._emit(U.LUI, T6, ZERO, ZERO, src.imm & msk)
                    elif src.kind == "reg" and src.reg >= 0:
                        self._emit(U.ANDI, T6, src.reg, ZERO, msk)
                    elif src.kind == "mem":
                        if not self._subword_load_value(src, pc, regs,
                                                        width, False, T6):
                            return False
                    else:
                        return False
                    self._emit(U.ANDI, dst.reg, dst.reg, ZERO,
                               (~msk) & M32)
                    self._emit(U.OR, dst.reg, dst.reg, T6)
                    return True
                return False
            if dst.kind == "reg" and dst.reg >= 0:
                if src.kind == "imm":
                    self._emit(U.LUI, dst.reg, ZERO, ZERO, src.imm)
                    return True
                if src.kind == "reg" and src.reg >= 0:
                    self._emit(U.ADD, dst.reg, src.reg, ZERO)
                    return True
                if src.kind == "mem":
                    a = self._addr_uops(src, pc, T0)
                    if a is None:
                        return False
                    self._emit(U.LOAD, dst.reg, a[0], ZERO, a[1])
                    return True
                return False
            if dst.kind == "mem":
                if self._mem_width(inst, dst) < 4:
                    return False
                a = self._addr_uops(dst, pc, T0)
                if a is None:
                    return False
                if src.kind == "imm":
                    # mov writes no flags: T6, not T1 (flags_src may be T1)
                    self._emit(U.ADDI, T6, ZERO, ZERO, src.imm & M32)
                    self._emit(U.STORE, 0, a[0], T6, a[1])
                    return True
                if src.kind == "reg" and src.reg >= 0:
                    self._emit(U.STORE, 0, a[0], src.reg, a[1])
                    return True
                return False
            return False

        if m in ("movzbl", "movzwl", "movzbq", "movzwq",
                 "movsbl", "movswl", "movsbq", "movswq"):
            if len(ops) != 2:
                return False
            src, dst = ops
            width = 1 if m[4] == "b" else 2
            signed = m.startswith("movs")
            # 16-bit destinations (movzbw) merge into dst[15:0] on real
            # x86 — not handled; the *l/*q forms write the full register
            if dst.kind != "reg" or dst.reg < 0 or abs(dst.width) < 32:
                return False
            if src.kind == "reg" and src.reg >= 0 and src.width < 0:
                return False                      # %ah-family source
            if src.kind == "reg" and src.reg >= 0:
                self._extend_reg(src.reg, width, signed, dst.reg)
                return True
            if src.kind == "mem":
                return self._subword_load_value(src, pc, regs, width,
                                                signed, dst.reg)
            return False

        # --- xchg: three-move swap (lock prefix already folded away —
        # atomicity is meaningless to a single-context replay) ---
        if m in ("xchg", "xchgl", "xchgq") and len(ops) == 2:
            a_op, b_op = ops
            if all(o.kind == "reg" and o.reg >= 0 and abs(o.width) >= 32
                   for o in ops):
                # xchg writes no flags: scratch must stay off T1/T2/TCMP
                self._emit(U.ADD, T6, a_op.reg, ZERO)
                self._emit(U.ADD, a_op.reg, b_op.reg, ZERO)
                self._emit(U.ADD, b_op.reg, T6, ZERO)
                return True
            mem = next((o for o in ops if o.kind == "mem"), None)
            reg = next((o for o in ops if o.kind == "reg" and o.reg >= 0
                        and abs(o.width) >= 32), None)
            if mem is not None and reg is not None \
                    and self._mem_width(inst, mem) >= 4:
                a = self._addr_uops(mem, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T6, a[0], ZERO, a[1])
                self._emit(U.STORE, 0, a[0], reg.reg, a[1])
                self._emit(U.ADD, reg.reg, T6, ZERO)
                return True
            return False

        # --- 32-bit rotates: two shifts + OR.  64-bit rotates cross the
        # uint32 projection boundary (high bits rotate into the tracked
        # low word) and demote; the count is masked &31 exactly as x86
        # masks 32-bit rotate counts, and count==0 degenerates to
        # r | (r << 32&31) == r, so no special case is needed ---
        if m in ("rol", "roll", "ror", "rorl"):
            if len(ops) == 1:
                ops = [Operand("imm", imm=1)] + ops
            if len(ops) != 2:
                return False
            src, dst = ops
            if dst.kind != "reg" or dst.reg < 0 or abs(dst.width) != 32:
                return False
            if src.kind == "imm":
                self._emit(U.LUI, T3, ZERO, ZERO, src.imm & 31)
            elif src.kind == "reg" and src.reg == RCX_ARCH:
                self._emit(U.ANDI, T3, RCX_ARCH, ZERO, 31)
            else:
                return False
            self._emit(U.LUI, T4, ZERO, ZERO, 32)
            self._emit(U.SUB, T4, T4, T3)
            right_first = m.startswith("ror")
            self._emit(U.SRL if right_first else U.SLL, T5, dst.reg, T3)
            self._emit(U.SLL if right_first else U.SRL, T6, dst.reg, T4)
            self._emit(U.OR, dst.reg, T5, T6)
            return True

        # --- cmov: branch-free select (value-faithful under faults) ---
        if m.startswith("cmov"):
            base = m if m in _CMOV else m.rstrip("lqw")
            if base not in _CMOV or len(ops) != 2:
                return False
            src, dst = ops
            if dst.kind != "reg" or dst.reg < 0 or abs(dst.width) < 32:
                return False        # 16-bit cmov merges into dst[15:0]
            if src.kind == "reg" and src.reg >= 0:
                sreg = src.reg
            elif src.kind == "mem" and self._mem_width(inst, src) >= 4:
                a = self._addr_uops(src, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T5, a[0], ZERO, a[1])
                sreg = T5
            else:
                return False
            if self._cond_bool(_CMOV[base], T4) is None:
                return False
            # cmov preserves EFLAGS — T6/T7 scratch keeps a live flags_src
            # in T1/T2/TCMP intact for a later consumer
            self._emit(U.XOR, T6, dst.reg, sreg)
            self._emit(U.SUB, T7, ZERO, T4)        # 0 or all-ones
            self._emit(U.AND, T6, T6, T7)
            self._emit(U.XOR, dst.reg, dst.reg, T6)
            return True

        # --- byte/halfword compare & test: sign-extended operands preserve
        # both the signed and the unsigned ordering of the sub-word domain
        sub_cmp_w = None
        if m in ("cmpb", "cmpw"):
            sub_cmp_w = 1 if m == "cmpb" else 2
        elif m == "cmp" and len(ops) == 2:
            # AT&T spells byte compares "cmp %cl,(%rax)" when a register
            # operand implies the size — the hot byte-match loops of
            # compression workloads are exactly this form
            ws = {abs(o.width) for o in ops
                  if o.kind == "reg" and o.reg >= 0 and o.width}
            if ws and max(ws) <= 16:
                sub_cmp_w = 1 if max(ws) == 8 else 2
        if sub_cmp_w is not None:
            if len(ops) != 2:
                return False
            width = sub_cmp_w
            msk = 0xFF if width == 1 else 0xFFFF
            sbit = msk ^ (msk >> 1)
            src, dst = ops                        # flags of dst - src
            def _sext_operand(o, treg):
                if o.kind == "imm":
                    v = o.imm & msk
                    v = v - (msk + 1) if v & sbit else v
                    return self._const(v & M32, treg)
                if o.kind == "reg" and o.reg >= 0 and o.width > 0:
                    self._extend_reg(o.reg, width, True, treg)
                    return treg
                if o.kind == "mem" and self._subword_load_value(
                        o, pc, regs, width, True, treg):
                    return treg
                return None
            breg = _sext_operand(src, TCMP)
            areg = _sext_operand(dst, T2) if breg is not None else None
            if areg is None:
                return False
            # kind "cmpb" ≠ "cmp": SF of a sub-word cmp is bit 7/15 of the
            # *wrapped* sub-word difference, which the sext-operand SUB does
            # not reproduce on overflow — sign-consumers must demote
            self.flags_src = ("cmpb", areg, breg)
            return True
        if m in ("testb", "testw"):
            if len(ops) != 2:
                return False
            width = 1 if m == "testb" else 2
            a, b = ops
            if any(o.kind == "reg" and o.reg >= 0 and o.width < 0
                   for o in ops):
                return False                      # %ah-family
            if a.kind == "imm" and b.kind == "reg" and b.reg >= 0:
                self._emit(U.ANDI, T2, b.reg, ZERO,
                           a.imm & (0xFF if width == 1 else 0xFFFF))
            elif a.kind == "reg" and a.reg >= 0 and b.kind == "reg" \
                    and b.reg >= 0:
                self._emit(U.AND, T2, a.reg, b.reg)
                self._emit(U.ANDI, T2, T2, ZERO,
                           0xFF if width == 1 else 0xFFFF)
            else:
                return False
            # sign-extend the sub-word result so SF (js/jns) is faithful
            self._extend_reg(T2, width, True, T2)
            self.flags_src = ("res", T2)
            return True

        # --- lea: pure address arithmetic, NO remap (real addresses) ---
        if m == "lea" or m == "leaq":
            src, dst = ops if len(ops) == 2 else (None, None)
            if dst is None or dst.kind != "reg" or dst.reg < 0 \
                    or src.kind != "mem" or src.base == -3:
                return False
            if src.rip_rel:
                self._emit(U.LUI, dst.reg, ZERO, ZERO, src.disp)
                return True
            t = T0
            if src.index >= 0:
                if src.scale > 1:
                    sh = self._const(src.scale.bit_length() - 1, T3)
                    self._emit(U.SLL, t, src.index, sh)
                else:
                    self._emit(U.ADD, t, src.index, ZERO)
                if src.base >= 0:
                    self._emit(U.ADD, t, t, src.base)
                self._emit(U.ADDI, dst.reg, t, ZERO, src.disp)
            elif src.base >= 0:
                self._emit(U.ADDI, dst.reg, src.base, ZERO, src.disp)
            else:
                self._emit(U.LUI, dst.reg, ZERO, ZERO, src.disp)
            return True

        # --- two-operand ALU ---
        stem = stem_of(m, _ALU2, _SHIFTS) or m
        if m in _ALU2 or stem in _ALU2:
            opcode = _ALU2.get(m, _ALU2.get(stem))
            rws = [abs(o.width) for o in ops
                   if o.kind == "reg" and o.reg >= 0 and o.width]
            sfx = m[-1] if m not in _ALU2 else ""   # "subb" → 'b'; "sub" → ""
            sub_w = 0
            if sfx == "b" or (rws and min(rws) == 8):
                sub_w = 1
            elif sfx == "w" or (rws and min(rws) == 16):
                sub_w = 2
            if sub_w and len(ops) == 2:
                if any(o.kind == "reg" and o.reg >= 0 and o.width < 0
                       for o in ops):
                    return False              # %ah-family
                return self._subword_alu(opcode, ops[0], ops[1], pc, regs,
                                         sub_w)
            if len(ops) == 3 and m.startswith("imul"):
                # imul $imm, src, dst
                immv, src, dst = ops
                if immv.kind != "imm" or src.kind != "reg" or src.reg < 0 \
                        or dst.kind != "reg" or dst.reg < 0:
                    return False
                c = self._const(immv.imm, T1)
                self._emit(U.MUL, dst.reg, src.reg, c)
                self.flags_src = ("res", dst.reg)
                return True
            if len(ops) != 2:
                return False
            src, dst = ops
            if dst.kind == "reg" and dst.reg >= 0:
                if src.kind == "imm":
                    imm_map = {U.ADD: U.ADDI, U.AND: U.ANDI, U.OR: U.ORI,
                               U.XOR: U.XORI}
                    if opcode in imm_map:
                        self._emit(imm_map[opcode], dst.reg, dst.reg, ZERO,
                                   src.imm)
                    elif opcode == U.SUB:
                        self._emit(U.ADDI, dst.reg, dst.reg, ZERO,
                                   (-src.imm) & M32)
                    else:
                        c = self._const(src.imm, T1)
                        self._emit(opcode, dst.reg, dst.reg, c)
                elif src.kind == "reg" and src.reg >= 0:
                    if (opcode == U.MUL
                            and self.FP_BASE is not None
                            and any(abs(o.width) == 64 for o in ops
                                    if o.kind == "reg")
                            and int(regs[dst.reg]) <= M32
                            and int(regs[src.reg]) <= M32):
                        # 64-bit imul whose true operands fit u32: also
                        # stash the high product half — the adjacent
                        # `shr $c, reg` (c >= 32) of the divide-by-
                        # constant idiom consumes it (peephole below)
                        self._emit(U.MULHU, HSH, dst.reg, src.reg)
                        self._hi_shadow = (dst.reg, i)
                    self._emit(opcode, dst.reg, dst.reg, src.reg)
                elif src.kind == "mem":
                    if self._mem_width(inst, src) < 4:
                        return False
                    a = self._addr_uops(src, pc, T0)
                    if a is None:
                        return False
                    self._emit(U.LOAD, T1, a[0], ZERO, a[1])
                    self._emit(opcode, dst.reg, dst.reg, T1)
                else:
                    return False
                self.flags_src = ("res", dst.reg)
                return True
            if dst.kind == "mem":                 # RMW on memory
                if self._mem_width(inst, dst) < 4:
                    return False
                a = self._addr_uops(dst, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T1, a[0], ZERO, a[1])
                if src.kind == "imm":
                    c = self._const(src.imm, T2)
                    self._emit(opcode, T1, T1, c)
                elif src.kind == "reg" and src.reg >= 0:
                    self._emit(opcode, T1, T1, src.reg)
                else:
                    return False
                self._emit(U.STORE, 0, a[0], T1, a[1])
                self.flags_src = ("res", T1)
                return True
            return False

        # --- shifts ---
        if stem in _SHIFTS or m in _SHIFTS:
            opcode = _SHIFTS.get(m, _SHIFTS.get(stem))
            if len(ops) == 1:                     # implicit shift by 1
                ops = [Operand("imm", imm=1)] + ops
            if len(ops) != 2:
                return False
            src, dst = ops
            if dst.kind != "reg" or dst.reg < 0:
                return False
            if src.kind == "imm" and src.imm >= 32 and opcode == U.SRL \
                    and self._hi_shadow == (dst.reg, i - 1):
                # wide shift of the imul-peephole product: the result is
                # the HIGH half shifted by c-32 (true when the quotient
                # fits u32 — the self-check verifies exactly that)
                c = self._const((src.imm - 32) & 31, T1)
                self._emit(U.SRL, dst.reg, HSH, c)
                self.flags_src = ("res", dst.reg)
                return True
            if src.kind == "imm":
                c = self._const(src.imm & 31, T1)
                self._emit(opcode, dst.reg, dst.reg, c)
            elif src.kind == "reg" and src.reg == 1:   # %cl
                self._emit(opcode, dst.reg, dst.reg, 1)
            else:
                return False
            self.flags_src = ("res", dst.reg)
            return True

        # --- inc/dec/neg/not ---
        if m in ("inc", "incl", "incq"):
            d = ops[0]
            if d.kind != "reg" or d.reg < 0:
                return False
            self._emit(U.ADDI, d.reg, d.reg, ZERO, 1)
            self.flags_src = ("res", d.reg)
            return True
        if m in ("dec", "decl", "decq"):
            d = ops[0]
            if d.kind != "reg" or d.reg < 0:
                return False
            self._emit(U.ADDI, d.reg, d.reg, ZERO, M32)
            self.flags_src = ("res", d.reg)
            return True
        if m in ("neg", "negl", "negq"):
            d = ops[0]
            if d.kind != "reg" or d.reg < 0:
                return False
            self._emit(U.SUB, d.reg, ZERO, d.reg)
            self.flags_src = ("res", d.reg)
            return True
        if m in ("not", "notl", "notq"):
            d = ops[0]
            if d.kind != "reg" or d.reg < 0:
                return False
            self._emit(U.XORI, d.reg, d.reg, ZERO, M32)
            return True

        # --- cmp/test: record the flag source for the following jcc ---
        if m.startswith("cmp"):
            if len(ops) != 2:
                return False
            src, dst = ops                        # flags of dst - src
            breg = None
            if src.kind == "imm":
                breg = self._const(src.imm, TCMP)
            elif src.kind == "reg" and src.reg >= 0:
                breg = src.reg
            areg = None
            if dst.kind == "reg" and dst.reg >= 0:
                areg = dst.reg
            elif dst.kind == "mem" and self._mem_width(inst, dst) >= 4:
                a = self._addr_uops(dst, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T2, a[0], ZERO, a[1])
                areg = T2
            if areg is None or breg is None:
                return False
            self.flags_src = ("cmp", areg, breg)
            return True
        if m.startswith("test"):
            if len(ops) != 2:
                return False
            if any(o.kind == "reg" and o.reg >= 0 and o.width < 0
                   for o in ops):
                return False                      # %ah-family
            a, b = ops
            widths = [abs(o.width) // 8 for o in ops
                      if o.kind == "reg" and o.reg >= 0 and o.width]
            w = min(widths) if widths else 4
            if w < 4:
                # objdump spells sub-word tests either "testb $1,…" or
                # plain "test $1,%sil" — route both through the sub-word
                # handling (mask, then sign-extend so SF is faithful)
                msk = 0xFF if w == 1 else 0xFFFF
                if a.kind == "imm" and b.kind == "reg" and b.reg >= 0:
                    self._emit(U.ANDI, T2, b.reg, ZERO, a.imm & msk)
                elif a.kind == "reg" and a.reg >= 0 \
                        and b.kind == "reg" and b.reg >= 0:
                    self._emit(U.AND, T2, a.reg, b.reg)
                    self._emit(U.ANDI, T2, T2, ZERO, msk)
                else:
                    return False
                self._extend_reg(T2, w, True, T2)
                self.flags_src = ("res", T2)
                return True
            if a.kind == "imm" and b.kind == "reg" and b.reg >= 0:
                self._emit(U.ANDI, T2, b.reg, ZERO, a.imm & M32)
                self.flags_src = ("res", T2)
                return True
            if any(o.kind != "reg" or o.reg < 0 for o in ops):
                return False
            if a.reg == b.reg:
                self.flags_src = ("res", a.reg)
            else:
                self._emit(U.AND, T2, a.reg, b.reg)
                self.flags_src = ("res", T2)
            return True

        # --- stack ops ---
        if m in ("push", "pushq"):
            s = ops[0]
            if s.kind != "reg" or s.reg < 0:
                return False
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            self._emit(U.ADDI, 4, 4, ZERO, (-8) & M32)       # rsp -= 8
            self._emit(U.STORE, 0, 4, s.reg, delta)
            return True
        if m in ("pop", "popq"):
            d = ops[0]
            if d.kind != "reg" or d.reg < 0:
                return False
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            self._emit(U.LOAD, d.reg, 4, ZERO, delta)
            self._emit(U.ADDI, 4, 4, ZERO, 8)
            return True
        if m in ("call", "callq"):
            # direct or indirect: the only architectural effects are the
            # return-address push and rip (which follows the captured
            # stream); an indirect target read has no register effect, so
            # both forms lift identically — demoting indirect calls would
            # drop the push and desynchronize the later ret's stack slot
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            ra = self._const((pc + inst.length) & M32, T1)
            self._emit(U.ADDI, 4, 4, ZERO, (-8) & M32)
            self._emit(U.STORE, 0, 4, ra, delta)
            return True
        if m in ("ret", "retq"):
            cl = self.pc_cluster.get(pc)
            if cl is None:
                return False
            delta = self._remap_const(cl)
            # golden-sim guard: the stack slot must hold the captured
            # return target (it won't when the RA was pushed by an op that
            # demoted to opaque, whose memory effects are unrecoverable) —
            # else the integrity branch below would diverge on the golden
            # replay itself
            addr = (int(self.reg[4]) + delta) & M32
            if (addr & 3) or (addr >> 2) >= self.mem_words or \
                    int(self.mem[addr >> 2]) != (next_pc & M32):
                return False
            self._emit(U.LOAD, T1, 4, ZERO, delta)
            self._emit(U.ADDI, 4, 4, ZERO, 8)
            # return-address integrity check: corrupting the stack slot is a
            # control-flow divergence (the captured stream went to next_pc)
            ra = self._const(next_pc & M32, T2)
            self._emit(U.BEQ, 0, T1, T2, taken=1)
            self.stats.branches += 1
            self.stats.branches_lifted += 1
            return True

        # --- unconditional jump: control flow follows the stream (indirect
        # targets included — the captured next_pc is the truth either way,
        # and a jmp has no register or memory effect to model) ---
        if m in ("jmp", "jmpq"):
            return True

        # --- conditional branches ---
        if m in _JCC_SIGNED or m in _JCC_UNSIGNED:
            self.stats.branches += 1
            taken = 1 if next_pc != (pc + inst.length) else 0
            ok = self._lift_jcc(m, taken)
            if ok:
                self.stats.branches_lifted += 1
            else:
                self.stats.branches_dropped += 1
            return True                           # never demote to opaque
        if m.startswith("j"):
            self.stats.branches += 1
            self.stats.branches_dropped += 1
            return True

        if m in ("cltd", "cdq"):
            # edx = sign-fill of eax: SRA by 31 (cdq sets no flags, so T6)
            c31 = self._const(31, T6)
            self._emit(U.SRA, 2, 0, c31)
            return True

        # --- 32-bit division: edx:eax / src → eax=quot, edx=rem.  The
        # 32-bit projection computes eax/src directly; the edx:eax
        # precondition (cltd sign-fill / xor-zeroed) is validated by the
        # register self-check — a genuinely 64-bit dividend demotes. ---
        if m in ("idiv", "idivl", "div", "divl"):
            if len(ops) != 1:
                return False
            o = ops[0]
            signed = m.startswith("i")
            if o.kind == "reg" and o.reg >= 0 and abs(o.width) == 32:
                breg = o.reg
            elif o.kind == "mem" and self._mem_width(inst, o) >= 4 \
                    and not m.endswith(("q",)):
                a = self._addr_uops(o, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, T6, a[0], ZERO, a[1])
                breg = T6
            else:
                return False
            q_op, r_op = (U.DIV, U.REM) if signed else (U.DIVU, U.REMU)
            self._emit(r_op, T5, 0, breg)      # remainder from original rax
            self._emit(q_op, 0, 0, breg)       # rax = quotient
            self._emit(U.ADD, 2, T5, ZERO)     # rdx = remainder
            return True

        if m in ("nop", "nopw", "nopl", "endbr64", "cqo", "cqto"):
            # cqo writes rdx from rax bit 63 — outside the 32-bit
            # projection: demote unless rdx happens to match (self-check);
            # nops are free
            return m.startswith(("nop", "endbr"))

        return False

    # -- scalar-SSE float lift (VERDICT r3 #6) ---------------------------
    #
    # The FP bank is phys FX0+k = xmm{k}'s low 32 bits; arithmetic maps
    # 1:1 onto the FADD/FSUB/FMUL/FDIV µops (f32, FTZ, canonical NaN —
    # isa/uops.py), so an FP-bank REGFILE fault propagates through real
    # float dataflow on the device.  comiss/min/max use the monotone
    # integer-key trick: key = bits ^ (sra(bits,31) | 0x80000000) maps
    # IEEE-754 order onto unsigned integer order, so the existing SLTU
    # branch machinery consumes float compares unchanged (±0 and NaN
    # edge cases self-check at lift time and demote).

    def _fx(self, o: Operand) -> "int | None":
        if o.kind == "xmm" and 0 <= o.reg < 16 and abs(o.width) <= 128:
            return self.FP_BASE + o.reg
        return None

    def _fp_key(self, src_reg: int, dst_reg: int, tmp: int) -> int:
        """Monotone integer key of an f32 bit pattern → dst_reg."""
        self._emit(U.ADDI, tmp, ZERO, ZERO, 31)
        self._emit(U.SRA, dst_reg, src_reg, tmp)
        self._emit(U.ORI, dst_reg, dst_reg, ZERO, 0x80000000)
        self._emit(U.XOR, dst_reg, src_reg, dst_reg)
        return dst_reg

    def _fp_operand(self, o: Operand, pc: int, tmp: int) -> "int | None":
        """Register holding the f32 operand's bits (xmm lane or a loaded
        memory word)."""
        fx = self._fx(o)
        if fx is not None:
            return fx
        if o.kind == "mem":
            a = self._addr_uops(o, pc, T0)
            if a is None:
                return None
            self._emit(U.LOAD, tmp, a[0], ZERO, a[1])
            return tmp
        return None

    def _lift_fp(self, m: str, ops: list, pc: int,
                 regs: np.ndarray) -> bool:
        alu = {"addss": U.FADD, "subss": U.FSUB,
               "mulss": U.FMUL, "divss": U.FDIV}
        if m in alu and len(ops) == 2:
            src, dst = ops
            d = self._fx(dst)
            if d is None:
                return False
            a = self._fp_operand(src, pc, FT0)
            if a is None:
                return False
            self._emit(alu[m], d, d, a)
            return True
        if m in ("movss", "movaps", "movapd", "movups", "movdqa",
                 "movdqu", "movd") and len(ops) == 2:
            src, dst = ops
            sfx, dfx = self._fx(src), self._fx(dst)
            if sfx is not None and dfx is not None:
                self._emit(U.ADD, dfx, sfx, ZERO)        # bit copy (lane 0)
                return True
            if dfx is not None and src.kind == "mem":
                a = self._addr_uops(src, pc, T0)
                if a is None:
                    return False
                self._emit(U.LOAD, dfx, a[0], ZERO, a[1])
                return True
            if sfx is not None and dst.kind == "mem" and m == "movss":
                a = self._addr_uops(dst, pc, T0)
                if a is None:
                    return False
                self._emit(U.STORE, 0, a[0], sfx, a[1])
                return True
            # movd xmm↔GPR: the int/float boundary (bit-pattern move) —
            # severing it would erase FP-bank corruption exactly at the
            # program-output conversion
            if m == "movd":
                if sfx is not None and dst.kind == "reg" and dst.reg >= 0 \
                        and abs(dst.width) == 32:
                    self._emit(U.ADD, dst.reg, sfx, ZERO)
                    return True
                if dfx is not None and src.kind == "reg" and src.reg >= 0 \
                        and abs(src.width) == 32:
                    self._emit(U.ADD, dfx, src.reg, ZERO)
                    return True
            return False
        if m in ("pxor", "xorps", "xorpd") and len(ops) == 2:
            sfx, dfx = self._fx(ops[0]), self._fx(ops[1])
            if sfx is None or dfx is None:
                return False
            if sfx == dfx:
                self._emit(U.LUI, dfx, ZERO, ZERO, 0)    # zeroing idiom
            else:
                self._emit(U.XOR, dfx, dfx, sfx)
            return True
        if m in ("maxss", "minss") and len(ops) == 2:
            src, dst = ops
            d = self._fx(dst)
            if d is None:
                return False
            a = self._fp_operand(src, pc, FT0)
            if a is None:
                return False
            ka = self._fp_key(a, FT1, T6)
            kd = self._fp_key(d, T7, T6)
            # cond = (key_src > key_dst) for maxss, (key_src < key_dst)
            # for minss; x86 picks the SOURCE when the condition holds
            if m == "maxss":
                self._emit(U.SLTU, T6, kd, ka)
            else:
                self._emit(U.SLTU, T6, ka, kd)
            # branchless select: d ^= (d ^ a) & (-cond)
            self._emit(U.XOR, T7, d, a)
            self._emit(U.SUB, T6, ZERO, T6)
            self._emit(U.AND, T7, T7, T6)
            self._emit(U.XOR, d, d, T7)
            return True
        if m in ("comiss", "ucomiss") and len(ops) == 2:
            src, dst = ops                        # flags of dst ? src
            a = self._fp_operand(dst, pc, FT0)
            b = self._fp_operand(src, pc, FT1)
            if a is None or b is None or a == b:
                return False
            ka = self._fp_key(a, T1, T6)
            kb = self._fp_key(b, TCMP, T6)
            self.flags_src = ("fcmp", ka, kb)
            return True
        return False

    def _branch_cond(self, kind: str, a: int, b: int) -> tuple | None:
        """(opcode, src1, src2, extra_uops_emitted) for a signed cond."""
        table = {"eq": (U.BEQ, a, b), "ne": (U.BNE, a, b),
                 "lt": (U.BLT, a, b), "ge": (U.BGE, a, b),
                 "swap_lt": (U.BLT, b, a), "swap_ge": (U.BGE, b, a)}
        return table.get(kind)

    def _lift_jcc(self, m: str, taken: int) -> bool:
        if self.flags_src is None:
            return False
        kind = self.flags_src[0]
        if kind == "fcmp":
            # float keys order like unsigned ints: only the unordered-
            # style consumers compilers emit after comiss are valid
            if m in _JCC_SIGNED and _JCC_SIGNED[m][0] not in ("eq", "ne"):
                return False
            kind = "cmp"
        if kind in ("cmp", "cmpb"):
            _, a, b = self.flags_src
        else:                                     # result vs zero
            a, b = self.flags_src[1], ZERO
        if m in _JCC_SIGNED:
            cond = _JCC_SIGNED[m][0]
            if cond == "sign":
                br = (U.BLT, a, ZERO) if kind == "res" else None
            elif cond == "nsign":
                br = (U.BGE, a, ZERO) if kind == "res" else None
            else:
                br = self._branch_cond(cond, a, b)
            if br is None:
                return False
            op, s1, s2 = br
            if not self._branch_selfcheck(op, s1, s2, taken):
                return False
            self._emit(op, 0, s1, s2, taken=taken)
            return True
        # unsigned via SLTU
        mode = _JCC_UNSIGNED[m]
        if mode is False:                         # jb: a < b
            self._emit(U.SLTU, T3, a, b)
            br = (U.BNE, T3, ZERO)
        elif mode is True:                        # jae: !(a < b)
            self._emit(U.SLTU, T3, a, b)
            br = (U.BEQ, T3, ZERO)
        elif mode == "swap_b":                    # ja: b < a
            self._emit(U.SLTU, T3, b, a)
            br = (U.BNE, T3, ZERO)
        else:                                     # jbe: !(b < a)
            self._emit(U.SLTU, T3, b, a)
            br = (U.BEQ, T3, ZERO)
        op, s1, s2 = br
        if not self._branch_selfcheck(op, s1, s2, taken):
            # roll back the SLTU we emitted
            self._rollback(len(self.opcode) - 1)
            return False
        self._emit(op, 0, s1, s2, taken=taken)
        return True

    def _branch_selfcheck(self, op: int, s1: int, s2: int,
                          taken: int) -> bool:
        """The lifted condition under the golden sim must equal the captured
        direction, or the golden replay itself would 'diverge'."""
        a = int(self.reg[s1]) & M32
        b = int(self.reg[s2]) & M32
        if op == U.BEQ:
            cond = a == b
        elif op == U.BNE:
            cond = a != b
        elif op == U.BLT:
            cond = self._s32(a) < self._s32(b)
        else:
            cond = self._s32(a) >= self._s32(b)
        return int(cond) == taken

    def _rollback(self, mark: int) -> None:
        del self.opcode[mark:]
        del self.dst[mark:]
        del self.src1[mark:]
        del self.src2[mark:]
        del self.imm[mark:]
        del self.taken[mark:]
        del self.mem_cluster[mark:]

    # -- datapath-width hooks (ingest/lift64.py overrides all four) --------

    @staticmethod
    def _xmm_lanes(row: np.ndarray) -> np.ndarray | None:
        """16 captured xmm low lanes from a full SHTRACE3 step row."""
        if row.shape[0] < 26:
            return None
        packed = row[18:26]
        out = np.empty(16, np.uint64)
        out[0::2] = packed & np.uint64(M32)
        out[1::2] = packed >> np.uint64(32)
        return out

    def _seed_regs(self, step0: np.ndarray) -> None:
        self.reg[:] = 0
        self.reg[:N_GPR] = step0[:N_GPR] & np.uint64(M32)
        lanes = self._xmm_lanes(step0)
        self._has_xmm = lanes is not None
        if self.FP_BASE is not None and lanes is not None:
            self.reg[self.FP_BASE:self.FP_BASE + 16] = lanes

    def _regs_match(self, next_full: np.ndarray) -> bool:
        """Post-macro-op self-check against the captured register file —
        the lift's correctness authority (full 64-bit in lift64).  With an
        SHTRACE3 capture the FP bank is held to the same standard: every
        xmm low lane must match, every macro-op."""
        if not (self.reg[:N_GPR] == (next_full[:N_GPR]
                                     & np.uint64(M32))).all():
            return False
        lanes = self._xmm_lanes(next_full)
        if self.FP_BASE is not None and lanes is not None:
            return bool(
                (self.reg[self.FP_BASE:self.FP_BASE + 16] == lanes).all())
        return True

    def _resync_regs(self, next_full: np.ndarray) -> None:
        """Opaque demotion: overwrite every mismatched register with its
        captured value.  Each emitted LUI's µop index is recorded — a
        fault whose struck register meets a resync before its next read
        is provably severed in replay while silicon keeps it, and the
        host-diff harness escalates exactly those coordinates to the
        whole-program emulator oracle (ingest/hostdiff.py)."""
        want = next_full[:N_GPR] & np.uint64(M32)
        changed = np.nonzero(self.reg[:N_GPR] != want)[0]
        for r in changed:
            self._emit_resync(int(r), int(want[r]))
        lanes = self._xmm_lanes(next_full)
        if self.FP_BASE is not None and lanes is not None:
            fb = self.FP_BASE
            for k in np.nonzero(self.reg[fb:fb + 16] != lanes)[0]:
                self._emit_resync(fb + int(k), int(lanes[k]))

    # x86-64 syscall convention: number in rax, args rdi/rsi/rdx/r10/r8/r9
    # (canonical encoding indices)
    _SYSCALL_READS = [0, 2, 6, 7, 8, 9, 10]
    # implicit register reads by mnemonic family (canonical indices:
    # rax=0 rcx=1 rdx=2 rsp=4 rbp=5 rsi=6 rdi=7) — operand lists don't
    # carry these (objdump prints 'rep movsb' with no operands)
    _IMPLICIT_READS = {
        "movs": [1, 6, 7], "stos": [0, 1, 7], "lods": [0, 1, 6],
        "scas": [0, 1, 7], "cmps": [1, 6, 7],
        "push": [4], "pop": [4], "call": [4], "ret": [4],
        "leave": [4, 5], "enter": [4, 5],
        "div": [0, 2], "idiv": [0, 2], "mul": [0, 2],
        # sign-extend family: Intel spellings AND the AT&T ones objdump
        # actually prints (cwtd=cwd, cltd=cdq, cqto=cqo) — the lifter's
        # own decode matches the AT&T forms
        "cwd": [0], "cdq": [0], "cqo": [0],
        "cwtd": [0], "cltd": [0], "cqto": [0],
    }

    def _demoted_read_set(self, inst: "Inst | None") -> list[int]:
        """Arch registers a demoted instruction READS on silicon: every
        reg operand (conservatively incl. the dest — AT&T RMW), every mem
        base/index, xmm regs as 16+k, plus implicit families (string ops
        read rsi/rdi/rcx with no operand list; push/pop read rsp; div
        reads rax/rdx).  Undecoded bytes return [-1] (wildcard)."""
        if inst is None:
            return [-1]
        parts = inst.mnemonic.split()
        m0 = parts[0]
        if m0 == "syscall":
            return list(self._SYSCALL_READS)
        reads: set[int] = set()
        # 'rep movsb' → family 'movs'; bare 'movsb'/'stosq' too; one-op
        # div/mul ('divq (%rax)') keyed by stem.  String families apply
        # only to the real string forms (no operands, or %ds:/%es:
        # segment-printed ones) — 'movsd'/'movslq' also strip to 'movs'
        # but are ordinary 2-operand moves.
        STRING_FAMS = ("movs", "stos", "lods", "scas", "cmps")
        stringish = (not inst.operands
                     or any(getattr(o, "seg", "") for o in inst.operands))
        for tok in parts[:2]:
            # strip at most ONE trailing size-suffix letter ('pushq',
            # 'stosb'); rstrip would eat into the mnemonic itself
            # ('call'→'ca', 'mul'→'mu', 'cwd'/'cdq'→'c') and orphan those
            # implicit-read entries
            stem = tok if tok in self._IMPLICIT_READS else (
                tok[:-1] if tok and tok[-1] in "bwldq" else tok)
            if stem in self._IMPLICIT_READS \
                    and (stem not in STRING_FAMS or stringish):
                reads.update(self._IMPLICIT_READS[stem])
        for o in inst.operands:
            if o.kind == "reg" and 0 <= o.reg < N_GPR:
                reads.add(int(o.reg))
            elif o.kind == "xmm" and 0 <= o.reg < 16:
                reads.add(16 + int(o.reg))
            if o.base >= 0:
                reads.add(int(o.base))
            if o.index >= 0:
                reads.add(int(o.index))
        return sorted(reads)

    def _emit_resync(self, phys: int, value: int) -> None:
        """A demotion-resync LUI, recorded for the severed-fault test —
        every resync emission MUST go through here (ingest/hostdiff.py
        _resync_severed depends on the record being complete)."""
        self.resync_uops.append(len(self.opcode))
        self._emit(U.LUI, phys, ZERO, ZERO, value & M32)

    def _final_reg_expect(self, vals: np.ndarray) -> list:
        return [int(x) for x in (vals[:N_GPR] & np.uint64(M32))]

    # -- main loop ----------------------------------------------------------

    def run(self) -> tuple[Trace, dict]:
        self.build_memory_map()
        steps = self.nt.steps
        n_macro = len(steps) - 1
        # initial register file: captured GPRs (width per mode), specials 0
        self._seed_regs(steps[0])
        init_reg = self.reg.astype(np.uint32).copy()
        init_mem = self.mem.copy()

        for i in range(n_macro):
            if self.max_uops and len(self.opcode) >= self.max_uops:
                n_macro = i
                break
            pc = int(steps[i][16])
            next_pc = int(steps[i + 1][16])
            next_full = steps[i + 1]
            next_regs = next_full[:N_GPR] & np.uint64(M32)
            inst = self.insts.get(pc)
            self.uop_start.append(len(self.opcode))
            self.stats.macro_ops += 1
            mark = len(self.opcode)
            reg_snap = self.reg.copy()
            mem_before = None
            flags_before = self.flags_src
            ok = False
            if inst is not None:
                mem_before = self.mem.copy()
                ok = self._lift_one(i, inst, steps[i], next_regs, next_pc)
                if ok:
                    ok = self._regs_match(next_full)
            if ok:
                self.stats.lifted += 1
            else:
                # opaque demotion: rollback, then resync every changed GPR
                self._rollback(mark)
                self.reg = reg_snap
                if mem_before is not None:
                    self.mem = mem_before
                self.flags_src = flags_before
                self._resync_regs(next_full)
                self.stats.opaque += 1
                if inst is None or inst.mnemonic == "syscall":
                    # unknown effects may include vector/k state
                    self._vec_reset()
                mn = inst.mnemonic if inst else f"@{pc:x}"
                self.stats.opaque_mnemonics[mn] = \
                    self.stats.opaque_mnemonics.get(mn, 0) + 1
                self.demoted_reads.append(
                    (i, self._demoted_read_set(inst)))

        self.stats.uops = len(self.opcode)
        if not self.opcode:                       # degenerate: empty window
            self._emit(U.NOP, 0, 0, 0)
        trace = Trace(
            opcode=np.asarray(self.opcode, dtype=np.int32),
            dst=np.asarray(self.dst, dtype=np.int32),
            src1=np.asarray(self.src1, dtype=np.int32),
            src2=np.asarray(self.src2, dtype=np.int32),
            imm=np.asarray(self.imm, dtype=np.uint32),
            taken=np.asarray(self.taken, dtype=np.int32),
            init_reg=init_reg,
            init_mem=init_mem,
        )
        trace.validate()
        meta = {
            "source": "nativetrace",
            "begin": self.nt.begin,
            "end": self.nt.end,
            "macro_ops": n_macro,
            "uop_start": [int(x) for x in self.uop_start],
            "final_reg_expect": self._final_reg_expect(steps[n_macro]),
            "clusters": [tuple(int(v) for v in c) for c in self.clusters],
            "mem_cluster": [int(x) for x in self.mem_cluster],
            "resync_uops": [int(x) for x in self.resync_uops],
            "demoted_reads": [(int(s), [int(r) for r in rs])
                              for s, rs in self.demoted_reads],
            "map_regions": self.map_regions(),
            "stats": self.stats.to_dict(),
            "nphys": int(self.reg.shape[0]),
            "fp_bank": self.FP_BASE,
            "arch_regs": GPR_NAMES_64,
        }
        return trace, meta


def lift(trace_path: str, binary: str, max_uops: int | None = None,
         nt: NativeTrace | None = None,
         insts: "dict[int, Inst] | None" = None) -> tuple[Trace, dict]:
    """nativetrace capture + binary → (Trace, metadata).

    ``nt``/``insts`` accept pre-parsed inputs so callers that also scan the
    raw capture (e.g. hostdiff's output-event pass) parse once."""
    if nt is None:
        nt = read_nativetrace(trace_path)
    if insts is None:
        insts = static_decode(binary)
    try:
        from shrewd_tpu.ingest.emu import elf_regions
        elf_regs = elf_regions(binary)
    except Exception:  # noqa: BLE001 — crash model degrades, lift survives
        elf_regs = []
    return Lifter(nt, insts, max_uops=max_uops, elf_regs=elf_regs).run()
