"""Snapshot-seeded x86-64 subset emulator: checkpoint → synthetic capture.

Checkpoint restore needs an instruction stream to rebuild a replay window
(SURVEY §5.4: checkpoints are architectural-only — the reference restores
arch state and *runs forward*, ``src/cpu/o3/cpu.cc:706-799``).  A live
ptrace capture (tools/nativetrace.cc) needs the program running on this
host at the right marker; a checkpoint mid-run has no such luxury.  This
module plays the host CPU's role instead: a 64-bit x86 subset interpreter
seeded from the ``ArchSnapshot`` (regs + memory image + pc) that emits the
same per-step record stream the ptrace tracer produces, so the *unchanged*
capture-based lifter (ingest/lift.py) consumes it.

The duplication is deliberate and load-bearing: the lifter re-simulates
every macro-op in its own 32-bit µop semantics and demotes on mismatch, so
running it over this emulator's stream is a differential test between two
independent implementations — a bug in either shows up as opaque demotions
(visible in LiftStats), not silent corruption.  On workloads with a live
capture available, ``tests/test_emu.py`` additionally pins this emulator's
step stream bit-for-bit against the real ptrace capture.

Width semantics follow the ISA: 8/16-bit destination writes merge, 32-bit
zero-extend to 64, 64-bit overwrite.  Flags are kept lazily (source op +
operands) and materialized per condition code.  Anything outside the
supported subset (syscalls included) ends the window — the window-boundary
analog of the tracer's end marker.

Reference anchors: restore-then-rewarm (``src/cpu/o3/cpu.cc:706-799``),
the CheckerCPU lockstep-interpreter pattern (``src/cpu/checker/cpu.hh``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from shrewd_tpu.ingest.lift import (Inst, NativeTrace, Operand, _CMOV,
                                    static_decode)

M8, M16, M32, M64 = 0xFF, 0xFFFF, 0xFFFFFFFF, 0xFFFFFFFFFFFFFFFF
RAX, RCX, RDX, RBX, RSP, RBP, RSI, RDI = range(8)

_ALU = {"add", "sub", "and", "or", "xor", "imul"}
_SHIFT = {"shl": "shl", "sal": "shl", "shr": "shr", "sar": "sar"}

_JCC = {"je": "e", "jz": "e", "jne": "ne", "jnz": "ne",
        "jb": "b", "jnae": "b", "jae": "ae", "jnb": "ae",
        "ja": "a", "jnbe": "a", "jbe": "be", "jna": "be",
        "jl": "l", "jnge": "l", "jge": "ge", "jnl": "ge",
        "jg": "g", "jnle": "g", "jle": "le", "jng": "le",
        "js": "s", "jns": "ns"}

# _CMOV maps cmov* → the lifter's condition vocabulary; translate to ours
_LIFT_COND = {"eq": "e", "ne": "ne", "lt": "l", "ge": "ge",
              "swap_lt": "g", "swap_ge": "le", "sign": "s", "nsign": "ns",
              "ub": "b", "uae": "ae", "ua": "a", "ube": "be"}


class StopEmu(Exception):
    """Window boundary: unsupported instruction / memory miss / syscall."""


class Region:
    def __init__(self, vaddr: int, data: bytes):
        self.vaddr = vaddr
        self.buf = bytearray(data)

    def contains(self, addr: int, size: int) -> bool:
        return self.vaddr <= addr and addr + size <= self.vaddr + len(self.buf)


class EmuResult(NamedTuple):
    nt: NativeTrace            # lifter-compatible synthetic capture
    steps: int
    stop_reason: str
    stop_pc: int


class Emulator:
    def __init__(self, insts: dict[int, Inst], regs: np.ndarray,
                 regions: list[tuple[int, bytes]], pc: int):
        self.insts = insts
        self.reg = [int(x) & M64 for x in regs[:16]]
        self.regions = [Region(v, d) for v, d in regions]
        self.pc = int(pc)
        self.flags = ("res", 0, 64, 0)   # kind, operands..., width
        self.stop_reason = "max_steps"

    # -- memory ------------------------------------------------------------

    def _region(self, addr: int, size: int) -> Region:
        for r in self.regions:
            if r.contains(addr, size):
                return r
        raise StopEmu(f"mem miss {addr:#x}+{size}")

    def load(self, addr: int, size: int) -> int:
        r = self._region(addr, size)
        off = addr - r.vaddr
        return int.from_bytes(r.buf[off:off + size], "little")

    def store(self, addr: int, size: int, value: int) -> None:
        r = self._region(addr, size)
        off = addr - r.vaddr
        r.buf[off:off + size] = (value & ((1 << (8 * size)) - 1)).to_bytes(
            size, "little")

    # -- registers ---------------------------------------------------------

    def rget(self, op: Operand) -> int:
        v = self.reg[op.reg]
        w = op.width
        if w == 64:
            return v
        if w == 32:
            return v & M32
        if w == 16:
            return v & M16
        if w == 8:
            return v & M8
        if w == -8:                       # high byte (%ah family)
            return (v >> 8) & M8
        raise StopEmu(f"reg width {w}")

    def rset(self, op: Operand, value: int) -> None:
        old = self.reg[op.reg]
        w = op.width
        if w == 64:
            nv = value & M64
        elif w == 32:
            nv = value & M32              # zero-extends
        elif w == 16:
            nv = (old & ~M16) | (value & M16)
        elif w == 8:
            nv = (old & ~M8) | (value & M8)
        elif w == -8:
            nv = (old & ~(M8 << 8)) | ((value & M8) << 8)
        else:
            raise StopEmu(f"reg width {w}")
        self.reg[op.reg] = nv

    # -- operands ----------------------------------------------------------

    def ea(self, op: Operand) -> int:
        if op.base == -3:
            raise StopEmu("unparsed mem operand")
        if op.rip_rel:
            return op.disp & M64
        a = op.disp
        if op.base >= 0:
            a += self.reg[op.base]
        if op.index >= 0:
            a += self.reg[op.index] * op.scale
        return a & M64

    def _op_width(self, inst: Inst, default: int = 64) -> int:
        for o in inst.operands:
            if o.kind == "reg" and o.reg >= 0 and o.width:
                return abs(o.width)
        return {"b": 8, "w": 16, "l": 32, "q": 64}.get(
            inst.mnemonic[-1], default)

    def read(self, inst: Inst, op: Operand, width: int) -> int:
        if op.kind == "imm":
            return op.imm & ((1 << width) - 1)
        if op.kind == "reg":
            if op.reg < 0:
                raise StopEmu("non-GPR operand")
            return self.rget(op)
        if op.kind == "mem":
            return self.load(self.ea(op), width // 8)
        raise StopEmu("operand kind")

    def write(self, inst: Inst, op: Operand, width: int, value: int) -> None:
        if op.kind == "reg":
            if op.reg < 0:
                raise StopEmu("non-GPR operand")
            self.rset(op, value)
        elif op.kind == "mem":
            self.store(self.ea(op), width // 8, value)
        else:
            raise StopEmu("write to imm")

    # -- flags -------------------------------------------------------------

    def set_flags_sub(self, a: int, b: int, width: int) -> None:
        self.flags = ("sub", a, b, width)

    def set_flags_add(self, a: int, b: int, width: int) -> None:
        self.flags = ("add", a, b, width)

    def set_flags_res(self, v: int, width: int) -> None:
        self.flags = ("res", v, width, 0)

    def _fl(self) -> tuple[bool, bool, bool, bool]:
        """(ZF, SF, CF, OF) from the lazy flags record."""
        kind = self.flags[0]
        if kind == "res":
            _, v, w, _ = self.flags
            mask = (1 << w) - 1
            r = v & mask
            return r == 0, bool(r >> (w - 1)), False, False
        _, a, b, w = self.flags
        mask = (1 << w) - 1
        a &= mask
        b &= mask
        if kind == "sub":
            r = (a - b) & mask
            cf = b > a
            of = bool(((a ^ b) & (a ^ r)) >> (w - 1) & 1)
        else:                              # add
            r = (a + b) & mask
            cf = a + b > mask
            of = bool((~(a ^ b) & (a ^ r)) >> (w - 1) & 1)
        return r == 0, bool(r >> (w - 1)), cf, of

    def cond(self, cc: str) -> bool:
        zf, sf, cf, of = self._fl()
        return {
            "e": zf, "ne": not zf,
            "b": cf, "ae": not cf,
            "a": not cf and not zf, "be": cf or zf,
            "l": sf != of, "ge": sf == of,
            "g": not zf and sf == of, "le": zf or sf != of,
            "s": sf, "ns": not sf,
        }[cc]

    # -- one step ----------------------------------------------------------

    def step(self) -> None:
        inst = self.insts.get(self.pc)
        if inst is None:
            raise StopEmu("undecoded pc")
        m = inst.mnemonic
        ops = inst.operands
        next_pc = self.pc + inst.length
        w = self._op_width(inst)
        mask = (1 << w) - 1
        sign = 1 << (w - 1)

        def sx(v: int, from_w: int) -> int:
            v &= (1 << from_w) - 1
            return v - (1 << from_w) if v >> (from_w - 1) else v

        if m in ("nop", "nopw", "nopl", "endbr64") or m.startswith("nop"):
            pass
        elif m in ("mov", "movb", "movw", "movl", "movq", "movabs"):
            src, dst = ops
            self.write(inst, dst, w, self.read(inst, src, w))
        elif m in ("movslq", "movsxd"):
            src, dst = ops
            self.write(inst, dst, 64, sx(self.read(inst, src, 32), 32) & M64)
        elif m.startswith(("movz", "movs")) and len(m) >= 6:
            src, dst = ops
            fw = 8 if m[4] == "b" else 16
            v = self.read(inst, src, fw)
            if m.startswith("movs"):
                v = sx(v, fw) & mask
            dw = abs(dst.width) if dst.kind == "reg" and dst.width else w
            self.write(inst, dst, dw, v & ((1 << dw) - 1))
        elif m in ("lea", "leaq", "leal"):
            src, dst = ops
            self.write(inst, dst, w, self.ea(src) & mask)
        elif m.rstrip("bwlq") in _ALU or m in _ALU:
            stem = m if m in _ALU else m.rstrip("bwlq")
            if stem == "imul" and len(ops) == 3:
                immv, src, dst = ops
                r = sx(self.read(inst, src, w), w) * immv.imm
                self.write(inst, dst, w, r & mask)
                self.set_flags_res(r & mask, w)
            else:
                src, dst = ops
                a = self.read(inst, dst, w)
                b = self.read(inst, src, w)
                if stem == "add":
                    r = a + b
                    self.set_flags_add(a, b, w)
                elif stem == "sub":
                    r = a - b
                    self.set_flags_sub(a, b, w)
                elif stem == "imul":
                    r = sx(a, w) * sx(b, w)
                    self.set_flags_res(r & mask, w)
                else:
                    r = {"and": a & b, "or": a | b, "xor": a ^ b}[stem]
                    self.set_flags_res(r & mask, w)
                self.write(inst, dst, w, r & mask)
        elif m.rstrip("bwlq") in _SHIFT or m in _SHIFT:
            stem = _SHIFT[m if m in _SHIFT else m.rstrip("bwlq")]
            if len(ops) == 1:
                ops = [Operand("imm", imm=1)] + ops
            src, dst = ops
            sh = self.read(inst, src, 8) & (63 if w == 64 else 31)
            a = self.read(inst, dst, w)
            if stem == "shl":
                r = a << sh
            elif stem == "shr":
                r = a >> sh
            else:
                r = (sx(a, w) >> sh) & mask
            self.write(inst, dst, w, r & mask)
            if sh:
                self.set_flags_res(r & mask, w)
        elif m.rstrip("lqwb") in ("inc", "dec", "neg", "not"):
            stem = m.rstrip("lqwb")
            d = ops[0]
            a = self.read(inst, d, w)
            if stem == "inc":
                r = a + 1
                self.set_flags_res(r & mask, w)      # CF preserved ≈ res
            elif stem == "dec":
                r = a - 1
                self.set_flags_res(r & mask, w)
            elif stem == "neg":
                r = -a
                self.set_flags_sub(0, a, w)
            else:
                r = ~a
            self.write(inst, d, w, r & mask)
        elif m.rstrip("bwlq") == "cmp" or m == "cmp":
            src, dst = ops
            self.set_flags_sub(self.read(inst, dst, w),
                               self.read(inst, src, w), w)
        elif m.rstrip("bwlq") == "test" or m == "test":
            a, b = ops
            self.set_flags_res(self.read(inst, a, w)
                               & self.read(inst, b, w), w)
        elif m in ("push", "pushq"):
            v = self.read(inst, ops[0], 64)
            self.reg[RSP] = (self.reg[RSP] - 8) & M64
            self.store(self.reg[RSP], 8, v)
        elif m in ("pop", "popq"):
            v = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
            self.write(inst, ops[0], 64, v)
        elif m in ("call", "callq"):
            if ops and ops[0].kind == "imm":
                target = ops[0].imm
            elif ops and ops[0].kind == "reg" and ops[0].reg >= 0:
                target = self.reg[ops[0].reg]
            elif ops and ops[0].kind == "mem" and ops[0].base != -3:
                target = self.load(self.ea(ops[0]), 8)
            else:
                raise StopEmu("call target")
            self.reg[RSP] = (self.reg[RSP] - 8) & M64
            self.store(self.reg[RSP], 8, next_pc)
            next_pc = target & M64
        elif m in ("ret", "retq"):
            next_pc = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
        elif m == "leave":
            self.reg[RSP] = self.reg[RBP]
            self.reg[RBP] = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
        elif m in ("jmp", "jmpq"):
            if ops and ops[0].kind == "imm":
                next_pc = ops[0].imm & M64
            elif ops and ops[0].kind == "reg" and ops[0].reg >= 0:
                next_pc = self.reg[ops[0].reg]
            else:
                raise StopEmu("indirect jmp form")
        elif m in _JCC:
            if self.cond(_JCC[m]):
                next_pc = ops[0].imm & M64
        elif m.startswith("cmov"):
            base = m if m in _CMOV else m.rstrip("lqw")
            if base not in _CMOV:
                raise StopEmu(f"cmov {m}")
            src, dst = ops
            if self.cond(_LIFT_COND[_CMOV[base]]):
                self.write(inst, dst, w, self.read(inst, src, w))
        elif m in ("cltq", "cdqe"):
            self.reg[RAX] = sx(self.reg[RAX] & M32, 32) & M64
        elif m in ("cwtl", "cwde"):
            self.reg[RAX] = (self.reg[RAX] & ~M32) | (
                sx(self.reg[RAX] & M16, 16) & M32)
        elif m in ("cltd", "cdq"):
            self.reg[RDX] = (self.reg[RDX] & ~M32) | (
                M32 if self.reg[RAX] & 0x80000000 else 0)
        elif m in ("cqto", "cqo"):
            self.reg[RDX] = M64 if self.reg[RAX] >> 63 else 0
        elif m.rstrip("lqwb") in ("div", "idiv"):
            stem = m.rstrip("lqwb")
            b = self.read(inst, ops[0], w)
            if b == 0:
                raise StopEmu("div by zero")
            if w == 32:
                a = ((self.reg[RDX] & M32) << 32) | (self.reg[RAX] & M32)
            else:
                a = ((self.reg[RDX] & M64) << 64) | (self.reg[RAX] & M64)
            if stem == "idiv":
                aa = a - (1 << (2 * w)) if a >> (2 * w - 1) else a
                bb = sx(b, w)
                q = abs(aa) // abs(bb)    # exact trunc-toward-zero
                if (aa < 0) != (bb < 0):
                    q = -q
                r = aa - q * bb
                if not (-(1 << (w - 1)) <= q <= (1 << (w - 1)) - 1):
                    raise StopEmu("div overflow")   # x86 #DE
            else:
                q, r = divmod(a, b)
                if q > (1 << w) - 1:
                    raise StopEmu("div overflow")   # x86 #DE
            if w == 32:
                self.reg[RAX] = q & M32   # 32-bit writes zero-extend
                self.reg[RDX] = r & M32
            else:
                self.reg[RAX] = q & M64
                self.reg[RDX] = r & M64
        elif m in ("xchg", "xchgl", "xchgq"):
            a, b = ops
            va = self.read(inst, a, w)
            vb = self.read(inst, b, w)
            self.write(inst, a, w, vb)
            self.write(inst, b, w, va)
        else:
            raise StopEmu(f"unsupported {m}")
        self.pc = next_pc & M64

    # -- run ---------------------------------------------------------------

    def canonical(self) -> np.ndarray:
        row = np.zeros(18, dtype=np.uint64)
        for i in range(16):
            row[i] = self.reg[i]
        row[16] = self.pc
        row[17] = 0x202                   # IF set, DF clear
        return row

    def run(self, max_steps: int) -> EmuResult:
        rows = [self.canonical()]
        begin = self.pc
        stop = "max_steps"
        for _ in range(max_steps):
            try:
                self.step()
            except StopEmu as e:
                # rows[-1] is already the clean state AT the boundary (the
                # unsupported instruction never executed) — exactly the
                # NativeTrace contract's "last record = state at end"
                stop = str(e)
                break
            rows.append(self.canonical())
        steps = np.stack(rows)
        regions = [(r.vaddr, bytes(r.buf)) for r in self.regions]
        # NativeTrace contract: steps[n_macro] is the state at the end
        # marker; regions snapshot the *initial* image — rebuild from the
        # originals the caller seeded (they were copied into Region bufs),
        # so hand back the caller's originals via from_snapshot instead.
        nt = NativeTrace(begin=begin, end=int(steps[-1][16]),
                         steps=steps, regions=regions)
        return EmuResult(nt=nt, steps=len(steps) - 1, stop_reason=stop,
                         stop_pc=int(steps[-1][16]))


def emulate_window(binary: str, regs: np.ndarray,
                   regions: list[tuple[int, bytes]], pc: int,
                   max_steps: int = 200_000,
                   insts: "dict[int, Inst] | None" = None) -> EmuResult:
    """Decode + run; regions are (vaddr, bytes) of the initial image.

    ``insts`` accepts a pre-parsed static decode so callers that also lift
    (warm.window_from_snapshot_lifted) disassemble once.

    NOTE the returned ``nt.regions`` must be the INITIAL image (the lifter
    snapshots memory at window start); Emulator.run hands back post-run
    buffers, so re-seed them here."""
    if insts is None:
        insts = static_decode(binary)
    emu = Emulator(insts, regs, regions, pc)
    res = emu.run(max_steps)
    return res._replace(nt=res.nt._replace(
        regions=[(v, d) for v, d in regions]))
