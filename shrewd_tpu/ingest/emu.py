"""Snapshot-seeded x86-64 subset emulator: checkpoint → synthetic capture.

Checkpoint restore needs an instruction stream to rebuild a replay window
(SURVEY §5.4: checkpoints are architectural-only — the reference restores
arch state and *runs forward*, ``src/cpu/o3/cpu.cc:706-799``).  A live
ptrace capture (tools/nativetrace.cc) needs the program running on this
host at the right marker; a checkpoint mid-run has no such luxury.  This
module plays the host CPU's role instead: a 64-bit x86 subset interpreter
seeded from the ``ArchSnapshot`` (regs + memory image + pc) that emits the
same per-step record stream the ptrace tracer produces, so the *unchanged*
capture-based lifter (ingest/lift.py) consumes it.

The duplication is deliberate and load-bearing: the lifter re-simulates
every macro-op in its own 32-bit µop semantics and demotes on mismatch, so
running it over this emulator's stream is a differential test between two
independent implementations — a bug in either shows up as opaque demotions
(visible in LiftStats), not silent corruption.  On workloads with a live
capture available, ``tests/test_emu.py`` additionally pins this emulator's
step stream bit-for-bit against the real ptrace capture.

Width semantics follow the ISA: 8/16-bit destination writes merge, 32-bit
zero-extend to 64, 64-bit overwrite.  Flags are kept lazily (source op +
operands) and materialized per condition code.  Anything outside the
supported subset (syscalls included) ends the window — the window-boundary
analog of the tracer's end marker.

Reference anchors: restore-then-rewarm (``src/cpu/o3/cpu.cc:706-799``),
the CheckerCPU lockstep-interpreter pattern (``src/cpu/checker/cpu.hh``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from shrewd_tpu.ingest.lift import (Inst, NativeTrace, Operand, _CMOV,
                                    static_decode, stem_of)

M8, M16, M32, M64 = 0xFF, 0xFFFF, 0xFFFFFFFF, 0xFFFFFFFFFFFFFFFF
RAX, RCX, RDX, RBX, RSP, RBP, RSI, RDI = range(8)
R11 = 11

_ALU = {"add", "sub", "and", "or", "xor", "imul"}
_SHIFT = {"shl": "shl", "sal": "shl", "shr": "shr", "sar": "sar",
          "rol": "rol", "ror": "ror"}

# one shared suffix-strip rule with the lifter (lift.stem_of): the rstrip
# bug this replaced existed in both files precisely because the logic was
# duplicated
_stem = stem_of

_JCC = {"je": "e", "jz": "e", "jne": "ne", "jnz": "ne",
        "jb": "b", "jnae": "b", "jae": "ae", "jnb": "ae",
        "ja": "a", "jnbe": "a", "jbe": "be", "jna": "be",
        "jl": "l", "jnge": "l", "jge": "ge", "jnl": "ge",
        "jg": "g", "jnle": "g", "jle": "le", "jng": "le",
        "js": "s", "jns": "ns"}

# _CMOV maps cmov* → the lifter's condition vocabulary; translate to ours
_LIFT_COND = {"eq": "e", "ne": "ne", "lt": "l", "ge": "ge",
              "swap_lt": "g", "swap_ge": "le", "sign": "s", "nsign": "ns",
              "ub": "b", "uae": "ae", "ua": "a", "ube": "be"}

# setcc suffix → condition code (sete, setnz, setbe, …)
_JCC_SET = {k[1:]: v for k, v in _JCC.items()}


class StopEmu(Exception):
    """Window boundary: unsupported instruction / memory miss / syscall."""


class Region:
    def __init__(self, vaddr: int, data: bytes, read_only: bool = False):
        self.vaddr = vaddr
        self.buf = bytearray(data)
        self.read_only = read_only

    def contains(self, addr: int, size: int) -> bool:
        return self.vaddr <= addr and addr + size <= self.vaddr + len(self.buf)


def elf_regions(binary: str) -> list:
    """PT_LOAD segments of a static non-PIE ELF as (vaddr, bytes, ro)
    triples — the read-only text/rodata backing a whole-program emulation
    needs beyond the writable-memory snapshot (a store into one is a fault
    on real hardware and classifies DUE here)."""
    import struct as _struct

    with open(binary, "rb") as f:
        blob = f.read()
    if blob[:4] != b"\x7fELF" or blob[4] != 2:
        raise ValueError("need a 64-bit ELF")
    e_phoff, = _struct.unpack_from("<Q", blob, 0x20)
    e_phentsize, = _struct.unpack_from("<H", blob, 0x36)
    e_phnum, = _struct.unpack_from("<H", blob, 0x38)
    loads = []
    relro = []                            # GNU_RELRO: rw in phdrs, ro live
    for i in range(e_phnum):
        off = e_phoff + i * e_phentsize
        p_type, p_flags = _struct.unpack_from("<II", blob, off)
        p_offset, p_vaddr, _p_paddr, p_filesz, p_memsz = \
            _struct.unpack_from("<5Q", blob, off + 8)
        if p_type == 0x6474E552:
            relro.append((p_vaddr, p_vaddr + p_memsz))
        if p_type != 1:                   # PT_LOAD
            continue
        data = blob[p_offset:p_offset + p_filesz]
        if p_memsz > p_filesz:            # bss zero-fill
            data = data + b"\x00" * (p_memsz - p_filesz)
        loads.append((p_vaddr, data, not (p_flags & 0x2)))   # PF_W
    out = []
    for vaddr, data, ro in loads:
        if ro:
            out.append((vaddr, data, True))
            continue
        # split the writable segment at RELRO boundaries (mprotected
        # read-only after startup — a store there is a fault on hardware)
        cut = vaddr
        for lo, hi in relro:
            if lo <= vaddr and cut < hi <= vaddr + len(data):
                out.append((cut, data[cut - vaddr:hi - vaddr], True))
                cut = hi
        if cut < vaddr + len(data):
            out.append((cut, data[cut - vaddr:], False))
    return out


class EmuResult(NamedTuple):
    nt: NativeTrace            # lifter-compatible synthetic capture
    steps: int
    stop_reason: str
    stop_pc: int


class ExitedEmu(Exception):
    """Clean program exit (exit/exit_group syscall) in do_syscalls mode."""

    def __init__(self, code: int):
        super().__init__(f"exit({code})")
        self.code = code


class Emulator:
    def __init__(self, insts: dict[int, Inst], regs: np.ndarray,
                 regions: list[tuple[int, bytes]], pc: int,
                 do_syscalls: bool = False, fs_base: int = 0):
        """``do_syscalls=True`` executes write/exit syscalls instead of
        ending the window: stdout bytes accumulate in ``self.stdout`` and
        exit raises ExitedEmu — the mode used for whole-program perturbed
        re-execution (64-bit fault classification, CheckerCPU role)."""
        self.insts = insts
        self.reg = [int(x) & M64 for x in regs[:16]]
        # snapshot regions first (they win on overlap), then any read-only
        # ELF fallbacks appended by the caller as (vaddr, data, True)
        self.regions = [Region(*r) for r in regions]
        self.pc = int(pc)
        self.flags = ("res", 0, 64, 0)   # kind, operands..., width
        self.stop_reason = "max_steps"
        self.do_syscalls = do_syscalls
        self.stdout = bytearray()
        self.fs_base = fs_base or self.FS_BASE
        self.xmm = [0] * 32          # 512-bit zmm values (EVEX regs 16-31)
        self.kreg = [0] * 8          # AVX-512 mask registers
        if do_syscalls and not fs_base:
            self.regions.append(Region(self.FS_BASE - 0x1000,
                                       bytes(0x2000)))

    # -- memory ------------------------------------------------------------

    def _region(self, addr: int, size: int) -> Region:
        for r in self.regions:
            if r.contains(addr, size):
                return r
        raise StopEmu(f"mem miss {addr:#x}+{size}")

    def load(self, addr: int, size: int) -> int:
        r = self._region(addr, size)
        off = addr - r.vaddr
        return int.from_bytes(r.buf[off:off + size], "little")

    def store(self, addr: int, size: int, value: int) -> None:
        r = self._region(addr, size)
        if r.read_only:
            raise StopEmu(f"store to read-only {addr:#x}")   # host: SIGSEGV
        off = addr - r.vaddr
        r.buf[off:off + size] = (value & ((1 << (8 * size)) - 1)).to_bytes(
            size, "little")

    # -- registers ---------------------------------------------------------

    def rget(self, op: Operand) -> int:
        v = self.reg[op.reg]
        w = op.width
        if w == 64:
            return v
        if w == 32:
            return v & M32
        if w == 16:
            return v & M16
        if w == 8:
            return v & M8
        if w == -8:                       # high byte (%ah family)
            return (v >> 8) & M8
        raise StopEmu(f"reg width {w}")

    def rset(self, op: Operand, value: int) -> None:
        old = self.reg[op.reg]
        w = op.width
        if w == 64:
            nv = value & M64
        elif w == 32:
            nv = value & M32              # zero-extends
        elif w == 16:
            nv = (old & ~M16) | (value & M16)
        elif w == 8:
            nv = (old & ~M8) | (value & M8)
        elif w == -8:
            nv = (old & ~(M8 << 8)) | ((value & M8) << 8)
        else:
            raise StopEmu(f"reg width {w}")
        self.reg[op.reg] = nv

    # -- operands ----------------------------------------------------------

    FS_BASE = 0x7000_0000_0000       # synthetic fallback (no capture)

    def ea(self, op: Operand) -> int:
        if op.base == -3:
            raise StopEmu("unparsed mem operand")
        if op.base == -5:
            # %gs:disp — the capture records fs_base only; resolving gs
            # against fs_base would silently read the wrong TLS block, so
            # stop loudly (the trial classifies DUE, never silent skew)
            raise StopEmu("gs-relative access (no gs_base captured)")
        if op.base == -4:
            # %fs:disp — TLS-relative.  With a captured fs_base the real
            # TLS block is in the writable-memory snapshot (pointer guard
            # included, so glibc's mangled function pointers demangle
            # correctly); without one, a zeroed synthetic block gives
            # single-threaded defaults.
            return (self.fs_base + op.disp) & M64
        # segment overrides FIRST: a segment-prefixed rip-relative form
        # must not slip past via the rip_rel early-return below (fs would
        # silently read non-TLS memory; gs must stop loudly)
        if op.seg == "gs":
            raise StopEmu("gs-relative access (no gs_base captured)")
        seg_base = self.fs_base if op.seg == "fs" else 0
        if op.rip_rel:
            return (seg_base + op.disp) & M64
        a = op.disp + seg_base
        if op.base >= 0:
            a += self.reg[op.base]
        if op.index >= 0:
            a += self.reg[op.index] * op.scale
        return a & M64

    def _op_width(self, inst: Inst, default: int = 64) -> int:
        for o in inst.operands:
            if o.kind == "reg" and o.reg >= 0 and o.width:
                return abs(o.width)
        return {"b": 8, "w": 16, "l": 32, "q": 64}.get(
            inst.mnemonic[-1], default)

    def read(self, inst: Inst, op: Operand, width: int) -> int:
        if op.kind == "imm":
            return op.imm & ((1 << width) - 1)
        if op.kind == "reg":
            if op.reg < 0:
                raise StopEmu("non-GPR operand")
            return self.rget(op)
        if op.kind == "mem":
            return self.load(self.ea(op), width // 8)
        raise StopEmu("operand kind")

    def write(self, inst: Inst, op: Operand, width: int, value: int) -> None:
        if op.kind == "reg":
            if op.reg < 0:
                raise StopEmu("non-GPR operand")
            self.rset(op, value)
        elif op.kind == "mem":
            self.store(self.ea(op), width // 8, value)
        else:
            raise StopEmu("write to imm")

    # -- flags -------------------------------------------------------------

    def set_flags_sub(self, a: int, b: int, width: int) -> None:
        self.flags = ("sub", a, b, width)

    def set_flags_add(self, a: int, b: int, width: int) -> None:
        self.flags = ("add", a, b, width)

    def set_flags_res(self, v: int, width: int) -> None:
        self.flags = ("res", v, width, 0)

    def _fl(self) -> tuple[bool, bool, bool, bool]:
        """(ZF, SF, CF, OF) from the lazy flags record."""
        kind = self.flags[0]
        if kind == "fl":                   # directly materialized flags
            return self.flags[1], self.flags[2], self.flags[3], self.flags[4]
        if kind == "res":
            _, v, w, _ = self.flags
            mask = (1 << w) - 1
            r = v & mask
            return r == 0, bool(r >> (w - 1)), False, False
        _, a, b, w = self.flags
        mask = (1 << w) - 1
        a &= mask
        b &= mask
        if kind == "sub":
            r = (a - b) & mask
            cf = b > a
            of = bool(((a ^ b) & (a ^ r)) >> (w - 1) & 1)
        else:                              # add
            r = (a + b) & mask
            cf = a + b > mask
            of = bool((~(a ^ b) & (a ^ r)) >> (w - 1) & 1)
        return r == 0, bool(r >> (w - 1)), cf, of

    def cond(self, cc: str) -> bool:
        zf, sf, cf, of = self._fl()
        return {
            "e": zf, "ne": not zf,
            "b": cf, "ae": not cf,
            "a": not cf and not zf, "be": cf or zf,
            "l": sf != of, "ge": sf == of,
            "g": not zf and sf == of, "le": zf or sf != of,
            "s": sf, "ns": not sf,
        }[cc]

    # -- one step ----------------------------------------------------------


    # -- SIMD subset (glibc str/mem primitives) ----------------------------

    def _simd_read(self, op: Operand, width_bits: int) -> int:
        if op.kind == "xmm":
            return self.xmm[op.reg] & ((1 << width_bits) - 1)
        if op.kind == "mem":
            return self.load(self.ea(op), width_bits // 8)
        if op.kind == "reg" and op.reg >= 0:
            return self.rget(op) & ((1 << width_bits) - 1)
        raise StopEmu("simd operand")

    def _simd_write(self, op: Operand, width_bits: int, v: int) -> None:
        v &= (1 << width_bits) - 1
        if op.kind == "xmm":
            # SSE forms (128-bit dest) preserve the upper ymm half; VEX
            # forms zero it — width 128 from an SSE mnemonic keeps upper,
            # the VEX dispatch below passes zero_upper=True instead
            self.xmm[op.reg] = v if width_bits >= 512 else \
                ((self.xmm[op.reg] >> width_bits) << width_bits) | v
        elif op.kind == "mem":
            self.store(self.ea(op), width_bits // 8, v)
        elif op.kind == "reg" and op.reg >= 0:
            self.write(None, op, max(abs(op.width), 32), v)
        else:
            raise StopEmu("simd operand")

    @staticmethod
    def _per_byte(a: int, b: int, nbytes: int, fn) -> int:
        out = 0
        for i in range(nbytes):
            out |= (fn((a >> (8 * i)) & 0xFF, (b >> (8 * i)) & 0xFF)
                    & 0xFF) << (8 * i)
        return out

    def _simd(self, m: str, ops: list) -> None:
        """The glibc str/mem SIMD vocabulary: moves, byte compares,
        min-unsigned, logicals, movemask, broadcast.  VEX (v-prefixed)
        forms zero the untouched upper ymm half; SSE forms preserve it
        (the architectural split that makes vzeroupper matter)."""
        vex = m.startswith("v")
        base = m[1:] if vex else m
        # EVEX spells element width into the mnemonic for full-register
        # moves/logicals; semantics are identical at our granularity
        _ALIAS = {"pxord": "pxor", "pxorq": "pxor",
                  "pandd": "pand", "pandq": "pand",
                  "pord": "por", "porq": "por",
                  "movdqu8": "movdqu", "movdqu16": "movdqu",
                  "movdqu32": "movdqu", "movdqu64": "movdqu",
                  "movdqa32": "movdqa", "movdqa64": "movdqa"}
        base = _ALIAS.get(base, base)
        width = max((o.width for o in ops if o.kind == "xmm"), default=128)
        nb = width // 8
        if base == "zeroupper":
            self.xmm = [x & ((1 << 128) - 1) for x in self.xmm]
            return
        if base in ("zeroall",):
            self.xmm = [0] * 32
            return
        if base in ("movdqu", "movdqa", "movaps", "movups", "movapd",
                    "movupd", "lddqu"):
            src, dst = ops
            v = self._simd_read(src, width)
            self._simd_write(dst, width, v)
            if vex and dst.kind == "xmm" and width < 512:
                self.xmm[dst.reg] &= (1 << width) - 1   # VEX zeroes→MAXVL
            return
        if base in ("movd", "movq"):
            w = 32 if base == "movd" else 64
            src, dst = ops
            v = self._simd_read(src, w)
            if dst.kind == "xmm":
                self.xmm[dst.reg] = v                      # zero-extends
            else:
                self._simd_write(dst, w, v)
            return
        if base in ("pbroadcastb", "pbroadcastw", "pbroadcastd",
                    "pbroadcastq", "broadcastss"):
            src, dst = ops
            ew = {"b": 1, "w": 2, "d": 4, "q": 8, "s": 4}[base[-1]
                                                          if base[-1] != "s"
                                                          else "s"]
            e = self._simd_read(src, 8 * ew)
            dw = dst.width or width
            v = 0
            for i in range(dw // (8 * ew)):
                v |= e << (8 * ew * i)
            self._simd_write(dst, dw, v)
            if vex and dst.kind == "xmm" and dw < 512:
                self.xmm[dst.reg] &= (1 << dw) - 1
            return
        if base == "pmovmskb":
            src, dst = ops
            v = self._simd_read(src, src.width or width)
            mask = 0
            for i in range((src.width or width) // 8):
                mask |= (((v >> (8 * i + 7)) & 1) << i)
            self._simd_write(dst, 32, mask)
            self.reg[dst.reg] &= 0xFFFFFFFF                # zext to 64
            return
        if base.startswith("kmov"):
            src_o, dst = ops
            kw = {"b": 8, "w": 16, "d": 32, "q": 64}[base[4]]
            if src_o.kind == "kreg":
                v = self.kreg[src_o.reg] & ((1 << kw) - 1)
                if dst.kind == "kreg":
                    self.kreg[dst.reg] = v
                else:
                    self._simd_write(dst, kw, v)
            else:
                self.kreg[dst.reg] = self._simd_read(src_o, kw)
            return
        if base.startswith("kunpck"):
            # kunpck{bw,wd,dq} %k_lo,%k_hi_src? — AT&T order
            # [src_low, src_high, dst]: dst = (high << w) | low
            kw = {"bw": 8, "wd": 16, "dq": 32}[base[6:]]
            lo, hi, dst = ops
            self.kreg[dst.reg] = (
                ((self.kreg[hi.reg] & ((1 << kw) - 1)) << kw)
                | (self.kreg[lo.reg] & ((1 << kw) - 1)))
            return
        if base.startswith("kortest"):
            a, b2 = ops
            v = self.kreg[a.reg] | self.kreg[b2.reg]
            # ZF = union empty; consumers in glibc branch on e/ne (CF-"all
            # ones" users would need a richer flag model and stop there)
            self.set_flags_res(v & M64, 64)
            return
        if base in ("pcmpeqb", "pcmpb", "pcmpneqb") \
                and ops[-1].kind == "kreg":
            if base == "pcmpb":                 # predicate immediate form
                pred, s2, s1, dst = ops
                if pred.imm not in (0, 4):
                    # 1 LT / 2 LE / 5 NLT / 6 NLE need signed per-byte
                    # compares — stop loudly rather than mis-mask as EQ
                    raise StopEmu(f"vpcmpb predicate imm {pred.imm}")
                neq = pred.imm == 4
            else:
                s2, s1, dst = ops
                neq = base == "pcmpneqb"
            vw = max((o.width for o in (s1, s2) if o.kind == "xmm"),
                     default=width)
            a = self._simd_read(s1, vw)
            b2 = self._simd_read(s2, vw)
            mask = 0
            for i in range(vw // 8):
                eq = ((a >> (8 * i)) & 0xFF) == ((b2 >> (8 * i)) & 0xFF)
                if eq != neq:
                    mask |= 1 << i
            self.kreg[dst.reg] = mask
            return
        if base in ("addss", "subss", "mulss", "divss", "minss",
                    "maxss") and len(ops) == 2:
            src, dst = ops
            if dst.kind != "xmm":
                raise StopEmu(f"{base} dst {dst.kind}")
            a = self._simd_read(src, 32) if src.kind != "mem" else None
            if src.kind == "mem":
                a = self.load(self.ea(src), 4)
            b = self.xmm[dst.reg] & 0xFFFFFFFF
            with np.errstate(all="ignore"):
                fa = np.uint32(a).view(np.float32)
                fb = np.uint32(b).view(np.float32)
                # min/max pick the SOURCE on NaN or tie (Intel MINSS/MAXSS)
                r = {"addss": fb + fa, "subss": fb - fa, "mulss": fb * fa,
                     "divss": fb / fa,
                     "minss": fb if fb < fa else fa,
                     "maxss": fb if fb > fa else fa}[base]
            bits = int(np.float32(r).view(np.uint32))
            self.xmm[dst.reg] = (self.xmm[dst.reg]
                                 & ~0xFFFFFFFF) | bits
            return
        if base in ("comiss", "ucomiss") and len(ops) == 2:
            src, dst = ops
            a = (self.load(self.ea(src), 4) if src.kind == "mem"
                 else self._simd_read(src, 32))
            b = self._simd_read(dst, 32)
            fa = np.uint32(a & 0xFFFFFFFF).view(np.float32)
            fb = np.uint32(b & 0xFFFFFFFF).view(np.float32)
            # hardware semantics exactly: unordered → ZF=CF=1 (PF too,
            # unmodeled); equal (incl. +0/-0) → ZF=1; dst<src → CF=1
            if np.isnan(fa) or np.isnan(fb):
                self.flags = ("fl", True, False, True, False)
            elif fb == fa:
                self.flags = ("fl", True, False, False, False)
            elif fb < fa:
                self.flags = ("fl", False, False, True, False)
            else:
                self.flags = ("fl", False, False, False, False)
            return
        if base == "movss" and len(ops) == 2:
            src, dst = ops
            if dst.kind == "xmm" and src.kind == "mem":
                v = self.load(self.ea(src), 4)
                self.xmm[dst.reg] = v               # load zero-extends
                return
            if dst.kind == "mem" and src.kind == "xmm":
                self.store(self.ea(dst), 4, self.xmm[src.reg] & 0xFFFFFFFF)
                return
            if dst.kind == "xmm" and src.kind == "xmm":
                self.xmm[dst.reg] = ((self.xmm[dst.reg] & ~0xFFFFFFFF)
                                     | (self.xmm[src.reg] & 0xFFFFFFFF))
                return
            raise StopEmu("movss operands")
        if base in ("pxor", "por", "pand", "pandn", "pcmpeqb", "pminub",
                    "psubb", "paddb"):
            if vex and len(ops) == 3:
                s2, s1, dst = ops
            else:
                s2, dst = ops
                s1 = dst
            a = self._simd_read(s1, width)
            b = self._simd_read(s2, width)
            if base == "pxor":
                r = a ^ b
            elif base == "por":
                r = a | b
            elif base == "pand":
                r = a & b
            elif base == "pandn":
                r = (~a) & b & ((1 << width) - 1)
            elif base == "pcmpeqb":
                r = self._per_byte(a, b, nb,
                                   lambda x, y: 0xFF if x == y else 0)
            elif base == "pminub":
                r = self._per_byte(a, b, nb, min)
            elif base == "psubb":
                r = self._per_byte(a, b, nb, lambda x, y: (x - y) & 0xFF)
            else:                                          # paddb
                r = self._per_byte(a, b, nb, lambda x, y: (x + y) & 0xFF)
            # VEX/EVEX destination writes zero through MAXVL (bit 511) —
            # `vpxor %xmm0,%xmm0,%xmm0` clears the whole zmm; SSE forms
            # preserve everything above their width
            self._simd_write(dst, 512 if vex else width,
                             r & ((1 << width) - 1))
            return
        raise StopEmu(f"unsupported simd {m}")

    def step(self, bulk_limit: int = 1) -> int:
        """Execute at most ``bulk_limit`` hardware steps and return the
        count consumed.  Every instruction consumes 1 except rep
        movs/stos, where a hardware step = ONE iteration (a single-step
        trap fires per iteration) — the rep handler may consume up to
        ``bulk_limit`` iterations in one call so whole-program runs don't
        pay a Python call per byte of a big memset, while callers that
        need exact step alignment (fault injection, per-step window
        validation) cap the bulk at their next boundary."""
        self._consumed = 1
        inst = self.insts.get(self.pc)
        if inst is None:
            raise StopEmu("undecoded pc")
        self._bulk_limit = max(1, bulk_limit)
        self._step_body(inst)
        return self._consumed

    def _step_body(self, inst) -> None:
        m = inst.mnemonic
        ops = inst.operands
        next_pc = self.pc + inst.length
        w = self._op_width(inst)
        mask = (1 << w) - 1
        sign = 1 << (w - 1)

        def sx(v: int, from_w: int) -> int:
            v &= (1 << from_w) - 1
            return v - (1 << from_w) if v >> (from_w - 1) else v

        if (any(o.kind in ("xmm", "kreg") for o in ops)
                or m in ("vzeroupper",)):
            self._simd(m, ops)
            self.pc = next_pc & M64
            return
        rep_parts = m.split()
        if (len(rep_parts) == 2
                and rep_parts[0] in ("rep", "repz", "repe")
                and rep_parts[1].rstrip("bwldq") in ("movs", "stos")):
            # the erms memcpy/memset cores: copy/fill rcx elements (DF
            # assumed clear — glibc never runs these with DF set).
            # Element size from the suffix, else from the register operand
            # ("rep stos %al,%es:(%rdi)" prints suffixless)
            kind_s = rep_parts[1].rstrip("bwldq")
            sfx = rep_parts[1][len(kind_s):]
            esz = {"b": 1, "w": 2, "l": 4, "d": 4, "q": 8}.get(sfx, 0)
            if not esz:
                widths = [abs(o.width) // 8 for o in ops
                          if o.kind == "reg" and o.reg >= 0 and o.width]
                esz = widths[0] if widths else 1
            # ONE iteration per step(), pc held until rcx reaches 0 — the
            # hardware model: a single-step trap fires after EVERY rep
            # iteration, so ptrace (tools/hostsfi.cc), the capture
            # (tools/nativetrace.cc), and the lifter all count per
            # iteration.  Executing the whole rep as one step desynced
            # every later fault coordinate by (iterations-1) — the r4
            # strmix due→masked channel.  A corrupted rcx simply walks
            # rdi/rsi out of the image and traps exactly where silicon
            # segfaults (no plausibility guard needed).
            n = self.reg[RCX]
            if n == 0:
                self.pc = next_pc & M64
                return
            k = int(min(n, self._bulk_limit))
            if kind_s == "movs":
                for _ in range(k):
                    self.store(self.reg[RDI], esz,
                               self.load(self.reg[RSI], esz))
                    self.reg[RSI] = (self.reg[RSI] + esz) & M64
                    self.reg[RDI] = (self.reg[RDI] + esz) & M64
            else:
                v = self.reg[RAX] & ((1 << (8 * esz)) - 1)
                for _ in range(k):
                    self.store(self.reg[RDI], esz, v)
                    self.reg[RDI] = (self.reg[RDI] + esz) & M64
            self.reg[RCX] = (n - k) & M64
            self._consumed = k
            if self.reg[RCX] == 0:
                self.pc = next_pc & M64
            return
        if m in ("bsf", "bsr", "tzcnt", "lzcnt"):
            src_o, dst = ops
            v = self.read(inst, src_o, w)
            if v == 0:
                res = w
                if m in ("tzcnt", "lzcnt"):
                    self.write(inst, dst, w, w)
                # bsf/bsr leave dst unchanged on zero
            else:
                if m in ("bsf", "tzcnt"):
                    res = (v & -v).bit_length() - 1
                elif m == "bsr":
                    res = v.bit_length() - 1
                else:                                      # lzcnt
                    res = w - v.bit_length()
                self.write(inst, dst, w, res)
            if m in ("tzcnt", "lzcnt"):
                # TZCNT/LZCNT define ZF from the *result* (BSF semantics
                # — ZF = src==0 — would mis-steer branches after tzcnt)
                self.set_flags_res(res & mask, w)
            else:
                self.set_flags_res(v & mask, w)   # bsf/bsr: ZF = src == 0
            self.pc = next_pc & M64
            return
        if m in ("nop", "nopw", "nopl", "endbr64") or m.startswith("nop"):
            pass
        elif m in ("mov", "movb", "movw", "movl", "movq", "movabs"):
            src, dst = ops
            self.write(inst, dst, w, self.read(inst, src, w))
        elif m in ("movslq", "movsxd"):
            src, dst = ops
            self.write(inst, dst, 64, sx(self.read(inst, src, 32), 32) & M64)
        elif m.startswith(("movz", "movs")) and len(m) >= 6:
            src, dst = ops
            fw = 8 if m[4] == "b" else 16
            v = self.read(inst, src, fw)
            if m.startswith("movs"):
                v = sx(v, fw) & mask
            dw = abs(dst.width) if dst.kind == "reg" and dst.width else w
            self.write(inst, dst, dw, v & ((1 << dw) - 1))
        elif m in ("lea", "leaq", "leal"):
            src, dst = ops
            self.write(inst, dst, w, self.ea(src) & mask)
        elif (stem := _stem(m, _ALU)) is not None:
            if stem == "imul" and len(ops) == 3:
                immv, src, dst = ops
                r = sx(self.read(inst, src, w), w) * immv.imm
                self.write(inst, dst, w, r & mask)
                self.set_flags_res(r & mask, w)
            else:
                src, dst = ops
                a = self.read(inst, dst, w)
                b = self.read(inst, src, w)
                if stem == "add":
                    r = a + b
                    self.set_flags_add(a, b, w)
                elif stem == "sub":
                    r = a - b
                    self.set_flags_sub(a, b, w)
                elif stem == "imul":
                    r = sx(a, w) * sx(b, w)
                    self.set_flags_res(r & mask, w)
                else:
                    r = {"and": a & b, "or": a | b, "xor": a ^ b}[stem]
                    self.set_flags_res(r & mask, w)
                self.write(inst, dst, w, r & mask)
        elif (sh_stem := _stem(m, _SHIFT)) is not None:
            stem = _SHIFT[sh_stem]
            if len(ops) == 1:
                ops = [Operand("imm", imm=1)] + ops
            src, dst = ops
            sh = self.read(inst, src, 8) & (63 if w == 64 else 31)
            a = self.read(inst, dst, w)
            if stem == "shl":
                r = a << sh
            elif stem == "shr":
                r = a >> sh
            elif stem == "sar":
                r = (sx(a, w) >> sh) & mask
            elif stem == "rol":
                sh %= w
                r = (a << sh) | (a >> (w - sh)) if sh else a
            else:                                 # ror
                sh %= w
                r = (a >> sh) | (a << (w - sh)) if sh else a
            self.write(inst, dst, w, r & mask)
            if sh and stem not in ("rol", "ror"):
                self.set_flags_res(r & mask, w)
        elif (stem := _stem(m, ("inc", "dec", "neg", "not"))) is not None:
            d = ops[0]
            a = self.read(inst, d, w)
            if stem == "inc":
                r = a + 1
                self.set_flags_res(r & mask, w)      # CF preserved ≈ res
            elif stem == "dec":
                r = a - 1
                self.set_flags_res(r & mask, w)
            elif stem == "neg":
                r = -a
                self.set_flags_sub(0, a, w)
            else:
                r = ~a
            self.write(inst, d, w, r & mask)
        elif _stem(m, ("cmp",)) is not None:
            src, dst = ops
            self.set_flags_sub(self.read(inst, dst, w),
                               self.read(inst, src, w), w)
        elif _stem(m, ("test",)) is not None:
            a, b = ops
            self.set_flags_res(self.read(inst, a, w)
                               & self.read(inst, b, w), w)
        elif m in ("push", "pushq"):
            v = self.read(inst, ops[0], 64)
            self.reg[RSP] = (self.reg[RSP] - 8) & M64
            self.store(self.reg[RSP], 8, v)
        elif m in ("pop", "popq"):
            v = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
            self.write(inst, ops[0], 64, v)
        elif m in ("call", "callq"):
            if ops and ops[0].kind == "imm":
                target = ops[0].imm
            elif ops and ops[0].kind == "reg" and ops[0].reg >= 0:
                target = self.reg[ops[0].reg]
            elif ops and ops[0].kind == "mem" and ops[0].base != -3:
                target = self.load(self.ea(ops[0]), 8)
            else:
                raise StopEmu("call target")
            self.reg[RSP] = (self.reg[RSP] - 8) & M64
            self.store(self.reg[RSP], 8, next_pc)
            next_pc = target & M64
        elif m in ("ret", "retq"):
            next_pc = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
        elif m == "leave":
            self.reg[RSP] = self.reg[RBP]
            self.reg[RBP] = self.load(self.reg[RSP], 8)
            self.reg[RSP] = (self.reg[RSP] + 8) & M64
        elif m in ("jmp", "jmpq"):
            if ops and ops[0].kind == "imm":
                next_pc = ops[0].imm & M64
            elif ops and ops[0].kind == "reg" and ops[0].reg >= 0:
                next_pc = self.reg[ops[0].reg]
            elif ops and ops[0].kind == "mem" and ops[0].base != -3:
                # jump tables / resolved-IFUNC GOT slots: same memory-
                # indirect form the call branch already supports
                next_pc = self.load(self.ea(ops[0]), 8)
            else:
                raise StopEmu("indirect jmp form")
        elif m in _JCC:
            if self.cond(_JCC[m]):
                next_pc = ops[0].imm & M64
        elif m.startswith("cmov"):
            base = m if m in _CMOV else m.rstrip("lqw")
            if base not in _CMOV:
                raise StopEmu(f"cmov {m}")
            src, dst = ops
            if self.cond(_LIFT_COND[_CMOV[base]]):
                self.write(inst, dst, w, self.read(inst, src, w))
        elif m in ("cltq", "cdqe"):
            self.reg[RAX] = sx(self.reg[RAX] & M32, 32) & M64
        elif m in ("cwtl", "cwde"):
            self.reg[RAX] = (self.reg[RAX] & ~M32) | (
                sx(self.reg[RAX] & M16, 16) & M32)
        elif m in ("cltd", "cdq"):
            self.reg[RDX] = (self.reg[RDX] & ~M32) | (
                M32 if self.reg[RAX] & 0x80000000 else 0)
        elif m in ("cqto", "cqo"):
            self.reg[RDX] = M64 if self.reg[RAX] >> 63 else 0
        elif m.rstrip("lqwb") in ("div", "idiv"):
            stem = m.rstrip("lqwb")
            b = self.read(inst, ops[0], w)
            if b == 0:
                raise StopEmu("div by zero")
            if w == 32:
                a = ((self.reg[RDX] & M32) << 32) | (self.reg[RAX] & M32)
            else:
                a = ((self.reg[RDX] & M64) << 64) | (self.reg[RAX] & M64)
            if stem == "idiv":
                aa = a - (1 << (2 * w)) if a >> (2 * w - 1) else a
                bb = sx(b, w)
                q = abs(aa) // abs(bb)    # exact trunc-toward-zero
                if (aa < 0) != (bb < 0):
                    q = -q
                r = aa - q * bb
                if not (-(1 << (w - 1)) <= q <= (1 << (w - 1)) - 1):
                    raise StopEmu("div overflow")   # x86 #DE
            else:
                q, r = divmod(a, b)
                if q > (1 << w) - 1:
                    raise StopEmu("div overflow")   # x86 #DE
            if w == 32:
                self.reg[RAX] = q & M32   # 32-bit writes zero-extend
                self.reg[RDX] = r & M32
            else:
                self.reg[RAX] = q & M64
                self.reg[RDX] = r & M64
        elif m.startswith("cmpxchg") and len(ops) == 2:
            # if rax(w) == dst: dst := src, ZF=1  else rax := dst, ZF=0
            # (cmpxchg8b/16b take one operand and fall through to StopEmu)
            src, dst = ops
            cur = self.read(inst, dst, w)
            acc = self.reg[RAX] & mask
            self.set_flags_sub(acc, cur, w)
            if acc == cur:
                self.write(inst, dst, w, self.read(inst, src, w))
            else:
                self.rset(Operand("reg", reg=RAX, width=w), cur)
        elif m.startswith("set") and m[3:] in _JCC_SET:
            v = 1 if self.cond(_JCC_SET[m[3:]]) else 0
            self.write(inst, ops[0], 8, v)
        elif m in ("xchg", "xchgl", "xchgq"):
            a, b = ops
            va = self.read(inst, a, w)
            vb = self.read(inst, b, w)
            self.write(inst, a, w, vb)
            self.write(inst, b, w, va)
        elif m == "syscall" and self.do_syscalls:
            nr = self.reg[RAX]
            if nr == 1 and self.reg[RDI] == 1:            # write(1, buf, n)
                n = self.reg[RDX]
                if n > (1 << 20):
                    raise StopEmu("write size")
                buf = bytes(self.load(self.reg[RSI] + i, 1)
                            for i in range(n))
                self.stdout += buf
                self.reg[RAX] = n
            elif nr in (60, 231):                          # exit/exit_group
                raise ExitedEmu(self.reg[RDI] & 0xFF)
            else:
                raise StopEmu(f"syscall {nr}")
            # kernel return clobbers: rcx = rip after syscall, r11 = rflags
            self.reg[RCX] = next_pc & M64
            self.reg[R11] = 0x202
        else:
            raise StopEmu(f"unsupported {m}")
        self.pc = next_pc & M64

    # -- run ---------------------------------------------------------------

    def canonical(self) -> np.ndarray:
        row = np.zeros(18, dtype=np.uint64)
        for i in range(16):
            row[i] = self.reg[i]
        row[16] = self.pc
        row[17] = 0x202                   # IF set, DF clear
        return row

    def run(self, max_steps: int) -> EmuResult:
        rows = [self.canonical()]
        begin = self.pc
        stop = "max_steps"
        for _ in range(max_steps):
            try:
                self.step()
            except StopEmu as e:
                # rows[-1] is already the clean state AT the boundary (the
                # unsupported instruction never executed) — exactly the
                # NativeTrace contract's "last record = state at end"
                stop = str(e)
                break
            rows.append(self.canonical())
        steps = np.stack(rows)
        regions = [(r.vaddr, bytes(r.buf)) for r in self.regions]
        # NativeTrace contract: steps[n_macro] is the state at the end
        # marker; regions snapshot the *initial* image — rebuild from the
        # originals the caller seeded (they were copied into Region bufs),
        # so hand back the caller's originals via from_snapshot instead.
        nt = NativeTrace(begin=begin, end=int(steps[-1][16]),
                         steps=steps, regions=regions)
        return EmuResult(nt=nt, steps=len(steps) - 1, stop_reason=stop,
                         stop_pc=int(steps[-1][16]))


class ProgramResult(NamedTuple):
    kind: str            # "exit" | "hang" | "stop:<reason>"
    stdout: bytes
    exit_code: int | None
    steps: int


def run_program(insts: dict[int, Inst], regs: np.ndarray,
                regions: list[tuple[int, bytes]], pc: int,
                max_steps: int = 2_000_000,
                fault: "tuple | None" = None,
                fs_base: int = 0) -> ProgramResult:
    """Whole-program (perturbed) re-execution to exit — the 64-bit
    CheckerCPU: classify a fault by the same program-outcome criteria the
    host-silicon oracle uses (stdout + exit status, tools/hostsfi.cc),
    with wrong paths executed for real rather than frozen.

    ``fault`` = (step, reg, bit) flips GPR ``reg`` bit ``bit`` (bit ∈
    [0,64) — the full 64-bit register, including the upper half the
    32-bit replay projection cannot track) after ``step`` dynamic
    instructions, exactly like the ptrace oracle's PTRACE_SETREGS flip."""
    emu = Emulator(insts, regs, regions, pc, do_syscalls=True,
                   fs_base=fs_base)
    steps = 0
    try:
        while steps < max_steps:
            if fault is not None and steps == fault[0]:
                if fault[1] >= 16:
                    # xmm[reg-16] low lane, the FP-bank coordinate space
                    # (hostsfi's PTRACE_SETFPREGS flip)
                    emu.xmm[fault[1] - 16] ^= (1 << fault[2])
                else:
                    emu.reg[fault[1]] ^= (1 << fault[2])
                    emu.reg[fault[1]] &= M64
            # bulk rep execution up to the next boundary we must observe
            # exactly: the fault-injection step, or the hang budget —
            # per-iteration stepping stays the unit of accounting
            limit = max_steps - steps
            if fault is not None and steps < fault[0]:
                limit = min(limit, fault[0] - steps)
            steps += emu.step(limit)
        return ProgramResult("hang", bytes(emu.stdout), None, steps)
    except ExitedEmu as e:
        return ProgramResult("exit", bytes(emu.stdout), e.code, steps)
    except StopEmu as e:
        return ProgramResult(f"stop:{e}", bytes(emu.stdout), None, steps)


def emulate_window(binary: str, regs: np.ndarray,
                   regions: list[tuple[int, bytes]], pc: int,
                   max_steps: int = 200_000,
                   insts: "dict[int, Inst] | None" = None) -> EmuResult:
    """Decode + run; regions are (vaddr, bytes) of the initial image.

    ``insts`` accepts a pre-parsed static decode so callers that also lift
    (warm.window_from_snapshot_lifted) disassemble once.

    NOTE the returned ``nt.regions`` must be the INITIAL image (the lifter
    snapshots memory at window start); Emulator.run hands back post-run
    buffers, so re-seed them here."""
    if insts is None:
        insts = static_decode(binary)
    emu = Emulator(insts, regs, regions, pc)
    res = emu.run(max_steps)
    return res._replace(nt=res.nt._replace(
        regions=[(v, d) for v, d in regions]))
