"""Journaled streaming ingest: binary in, campaign-ready plan out.

The reference drives campaigns straight from a workload binary (boot →
capture → fast-forward → measure); here the same driver is decomposed
into five resumable, WAL-journaled stages —

    capture   verify the stored binary's digest, resolve the
              kernel_begin/kernel_end markers, statically decode the
              ELF, and run the ptrace tracer; the raw capture becomes a
              durable store payload
    lift      macro→µop lift of the full capture with the lifter's
              register/branch self-check against the host capture (the
              oracle); a lift rate below the floor is divergence
    liveness  first-access liveness masks over the capture
    simpoint  BBV profile + k-means representative selection
    window    per-representative emulate→snapshot→run→lift, each window
              an independent unit lifted in parallel, with a boundary
              golden (start registers, pc, region digest) per window

— each writing into the content-digest-keyed ``ArtifactStore``
(``store.py``).  Stage completion is recorded in a per-tenant
write-ahead journal (``ingest_stage`` / ``ingest_done`` /
``ingest_quarantine`` — journaled BEFORE in-memory state is trusted,
GL201/GL202-certified) so a hard kill at any boundary resumes from the
last durable stage: replay restores the ledger, and every stage
re-verifies its store artifacts before being skipped, so a journal that
is AHEAD of a torn store payload simply re-runs the stage.

Poison vs damage: a store artifact that fails verification is a cache
MISS (recompute); a submitted binary whose bytes no longer hash to its
claimed digest, an unparseable ELF, a markerless workload, or lift
divergence vs the host oracle is POISON — the pipeline raises
``IngestQuarantine``, the journal records it durably, and the scheduler
parks the tenant in ``quarantined`` with the evidence doc instead of
retrying or taking the pod down.

Import discipline: jax-free at module import (the scheduler spool path
must stay light); the lifter/emulator enter inside the stage functions.
"""

from __future__ import annotations

import os
import subprocess

from shrewd_tpu.ingest.store import ArtifactStore, axes_key
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.service.journal import FleetJournal
from shrewd_tpu.utils import debug

#: the journaled stage order (the reference's boot→capture→fast-forward
#: driver, decomposed); a stage's index is its chaos ordinal
#: (``at_stage`` in ``corrupt_binary`` / ``kill_during_lift`` plans)
STAGES = ("capture", "lift", "liveness", "simpoint", "window")

WAL_NAME = "ingest.jsonl"

#: the ingest axes and their defaults — normalized before keying the
#: store, so ``{}`` and an explicit-defaults dict share artifacts
DEFAULT_AXES = {
    "interval": 2000,        # macro-ops per BBV interval
    "k": 3,                  # SimPoint clusters requested
    "max_steps": 200_000,    # capture macro-op budget
    "seed": 0,               # SimPoint k-means seed
    "min_lift_rate": 0.25,   # lift-divergence quarantine floor
    "max_workers": 4,        # parallel window lifts
    "preprocess": False,     # terminal chunk-preprocess stage (see below)
    "chunk": 65536,          # chunk length S for the preprocess stage
}


def normalize_axes(axes: dict | None) -> dict:
    axes = dict(axes or {})
    unknown = sorted(set(axes) - set(DEFAULT_AXES))
    if unknown:
        raise ValueError(f"unknown ingest axes {unknown} "
                         f"(one of {sorted(DEFAULT_AXES)})")
    out = dict(DEFAULT_AXES)
    out.update(axes)
    return out


class IngestQuarantine(RuntimeError):
    """A submission-is-poison verdict from an ingest stage: the binary,
    not the pod, is at fault — the scheduler quarantines immediately
    (no retry budget: a deterministic rejection cannot heal)."""

    def __init__(self, stage: str, reason: str):
        self.stage = stage
        self.reason = reason
        super().__init__(f"ingest {stage}: {reason}")


class IngestPipeline:
    """One tenant's journaled ingest run over a shared artifact store.

    ``outdir`` is the tenant's ``ingest/`` namespace (it rides tenant
    checkpoint copies, so gateway migration moves the WAL with the
    tenant); ``store`` is shared — across tenants, and across pods when
    the federation threads one ``store_dir`` through its schedulers."""

    def __init__(self, outdir: str, store: ArtifactStore, digest: str,
                 axes: dict | None = None, chaos=None):
        os.makedirs(outdir, exist_ok=True)
        self.outdir = outdir
        self.store = store
        self.digest = digest
        self.axes = normalize_axes(axes)
        self.key = axes_key(self.axes)
        self.chaos = chaos
        #: journaled ledger (mutated only via ``_apply_record``)
        self.stage_done: dict = {}
        self.plan_doc: dict | None = None
        self.quarantine_rec: dict | None = None
        #: work counters (the dedup/warm-start pins read these)
        self.captures = 0
        self.lifts = 0
        self._nt = None
        self._insts = None
        jp = os.path.join(outdir, WAL_NAME)
        records, torn, _valid = (FleetJournal.replay_path(jp)
                                 if os.path.exists(jp) else ([], 0, 0))
        self.journal = FleetJournal(jp)
        self.torn_dropped = torn
        for r in records:
            self._apply_record(r)

    # --- the WAL contract -------------------------------------------------

    def _jlog(self, kind: str, data: dict | None = None) -> None:
        """Journal-then-apply: the transition is durable before any
        in-memory ledger trusts it (GL201), and replay shares the exact
        mutation path (``_apply_record``, GL202)."""
        rec = {"kind": kind}
        if data:
            rec.update(data)
        self.journal.append(kind, data)
        self._apply_record(rec)

    def _apply_record(self, r: dict) -> None:
        kind = r.get("kind")
        if kind == "ingest_stage":
            self.stage_done[r["stage"]] = {
                "ordinal": int(r.get("ordinal", -1)),
                "cached": bool(r.get("cached", False))}
        elif kind == "ingest_done":
            self.plan_doc = dict(r.get("plan") or {})
        elif kind == "ingest_quarantine":
            self.quarantine_rec = {"stage": r.get("stage", ""),
                                   "error": r.get("error", "")}

    # --- verification helpers ---------------------------------------------

    def _check_binary(self, stage: str) -> None:
        """Every stage re-verifies the stored binary before touching it:
        rot between stages (chaos ``corrupt_binary``, real bit-rot) must
        quarantine AT the stage that would consume the bad bytes."""
        if not self.store.verify_binary(self.digest):
            raise IngestQuarantine(
                stage, f"stored binary no longer hashes to its claimed "
                       f"digest {self.digest[:12]} (rot or tamper)")

    def _chaos_gate(self, ordinal: int) -> None:
        if self.chaos is None:
            return
        from shrewd_tpu import chaos as chaos_mod

        if self.chaos.take_corrupt_binary(ordinal) is not None:
            chaos_mod.rot_file(self.store.binary_path(self.digest))
        self.chaos.maybe_kill_during_lift(ordinal)

    def _stage_ok(self, stage: str) -> bool:
        """A stage is durably complete iff its store document (and every
        payload it vouches for) verifies — the journal alone is never
        enough, so a journal ahead of a torn store re-runs the stage."""
        return self.store.get_doc(self.digest, self.key, stage) is not None

    def _plan_probe(self) -> dict | None:
        """The O(1) warm start: a verified terminal ``plan`` document
        (its payload table covers every window trace)."""
        return self.store.get_doc(self.digest, self.key, "plan")

    def _stage_list(self) -> tuple:
        """The journaled stage order for THIS run's axes: the optional
        terminal ``preprocess`` stage (chunk-window preprocessing for the
        chunked replay engines) rides the same WAL/store certification as
        the five base stages — same ``ingest_stage`` journal kind, same
        doc-verified resume — so GL201/GL202 hold for it with no new
        record kinds."""
        if self.axes.get("preprocess"):
            return STAGES + ("preprocess",)
        return STAGES

    # --- the run loop -----------------------------------------------------

    def run(self) -> dict:
        """Execute (or resume, or warm-start) the pipeline; returns the
        terminal plan document.  Raises ``IngestQuarantine`` — durably
        journaled first — when the submission is poison."""
        if self.quarantine_rec is not None:
            # the poison verdict is durable: never re-run a quarantined
            # submission (the binary cannot have healed)
            raise IngestQuarantine(self.quarantine_rec["stage"],
                                   self.quarantine_rec["error"])
        if self.plan_doc is not None and self._plan_probe() is not None:
            return self.plan_doc
        probe = self._plan_probe()
        if probe is None:
            # single-flight: concurrent submissions of the same
            # (digest, axes) serialize here; the loser re-probes and
            # warm-starts from the winner's artifacts
            with self.store.lock(self.digest, self.key):
                probe = self._plan_probe()
                if probe is None:
                    self._run_stages()
                    return self.plan_doc
        # warm start — journal the cache hit so the tenant's WAL is
        # self-contained evidence of where its windows came from
        for ordinal, stage in enumerate(self._stage_list()):
            self._jlog("ingest_stage", {"stage": stage,
                                        "ordinal": ordinal,
                                        "cached": True})
        self._jlog("ingest_done", {"plan": probe})
        obs_trace.tracer().emit("ingest_warm_start", cat="ingest",
                                digest=self.digest[:12])
        debug.dprintf("Ingest", "warm start for %s (0 lifts)",
                      self.digest[:12])
        return self.plan_doc

    def _run_stages(self) -> None:
        try:
            for ordinal, stage in enumerate(self._stage_list()):
                if stage in self.stage_done and self._stage_ok(stage):
                    continue          # resumed past a durable stage
                cached = self._stage_ok(stage)
                if not cached:
                    self._chaos_gate(ordinal)
                    self._check_binary(stage)
                    getattr(self, "_stage_" + stage)()
                self._jlog("ingest_stage", {"stage": stage,
                                            "ordinal": ordinal,
                                            "cached": cached})
                obs_trace.tracer().emit("ingest_stage", cat="ingest",
                                        stage=stage, cached=cached)
            plan = self._build_plan_doc()
            self.store.put_doc(self.digest, self.key, "plan", plan)
            self._jlog("ingest_done", {"plan": plan})
        except IngestQuarantine as q:
            # the verdict is durable BEFORE it propagates: a recovery
            # after the kill replays straight back into quarantine
            self._jlog("ingest_quarantine", {"stage": q.stage,
                                             "error": str(q)})
            raise

    def resolved_plan(self, base_plan: dict) -> dict:
        """Merge the scenario axes of the submitted plan with the
        store-resident windows: the result is an ordinary pre-lifted
        ``CampaignPlan`` document (TraceFileSpec per window), which is
        exactly what makes binary-path tallies bit-identical to the
        plan-path ones."""
        if self.plan_doc is None:
            raise RuntimeError("ingest pipeline has not completed")
        plan = {k: v for k, v in dict(base_plan).items()
                if k != "simpoints"}
        plan["simpoints"] = [
            {"type": "TraceFileSpec", "name": e["name"],
             "path": self.store.payload_path(self.digest, self.key,
                                             e["file"])}
            for e in self.plan_doc["simpoints"]]
        return plan

    # --- stages -----------------------------------------------------------

    def _binary(self) -> str:
        return self.store.binary_path(self.digest)

    def _scratch(self, name: str) -> str:
        # every scratch name carries ".tmp." — pre-rename staging is
        # non-durable, and crash-point snapshots scrub on that marker
        return os.path.join(self.outdir, f"{os.getpid()}.{name}")

    def _load_capture(self):
        """Parse the durable capture once per process (stages share it);
        the artifact store remains the source of truth across crashes."""
        if self._nt is None:
            from shrewd_tpu.ingest.lift import (read_nativetrace,
                                                static_decode)

            self._nt = read_nativetrace(
                self.store.payload_path(self.digest, self.key,
                                        "capture.bin"))
            self._insts = static_decode(self._binary())
        return self._nt, self._insts

    def _stage_capture(self) -> None:
        from shrewd_tpu.ingest import hostdiff
        from shrewd_tpu.ingest.lift import read_nativetrace, static_decode

        binary = self._binary()
        try:
            begin, end = hostdiff.elf_markers(binary)
        except ValueError as e:
            raise IngestQuarantine("capture", str(e))
        try:
            static_decode(binary)
        except Exception as e:  # noqa: BLE001 — an undecodable text
            # section is a property of the submission, not the pod
            raise IngestQuarantine("capture",
                                   f"static decode failed: {e}")
        tracer = hostdiff.build_tracer()
        scratch = self._scratch("capture.tmp.bin")
        try:
            subprocess.run(
                [str(tracer), scratch, f"{begin:x}", f"{end:x}",
                 str(int(self.axes["max_steps"])), binary],
                check=True, capture_output=True, text=True)
        except (OSError, subprocess.CalledProcessError) as e:
            tail = (getattr(e, "stderr", "") or str(e)).strip()[-200:]
            raise IngestQuarantine("capture", f"capture failed: {tail}")
        try:
            nt = read_nativetrace(scratch)
        except (OSError, ValueError) as e:
            raise IngestQuarantine("capture", f"bad capture: {e}")
        sha = self.store.commit_payload(scratch, self.digest, self.key,
                                        "capture.bin")
        self.store.put_doc(self.digest, self.key, "capture", {
            "begin": begin, "end": end,
            "steps": int(nt.steps.shape[0] - 1),
            "fs_base": int(nt.fs_base),
            "payloads": {"capture.bin": sha}})
        self.captures += 1

    def _stage_lift(self) -> None:
        from shrewd_tpu.ingest.lift import lift
        from shrewd_tpu.trace import format as tf

        nt, insts = self._load_capture()
        try:
            trace, meta = lift("<ingest>", self._binary(), nt=nt,
                               insts=insts)
        except Exception as e:  # noqa: BLE001 — the lifter rejecting a
            # capture is a verdict on the submission
            raise IngestQuarantine("lift", f"lift failed: {e}")
        rate = float(meta["stats"]["lift_rate"])
        floor = float(self.axes["min_lift_rate"])
        if rate < floor:
            raise IngestQuarantine(
                "lift", f"lift divergence vs host oracle: lift_rate "
                        f"{rate:.4f} < floor {floor}")
        tmp = self._scratch("full.tmp.npz")
        tf.save(tmp, trace, meta)
        sha = self.store.commit_payload(tmp, self.digest, self.key,
                                        "full.npz")
        self.store.put_doc(self.digest, self.key, "lift", {
            "uops": int(trace.n), "lift_rate": rate,
            "payloads": {"full.npz": sha}})
        self.lifts += 1

    def _stage_liveness(self) -> None:
        import numpy as np

        from shrewd_tpu.ingest import liveness

        nt, insts = self._load_capture()
        lv = liveness.analyze(nt, insts)
        tmp = self._scratch("liveness.tmp.npz")
        np.savez_compressed(
            tmp, reg_live=np.asarray(lv.reg_live, dtype=bool),
            mem_live32=np.asarray(sorted(lv.mem_live32),
                                  dtype=np.uint64))
        sha = self.store.commit_payload(tmp, self.digest, self.key,
                                        "liveness.npz")
        self.store.put_doc(self.digest, self.key, "liveness", {
            "steps": int(lv.steps), "truncated": bool(lv.truncated),
            "unknown_insts": int(lv.unknown_insts),
            "live_words": len(lv.mem_live32),
            "payloads": {"liveness.npz": sha}})

    def _stage_simpoint(self) -> None:
        import numpy as np

        from shrewd_tpu.ingest.simpoint import (bbv_profile,
                                                choose_simpoints)

        nt, _insts = self._load_capture()
        steps = nt.steps[:-1]
        profile = bbv_profile(steps[:, 16],
                              int(self.axes["interval"]))
        sps = choose_simpoints(profile, int(self.axes["k"]),
                               seed=int(self.axes["seed"]))
        tmp = self._scratch("clusters.tmp.npz")
        np.savez_compressed(tmp, intervals=sps.intervals,
                            weights=sps.weights, labels=sps.labels)
        sha = self.store.commit_payload(tmp, self.digest, self.key,
                                        "clusters.npz")
        self.store.put_doc(self.digest, self.key, "simpoint", {
            "interval": int(self.axes["interval"]),
            "k": int(self.axes["k"]), "seed": int(self.axes["seed"]),
            "n_intervals": int(len(sps.labels)),
            "intervals": [int(x) for x in sps.intervals],
            "weights": [float(x) for x in sps.weights],
            "payloads": {"clusters.npz": sha}})

    def _stage_window(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        import hashlib

        from shrewd_tpu.ingest.emu import Emulator, StopEmu, elf_regions
        from shrewd_tpu.ingest.lift import lift
        from shrewd_tpu.trace import format as tf

        nt, insts = self._load_capture()
        sdoc = self.store.get_doc(self.digest, self.key, "simpoint")
        if sdoc is None:
            raise RuntimeError("window stage reached with no durable "
                               "simpoint artifact")
        binary = self._binary()
        interval = int(sdoc["interval"])
        steps = nt.steps[:-1]
        regions = [(v, d) for v, d in nt.regions]
        regions += elf_regions(binary)

        def _one(i: int, rep: int, weight: float):
            # each representative window is an independent unit: fresh
            # emulator, own snapshot, own lift — safe to run in parallel
            start = rep * interval
            length = min(interval, len(steps) - start)
            emu = Emulator(insts, nt.steps[0][:16], regions,
                           int(nt.steps[0][16]), fs_base=nt.fs_base)
            try:
                for _ in range(start):
                    emu.step()
            except StopEmu as e:
                raise IngestQuarantine(
                    "window", f"emulation to window {i} start failed: "
                              f"{e}")
            snap_regions = [(r.vaddr, bytes(r.buf))
                            for r in emu.regions]
            res = emu.run(length)
            trace, meta = lift(
                "<ingest>", binary,
                nt=res.nt._replace(regions=snap_regions), insts=insts)
            meta["simpoint_interval"] = rep
            meta["simpoint_weight"] = weight
            meta["simpoint_start_step"] = start
            tmp = self._scratch(f"win{i}.tmp.npz")
            tf.save(tmp, trace, meta)
            rh = hashlib.sha256()
            for vaddr, buf in snap_regions:
                rh.update(vaddr.to_bytes(8, "little"))
                rh.update(buf)
            golden = {"interval": rep, "weight": weight,
                      "start_step": start,
                      "start_regs": [int(x) for x in res.nt.steps[0][:16]],
                      "start_pc": int(res.nt.steps[0][16]),
                      "regions_sha256": rh.hexdigest(),
                      "uops": int(trace.n)}
            return i, tmp, golden

        reps = [(i, int(rep), float(w)) for i, (rep, w) in
                enumerate(zip(sdoc["intervals"], sdoc["weights"]))]
        with ThreadPoolExecutor(
                max_workers=max(1, int(self.axes["max_workers"]))) as ex:
            results = list(ex.map(lambda a: _one(*a), reps))
        payloads = {}
        sims = []
        for i, tmp, golden in results:
            fname = f"win{i}.npz"
            sha = self.store.commit_payload(tmp, self.digest, self.key,
                                            fname)
            payloads[fname] = sha
            self.store.put_doc(self.digest, self.key, f"win{i}",
                               {**golden, "payloads": {fname: sha}})
            sims.append({"name": f"sp{golden['interval']}",
                         "file": fname,
                         "interval": golden["interval"],
                         "weight": golden["weight"],
                         "start_step": golden["start_step"]})
            self.lifts += 1
        self.store.put_doc(self.digest, self.key, "window", {
            "simpoints": sims, "payloads": dict(payloads)})

    def _stage_preprocess(self) -> None:
        """Optional terminal stage (axes ``preprocess=True``): build the
        chunked engines' preprocessed window (ops/window.py — NOP-padded
        SoA chunk arrays + golden boundary states at chunk length
        ``axes['chunk']``) for every lifted window and persist it
        content-addressed under the WINDOW TRACE's digest.  Campaigns and
        federated pods then open it mmap'd in O(1) — zero lifts, zero
        re-preprocessing — with chunks materializing lazily as the wave
        driver touches them.  The stage document records each window's
        (trace digest, S) store address; the heavyweight array payloads
        live under the trace digest so two binaries lifting to the same
        window share one copy."""
        from shrewd_tpu.ops.chunked import preprocess_window
        from shrewd_tpu.ops.trial import TrialKernel
        from shrewd_tpu.trace import format as tf

        wdoc = self.store.get_doc(self.digest, self.key, "window")
        if wdoc is None:
            raise RuntimeError("preprocess stage reached with no durable "
                               "window artifact")
        S = int(self.axes["chunk"])
        entries = []
        for e in wdoc["simpoints"]:
            path = self.store.payload_path(self.digest, self.key,
                                           e["file"])
            trace, _meta = tf.load(path)
            win = preprocess_window(TrialKernel(trace), S,
                                    store=self.store)
            entries.append({"name": e["name"], "file": e["file"],
                            "trace_digest": win.trace_digest,
                            "S": int(win.S), "C": int(win.C),
                            "uops": int(win.n)})
        self.store.put_doc(self.digest, self.key, "preprocess", {
            "chunk": S, "windows": entries})

    def _build_plan_doc(self) -> dict:
        wdoc = self.store.get_doc(self.digest, self.key, "window")
        if wdoc is None:
            raise RuntimeError("plan build reached with no durable "
                               "window artifact")
        return {"digest": self.digest, "axes": dict(self.axes),
                "simpoints": list(wdoc["simpoints"]),
                "payloads": dict(wdoc["payloads"])}
