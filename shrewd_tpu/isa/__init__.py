from shrewd_tpu.isa import semantics, uops

__all__ = ["semantics", "uops"]
