"""The µop dataflow ISA.

The replay substrate of the framework: a compact RISC-style micro-op set rich
enough to follow a flipped bit through register/memory dataflow to
architectural outputs.  It plays the role gem5's per-ISA ``StaticInst``
hierarchy plays for execution semantics (reference ``src/cpu/static_inst.hh:88``
and the ISA-DSL-generated ``execute()`` bodies), deliberately reduced to the
dataflow algebra SFI classification needs (SURVEY §7 "Hard parts" #4: trace
replay reduces classification to dataflow over recorded operands).

Design constraints (TPU-first):
- fixed-width 32-bit data path, ``uint32`` values everywhere (packed SoA
  arrays, VPU-friendly; 64-bit extension = paired words);
- a closed opcode set evaluated by *branchless select* inside ``lax.scan`` —
  no data-dependent Python control flow;
- every µop's timing proxy is its trace index (1-IPC issue model).

OpClasses mirror the reference's FU capability classes
(``src/cpu/FuncUnitConfig.py``, ``src/cpu/o3/fu_pool.cc:177-294``) at the
granularity the shadow-FU model needs.
"""

from __future__ import annotations

import numpy as np

# --- opcodes ---------------------------------------------------------------

NOP = 0
ADD = 1      # rd = rs1 + rs2
SUB = 2      # rd = rs1 - rs2
AND = 3
OR = 4
XOR = 5
SLL = 6      # rd = rs1 << (rs2 & 31)
SRL = 7      # logical right shift
SRA = 8      # arithmetic right shift
ADDI = 9     # rd = rs1 + imm
ANDI = 10
ORI = 11
XORI = 12
LUI = 13     # rd = imm
MUL = 14     # rd = low32(rs1 * rs2)
SLT = 15     # rd = (signed) rs1 < rs2
SLTU = 16    # rd = (unsigned) rs1 < rs2
# Division µops carry x86 #DE semantics: rs2 == 0 (and signed overflow
# INT_MIN/-1) TRAPS the trial (DUE) — the host oracle sees SIGFPE there
# (tools/hostsfi.cc), so faithful classification requires a real trap.
DIV = 17     # rd = (signed) rs1 / rs2, trunc toward zero
REM = 18     # rd = (signed) rs1 % rs2 (sign of dividend)
DIVU = 19    # rd = (unsigned) rs1 / rs2
REMU = 20    # rd = (unsigned) rs1 % rs2
LOAD = 21    # rd = mem[rs1 + imm]
STORE = 22   # mem[rs1 + imm] = rs2
BEQ = 23     # branch if rs1 == rs2
BNE = 24
BLT = 25     # signed
BGE = 26     # signed
# FP µops: f32 values in the same u32 register file (bitcast).  Semantics
# are IEEE round-to-nearest with two platform-independence canonicalizations
# so every backend (XLA CPU, TPU, C++ golden, scalar python) computes the
# same BITS: subnormal inputs/outputs flush to signed zero (the accelerator
# FTZ behavior) and every NaN result is the canonical quiet NaN 0x7FC00000
# (x86 would propagate payloads; payload propagation is not portable).
FADD = 27
FSUB = 28
FMUL = 29
FDIV = 30    # IEEE: x/0 = ±inf, 0/0 = NaN — no trap (unlike integer DIV)
MULHU = 31   # rd = high32(rs1 * rs2), unsigned — the wide half of x86's
             # 64-bit multiply, which compilers emit for every unsigned
             # divide-by-constant (magic-number multiply + shr >= 32)

N_OPCODES = 32

OPCODE_NAMES = [
    "nop", "add", "sub", "and", "or", "xor", "sll", "srl", "sra",
    "addi", "andi", "ori", "xori", "lui", "mul", "slt", "sltu",
    "div", "rem", "divu", "remu",
    "load", "store", "beq", "bne", "blt", "bge",
    "fadd", "fsub", "fmul", "fdiv", "mulhu",
]

# --- op classes (shadow-FU capability granularity) -------------------------

OC_INT_ALU = 0    # add/sub/logic/shift/compare/branch-compare
OC_INT_MULT = 1   # MUL + the DIV family (the reference's IntMultDiv unit)
OC_MEM_READ = 2   # LOAD (address-generation + access)
OC_MEM_WRITE = 3  # STORE
OC_NONE = 4       # NOP
OC_FP_ALU = 5     # FADD/FSUB (reference FP_ALU, FuncUnitConfig.py)
OC_FP_MULT = 6    # FMUL/FDIV (reference FP_MultDiv)

N_OPCLASSES = 7
OPCLASS_NAMES = ["IntAlu", "IntMult", "MemRead", "MemWrite", "No_OpClass",
                 "FloatAdd", "FloatMultDiv"]

_OPCLASS_TABLE = np.array([
    OC_NONE,                                      # NOP
    OC_INT_ALU, OC_INT_ALU, OC_INT_ALU, OC_INT_ALU, OC_INT_ALU,   # ADD..XOR
    OC_INT_ALU, OC_INT_ALU, OC_INT_ALU,           # shifts
    OC_INT_ALU, OC_INT_ALU, OC_INT_ALU, OC_INT_ALU, OC_INT_ALU,   # imm ops
    OC_INT_MULT,                                  # MUL
    OC_INT_ALU, OC_INT_ALU,                       # SLT/SLTU
    OC_INT_MULT, OC_INT_MULT, OC_INT_MULT, OC_INT_MULT,  # DIV..REMU
    # (the reference's IntMultDiv unit executes both, FuncUnitConfig.py)
    OC_MEM_READ, OC_MEM_WRITE,                    # LOAD/STORE
    OC_INT_ALU, OC_INT_ALU, OC_INT_ALU, OC_INT_ALU,  # branches
    OC_FP_ALU, OC_FP_ALU, OC_FP_MULT, OC_FP_MULT,    # FADD..FDIV
    OC_INT_MULT,                                     # MULHU
], dtype=np.int32)


def opclass_of(opcodes: np.ndarray) -> np.ndarray:
    """Vectorized opcode → OpClass map."""
    return _OPCLASS_TABLE[np.asarray(opcodes)]


# --- structural predicates (host-side; device code precomputes these) ------

def writes_dest(op: np.ndarray) -> np.ndarray:
    op = np.asarray(op)
    return (((op >= ADD) & (op <= REMU)) | (op == LOAD) | is_fp(op)
            | (op == MULHU))


def is_div(op):
    op = np.asarray(op)
    return (op >= DIV) & (op <= REMU)


def is_fp(op):
    op = np.asarray(op)
    return (op >= FADD) & (op <= FDIV)


def is_load(op):
    return np.asarray(op) == LOAD


def is_store(op):
    return np.asarray(op) == STORE


def is_branch(op):
    op = np.asarray(op)
    return (op >= BEQ) & (op <= BGE)


def is_mem(op):
    op = np.asarray(op)
    return (op == LOAD) | (op == STORE)


def uses_src1(op):
    op = np.asarray(op)
    return (op != NOP) & (op != LUI)


def uses_src2(op):
    op = np.asarray(op)
    return (((op >= ADD) & (op <= SRA)) | (op == MUL) | (op == MULHU)
            | (op == SLT) | (op == SLTU) | is_div(op) | is_fp(op)
            | (op == STORE) | is_branch(op))
