"""Scalar reference semantics for the µop ISA.

The host-side golden interpreter: the analog of the reference's CheckerCPU
(``src/cpu/checker/cpu.hh``) — an independent, simple implementation of the
same ISA semantics that the batched device kernels are differentially tested
against.  Also used by the trace generator to resolve branch outcomes and
golden values while generating.

All values are Python ints masked to 32 bits (uint32 semantics); signed
interpretation is explicit.
"""

from __future__ import annotations

import numpy as np

from shrewd_tpu.isa import uops as U

M32 = 0xFFFFFFFF


def _s32(x: int) -> int:
    """Reinterpret uint32 as signed."""
    x &= M32
    return x - (1 << 32) if x & 0x80000000 else x


_QNAN = 0x7FC00000
_FLT_MIN_EXP = 0x00800000       # smallest normal magnitude, as bits


def _fp_flush(bits: int) -> int:
    """Subnormal → signed zero (the FTZ half of the FP µop contract)."""
    if 0 < (bits & 0x7FFFFFFF) < _FLT_MIN_EXP:
        return bits & 0x80000000
    return bits


def _fp_op(op: int, a: int, b: int) -> int:
    """f32 bits × f32 bits → canonical f32 bits (see uops.py FP contract:
    IEEE RN, FTZ on inputs and outputs, canonical quiet NaN)."""
    af = np.uint32(_fp_flush(a)).view(np.float32)
    bf = np.uint32(_fp_flush(b)).view(np.float32)
    with np.errstate(all="ignore"):
        if op == U.FADD:
            r = np.float32(af + bf)
        elif op == U.FSUB:
            r = np.float32(af - bf)
        elif op == U.FMUL:
            r = np.float32(af * bf)
        else:
            r = np.float32(np.divide(af, bf, dtype=np.float32))
    if np.isnan(r):
        return _QNAN
    return _fp_flush(int(np.float32(r).view(np.uint32)))


def alu(op: int, a: int, b: int, imm: int) -> int:
    """Compute the µop's primary result (uint32).

    For memory ops the 'result' is the effective address (address-generation
    output); for branches it is the comparison outcome (0/1).
    """
    a &= M32
    b &= M32
    imm &= M32
    if op == U.NOP:
        return 0
    if op == U.ADD:
        return (a + b) & M32
    if op == U.SUB:
        return (a - b) & M32
    if op == U.AND:
        return a & b
    if op == U.OR:
        return a | b
    if op == U.XOR:
        return a ^ b
    if op == U.SLL:
        return (a << (b & 31)) & M32
    if op == U.SRL:
        return a >> (b & 31)
    if op == U.SRA:
        return (_s32(a) >> (b & 31)) & M32
    if op == U.ADDI:
        return (a + imm) & M32
    if op == U.ANDI:
        return a & imm
    if op == U.ORI:
        return a | imm
    if op == U.XORI:
        return a ^ imm
    if op == U.LUI:
        return imm
    if op == U.MUL:
        return (a * b) & M32
    if op == U.MULHU:
        return ((a * b) >> 32) & M32
    if op == U.SLT:
        return 1 if _s32(a) < _s32(b) else 0
    if op == U.SLTU:
        return 1 if a < b else 0
    if op in (U.DIV, U.REM):
        # x86 #DE cases (b==0, INT_MIN/-1) are TRAPS, resolved by the
        # kernels' trap path; the ALU result for them is defined as 0 so
        # every backend computes identically on the dead lane
        if b == 0 or (a == 0x80000000 and b == M32):
            return 0
        sa, sb = _s32(a), _s32(b)
        q = abs(sa) // abs(sb)
        if (sa < 0) != (sb < 0):
            q = -q
        return (q if op == U.DIV else sa - q * sb) & M32
    if op in (U.DIVU, U.REMU):
        if b == 0:
            return 0
        return (a // b if op == U.DIVU else a % b) & M32
    if U.FADD <= op <= U.FDIV:
        return _fp_op(op, a, b)
    if op in (U.LOAD, U.STORE):
        return (a + imm) & M32          # effective address
    if op == U.BEQ:
        return 1 if a == b else 0
    if op == U.BNE:
        return 1 if a != b else 0
    if op == U.BLT:
        return 1 if _s32(a) < _s32(b) else 0
    if op == U.BGE:
        return 1 if _s32(a) >= _s32(b) else 0
    raise ValueError(f"unknown opcode {op}")


def scalar_replay(trace, reg: np.ndarray, mem: np.ndarray,
                  record_mem: list | None = None):
    """Run a whole trace over (regfile, memory) — fault-free golden path.

    ``reg``/``mem`` are uint32 arrays, modified in place.  Returns the list of
    computed branch outcomes (for generator bookkeeping).  Memory addressing:
    word index = addr >> 2, valid iff aligned and within ``len(mem)`` words —
    identical to the device kernel's model.

    ``record_mem``, if given, collects the golden memory-access stream as
    ``(µop_index, word_index, is_store)`` tuples — the input to the cache
    timeline builder (models/ruby.py).
    """
    n_words = len(mem)
    taken = []
    for i in range(trace.n):
        op = int(trace.opcode[i])
        a = int(reg[trace.src1[i]])
        b = int(reg[trace.src2[i]])
        imm = int(trace.imm[i])
        res = alu(op, a, b, imm)
        if op == U.LOAD:
            addr = res
            assert addr % 4 == 0 and addr >> 2 < n_words, "golden trace must be in-range"
            res = int(mem[addr >> 2])
            reg[trace.dst[i]] = res
            if record_mem is not None:
                record_mem.append((i, addr >> 2, False))
        elif op == U.STORE:
            addr = res
            assert addr % 4 == 0 and addr >> 2 < n_words, "golden trace must be in-range"
            mem[addr >> 2] = b
            if record_mem is not None:
                record_mem.append((i, addr >> 2, True))
        elif U.is_branch(np.int64(op)):
            taken.append(res)
        elif U.writes_dest(np.int64(op)):
            reg[trace.dst[i]] = res
    return taken
