"""Simulation-control tier: exit events + the Simulator automation API."""

from shrewd_tpu.sim.exit_event import ExitEvent
from shrewd_tpu.sim.simulator import Simulator

__all__ = ["ExitEvent", "Simulator"]
