"""Typed exit events — the framework↔user automation contract.

The reference's public automation API is the ``ExitEvent`` enum plus a
user-generator mapping (``python/gem5/simulate/exit_event.py:39-58``,
``simulator.py:208``; SURVEY §A.4 records it as the protocol to keep). The
TPU campaign's control points differ — there is no guest OS raising
hypercalls — so the event *vocabulary* is campaign-shaped, but the protocol
(event → generator, ``yield True`` stops the run) is the same.
"""

from __future__ import annotations

import enum


class ExitEvent(enum.Enum):
    # one sharded trial batch finished (payload: BatchInfo)
    BATCH_COMPLETE = "batch_complete"
    # a (simpoint, structure) campaign met its CI target (payload: result)
    CI_CONVERGED = "ci_converged"
    # a (simpoint, structure) campaign hit its trial cap unconverged
    MAX_TRIALS = "max_trials"
    # campaign checkpoint written (payload: checkpoint dir)
    CHECKPOINT = "checkpoint"
    # a batch ran below the device tier (payload: DegradeInfo) — the
    # resilience ladder substituted CPU-JAX or the host oracle
    BACKEND_DEGRADED = "backend_degraded"
    # the device→host escalation rate crossed the configured budget
    # (payload: EscalationInfo); emitted once, then the run continues
    # (action=warn) or the event stream ends early (action=abort)
    ESCALATION_EXCEEDED = "escalation_exceeded"
    # a batch failed the integrity layer's canary/invariant checks or the
    # audit mismatch budget was exceeded (payload: evidence dict from
    # integrity.IntegrityMonitor, or integrity.AuditBudgetInfo for the
    # budget gate).  Quarantined batches that recover via re-dispatch emit
    # this with kind="recovered"; an unrecoverable violation or an
    # audit_action=abort breach ends the stream after a resumable
    # checkpoint (rc 3)
    INTEGRITY_VIOLATION = "integrity_violation"
    # SIGTERM/SIGINT drain: the in-flight batch finished, a resumable
    # checkpoint was written, and the event stream ends (payload: the
    # checkpoint dir, or None without an outdir).  The CLI exits rc 4.
    PREEMPTED = "preempted"
    # an elastic peer stopped heartbeating and its batch lease was revoked
    # (payload: elastic.WorkerLostInfo — who died, the reclaimed batch,
    # the surviving membership); the campaign continues on the survivors
    WORKER_LOST = "worker_lost"
    # one simpoint finished all structures (payload: simpoint name)
    SIMPOINT_COMPLETE = "simpoint_complete"
    # the whole plan finished (payload: {(simpoint, structure): result})
    CAMPAIGN_COMPLETE = "campaign_complete"
