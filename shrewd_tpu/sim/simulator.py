"""The Simulator: exit events → user generators.

The reference's stdlib ``Simulator`` (``python/gem5/simulate/simulator.py:58``,
``run()`` at ``:530``) maps each typed exit event to a user-supplied Python
*generator*; yielding ``True`` stops the run, ``False``/``None`` continues
(``simulator.py:208``; SURVEY §A.4 calls this the public automation API to
keep). This class preserves that protocol over the campaign orchestrator's
event stream.

    sim = Simulator(plan, outdir="m5out", on_exit_event={
        ExitEvent.BATCH_COMPLETE: my_progress_gen(),
        ExitEvent.CI_CONVERGED: my_result_gen(),
    })
    sim.run()
"""

from __future__ import annotations

from typing import Generator, Iterable, Mapping

from shrewd_tpu.sim.exit_event import ExitEvent


class Simulator:
    def __init__(self, plan, mesh=None, outdir: str | None = None,
                 on_exit_event: Mapping[ExitEvent, Iterable] | None = None):
        # deferred import: campaign.orchestrator imports sim.exit_event, so a
        # module-level import here would close an import cycle
        from shrewd_tpu.campaign.orchestrator import Orchestrator
        self.orchestrator = Orchestrator(plan, mesh=mesh, outdir=outdir)
        self._handlers: dict[ExitEvent, Generator] = {}
        for ev, gen in (on_exit_event or {}).items():
            self._handlers[ev] = iter(gen)  # accept generators or iterables
        self.last_event: ExitEvent | None = None
        self.last_payload: object = None

    @classmethod
    def from_checkpoint(cls, ckpt_dir: str, mesh=None,
                        outdir: str | None = None,
                        on_exit_event=None) -> "Simulator":
        from shrewd_tpu.campaign.orchestrator import Orchestrator
        sim = cls.__new__(cls)
        sim.orchestrator = Orchestrator.resume(ckpt_dir, mesh=mesh,
                                               outdir=outdir)
        sim._handlers = {}
        for ev, gen in (on_exit_event or {}).items():
            sim._handlers[ev] = iter(gen)
        sim.last_event = None
        sim.last_payload = None
        return sim

    def run(self) -> dict:
        """Drive the campaign to completion or to the first handler that
        yields True. Returns results collected so far."""
        for event, payload in self.orchestrator.events():
            self.last_event, self.last_payload = event, payload
            handler = self._handlers.get(event)
            if handler is None:
                continue
            try:
                # the payload is available to handlers via self.last_payload,
                # matching the reference where generators consult the
                # simulator object rather than receiving arguments
                stop = next(handler)
            except StopIteration:
                del self._handlers[event]  # exhausted handlers fall back
                continue
            if stop:
                break
        self.orchestrator.write_outputs()
        return dict(self.orchestrator.results)
