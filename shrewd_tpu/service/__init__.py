"""Multi-tenant campaign service: a resident scheduler over one mesh.

The reference's only answer to "many experiments" is ``multisim`` — a
process-per-config fan-out where each gem5 instance owns the machine and
campaigns run embarrassingly serial.  This package is the TPU-native
alternative: ONE resident process owns the mesh and interleaves many
concurrent campaigns (*tenants*) through the pipelined engine
(``parallel/pipeline.py``), under a global dispatch-depth budget, with
weighted fair-share + strict-priority scheduling, per-tenant stopping,
checkpoints, integrity/chaos state, and admission-time certification.

- ``queue.py``     — ``TenantSpec`` + the durable submission spool
  (atomic claims over a shared directory, the elastic coord-dir idiom),
  so tenants can be submitted while the fleet runs, plus the
  ``ServerLock`` single-server guard and the ``bad/`` quarantine for
  poisoned submissions;
- ``scheduler.py`` — ``CampaignScheduler``, the resident scheduler that
  ticks each tenant's ``StepDriver`` one batch/interval at a time, with
  the poison-tenant retry/quarantine ladder and the per-tenant tick
  watchdog;
- ``journal.py``   — the fleet's write-ahead journal: fsync'd
  checksummed records for every scheduler state transition, compacted
  into ``fleet.json``, so ``CampaignScheduler.recover()`` survives a
  hard kill (SIGKILL/OOM) at any instruction boundary.

The invariant is non-negotiable and pinned in ``tests/test_fleet.py``:
each tenant's final tallies are bit-identical to its solo serial run
(frozen per-batch PRNG keys), including under preemption, mid-fleet
chaos, and drain/resume — co-scheduling changes wall-clock, never
results.

Import discipline: jax-free at package import (specs and the spool are
pure host-side work; jax enters only when the scheduler elaborates a
tenant's orchestrator).
"""

from shrewd_tpu.service.journal import FleetJournal, is_dirty, journal_path
from shrewd_tpu.service.queue import (LockHeld, ServerLock,
                                      SubmissionQueue, TenantSpec)
from shrewd_tpu.service.scheduler import (IDLE, CampaignScheduler,
                                          FleetKilled, TenantKilled)

__all__ = ["CampaignScheduler", "FleetJournal", "FleetKilled", "IDLE",
           "LockHeld", "ServerLock", "SubmissionQueue", "TenantKilled",
           "TenantSpec", "is_dirty", "journal_path"]
