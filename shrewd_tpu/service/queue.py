"""Tenant specs + the durable submission queue (atomic spool directory).

A **tenant** is one campaign plan plus its scheduling identity: a name,
a strict-priority class, a fair-share weight, and an optional batch
quota.  ``TenantSpec`` is the JSON-round-trippable submission unit — the
whole tenant is reproducible from its spec document alone, exactly like
a campaign from its ``config.json`` (the plan rides inside the spec).

The **submission queue** is a spool directory with the same durability
discipline as the elastic lease board (``parallel/elastic.py``): every
document is written via ``resilience.write_json_atomic`` (tmp + fsync +
rename + dir-fsync) and carries a content checksum, so a torn submission
reads as absent, never as a half-tenant.  Claims are atomic renames
(``pending/`` → ``claimed/``), so two servers racing a spool cannot both
admit one tenant, and tenants can be submitted while the fleet runs —
the scheduler polls ``pending/`` between ticks.

Layout::

    <root>/pending/   NNNNNN_<name>.json   submitted, unclaimed
    <root>/claimed/   NNNNNN_<name>.json   admitted by a scheduler
    <root>/done/      NNNNNN_<name>.json   final per-tenant result doc

Import discipline: jax-free (pure host-side file coordination; the plan
inside a spec is elaborated only by the scheduler).
"""

from __future__ import annotations

import os
import re
import time

from shrewd_tpu.resilience import load_json_verified, write_json_atomic
from shrewd_tpu.utils import debug

debug.register_flag("Fleet", "multi-tenant scheduler / submission queue")

_TICKET_RE = re.compile(r"^(\d{6})_.*\.json$")


def sanitize(name: str) -> str:
    """Filesystem-safe tenant name (the elastic ``_sanitize`` discipline;
    one definition here so spool tickets and per-tenant output
    directories cannot disagree)."""
    return re.sub(r"[^A-Za-z0-9_.+-]", "+", name)


class TenantSpec:
    """One tenant's submission: plan + scheduling identity.

    ``plan`` is the ``CampaignPlan.to_dict()`` document (kept as a dict
    so the spec round-trips without jax); ``priority`` is a strict class
    (higher preempts lower entirely), ``weight`` the fair-share stride
    within a class, and ``quota_batches`` an optional scheduler-level
    resource cap — a tenant at quota is drained to a resumable
    checkpoint (status ``quota``), never silently truncated."""

    def __init__(self, name: str, plan: dict, priority: int = 0,
                 weight: float = 1.0, quota_batches: int = 0,
                 submitted_at: float = 0.0):
        if not name:
            raise ValueError("tenant needs a non-empty name")
        if not float(weight) > 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0 "
                             f"(got {weight})")
        if int(quota_batches) < 0:
            raise ValueError(f"tenant {name!r}: quota_batches must be >= 0")
        self.name = str(name)
        self.plan = dict(plan)
        self.priority = int(priority)
        self.weight = float(weight)
        self.quota_batches = int(quota_batches)
        self.submitted_at = float(submitted_at)

    def build_plan(self):
        from shrewd_tpu.campaign.plan import CampaignPlan

        return CampaignPlan.from_dict(self.plan)

    def to_dict(self) -> dict:
        return {"name": self.name, "plan": dict(self.plan),
                "priority": self.priority, "weight": self.weight,
                "quota_batches": self.quota_batches,
                "submitted_at": self.submitted_at}

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        return cls(name=d["name"], plan=d["plan"],
                   priority=d.get("priority", 0),
                   weight=d.get("weight", 1.0),
                   quota_batches=d.get("quota_batches", 0),
                   submitted_at=d.get("submitted_at", 0.0))


class SubmissionQueue:
    """The durable spool (see module docstring)."""

    def __init__(self, root: str):
        self.root = root
        self.pending_dir = os.path.join(root, "pending")
        self.claimed_dir = os.path.join(root, "claimed")
        self.done_dir = os.path.join(root, "done")
        for d in (self.pending_dir, self.claimed_dir, self.done_dir):
            os.makedirs(d, exist_ok=True)

    # --- submission ------------------------------------------------------

    def _next_seq(self) -> int:
        seq = 0
        for d in (self.pending_dir, self.claimed_dir, self.done_dir):
            for name in os.listdir(d):
                m = _TICKET_RE.match(name)
                if m:
                    seq = max(seq, int(m.group(1)) + 1)
        return seq

    def submit(self, spec: TenantSpec) -> str:
        """Spool one tenant; returns the ticket name.  The sequence
        number is reserved with an O_EXCL placeholder (two racing
        submitters cannot share a ticket), then the real document
        atomically replaces it — a poll between the two sees an invalid
        document and skips it, never a half-spec."""
        doc = spec.to_dict()
        if not doc.get("submitted_at"):
            # graftlint: allow-wall-clock -- submission timestamp feeds
            # the queue-latency observability stat only; scheduling
            # decisions are pure functions of admission order and batch
            # counts, and tallies are frozen-key pure either way
            doc["submitted_at"] = time.time()
        seq = self._next_seq()
        while True:
            ticket = f"{seq:06d}_{sanitize(spec.name)}.json"
            path = os.path.join(self.pending_dir, ticket)
            try:
                os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
                break
            except FileExistsError:
                seq += 1
        write_json_atomic(path, doc)
        debug.dprintf("Fleet", "submitted %s (priority=%d weight=%g)",
                      ticket, spec.priority, spec.weight)
        return ticket

    # --- the scheduler side ----------------------------------------------

    def pending(self) -> list[str]:
        return sorted(n for n in os.listdir(self.pending_dir)
                      if _TICKET_RE.match(n))

    def claim(self) -> list[tuple[str, TenantSpec]]:
        """Claim every currently-valid pending submission, in ticket
        order.  The claim is an atomic rename into ``claimed/`` — a
        racing second server loses with OSError and skips.  Invalid
        documents (in-flight placeholder, torn write) stay pending for a
        later poll; they become claimable once their atomic replace
        lands."""
        out = []
        for ticket in self.pending():
            src = os.path.join(self.pending_dir, ticket)
            try:
                doc = load_json_verified(src)
                spec = TenantSpec.from_dict(doc)
            except (OSError, ValueError, KeyError):
                continue             # placeholder / torn / malformed: skip
            dst = os.path.join(self.claimed_dir, ticket)
            try:
                os.rename(src, dst)
            except OSError:
                continue             # lost the claim race
            out.append((ticket, spec))
            debug.dprintf("Fleet", "claimed %s", ticket)
        return out

    def mark_done(self, ticket: str, result: dict) -> None:
        """Publish the tenant's final result document (atomic, like every
        persisted artifact) and retire the claimed ticket."""
        write_json_atomic(os.path.join(self.done_dir, ticket), dict(result))
        try:
            os.unlink(os.path.join(self.claimed_dir, ticket))
        except OSError:
            pass

    def done(self, ticket: str) -> dict | None:
        try:
            return load_json_verified(os.path.join(self.done_dir, ticket))
        except (OSError, ValueError):
            return None
