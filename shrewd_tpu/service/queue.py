"""Tenant specs + the durable submission queue (atomic spool directory).

A **tenant** is one campaign plan plus its scheduling identity: a name,
a strict-priority class, a fair-share weight, and an optional batch
quota.  ``TenantSpec`` is the JSON-round-trippable submission unit — the
whole tenant is reproducible from its spec document alone, exactly like
a campaign from its ``config.json`` (the plan rides inside the spec).

The **submission queue** is a spool directory with the same durability
discipline as the elastic lease board (``parallel/elastic.py``): every
document is written via ``resilience.write_json_atomic`` (tmp + fsync +
rename + dir-fsync) and carries a content checksum, so a torn submission
reads as absent, never as a half-tenant.  Claims are atomic renames
(``pending/`` → ``claimed/``), so two servers racing a spool cannot both
admit one tenant, and tenants can be submitted while the fleet runs —
the scheduler polls ``pending/`` between ticks.

Layout::

    <root>/pending/   NNNNNN_<name>.json   submitted, unclaimed
    <root>/claimed/   NNNNNN_<name>.json   admitted by a scheduler
    <root>/done/      NNNNNN_<name>.json   final per-tenant result doc
    <root>/bad/       NNNNNN_<name>.json   poisoned submission + .reason
    <root>/server.lock                     O_EXCL+pid single-server guard

A **poisoned** submission — complete JSON whose checksum fails, or a
document the spec validator rejects — is deterministically bad (an
atomic replace can never heal it), so ``claim`` moves it to ``bad/``
with a ``.reason`` doc and counts it, instead of raising out of the
scheduler's poll loop or skipping it forever.  Only documents that do
not PARSE stay pending: that is the in-flight signature of the atomic
submit (O_EXCL placeholder → atomic replace).

Import discipline: jax-free (pure host-side file coordination; the plan
inside a spec is elaborated only by the scheduler).
"""

from __future__ import annotations

import json
import os
import re

from shrewd_tpu.obs import clock as obs_clock
from shrewd_tpu.resilience import (doc_checksum, load_json_verified,
                                   write_json_atomic)
from shrewd_tpu.utils import debug

debug.register_flag("Fleet", "multi-tenant scheduler / submission queue")

_TICKET_RE = re.compile(r"^(\d{6})_.*\.json$")


def sanitize(name: str) -> str:
    """Filesystem-safe tenant name (the elastic ``_sanitize`` discipline;
    one definition here so spool tickets and per-tenant output
    directories cannot disagree)."""
    return re.sub(r"[^A-Za-z0-9_.+-]", "+", name)


class TenantSpec:
    """One tenant's submission: plan + scheduling identity.

    ``plan`` is the ``CampaignPlan.to_dict()`` document (kept as a dict
    so the spec round-trips without jax); ``priority`` is a strict class
    (higher preempts lower entirely), ``weight`` the fair-share stride
    within a class, and ``quota_batches`` an optional scheduler-level
    resource cap — a tenant at quota is drained to a resumable
    checkpoint (status ``quota``), never silently truncated."""

    def __init__(self, name: str, plan: dict, priority: int = 0,
                 weight: float = 1.0, quota_batches: int = 0,
                 submitted_at: float = 0.0, slo_s: float = 0.0,
                 shards: int = 1, binary_b64: str = "",
                 binary_digest: str = "", ingest: dict | None = None):
        if not name:
            raise ValueError("tenant needs a non-empty name")
        if bool(binary_b64) != bool(binary_digest):
            raise ValueError(
                f"tenant {name!r}: binary_b64 and binary_digest come "
                f"together (a payload without its claimed digest — or a "
                f"digest with no payload — cannot be verified)")
        if ingest and not binary_digest:
            raise ValueError(f"tenant {name!r}: ingest axes only apply "
                             f"to a binary-carrying submission")
        if not float(weight) > 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0 "
                             f"(got {weight})")
        if int(quota_batches) < 0:
            raise ValueError(f"tenant {name!r}: quota_batches must be >= 0")
        if float(slo_s) < 0:
            raise ValueError(f"tenant {name!r}: slo_s must be >= 0")
        if int(shards) < 1:
            raise ValueError(f"tenant {name!r}: shards must be >= 1")
        self.name = str(name)
        self.plan = dict(plan)
        self.priority = int(priority)
        self.weight = float(weight)
        self.quota_batches = int(quota_batches)
        self.submitted_at = float(submitted_at)
        #: completion SLO in seconds (0 = none): advisory — the
        #: federation gateway compares it against its half-width-
        #: trajectory deadline estimate at admission and when deciding
        #: rebalancing migrations; schedulers never consume it (no
        #: wall clock enters any scheduling decision)
        self.slo_s = float(slo_s)
        #: single-campaign sharding degree (federation/gateway.py): the
        #: gateway splits the plan's frozen batch-id space round-robin
        #: across ``shards`` journaled sub-tenants on distinct pods and
        #: folds their tallies bit-identically to the solo run; 1 (the
        #: default) is byte-for-byte the unsharded path.  Plain pod
        #: schedulers ignore the field — sub-tenant specs always carry
        #: shards=1 (the split happens once, at the gateway).
        self.shards = int(shards)
        #: binary-in submission (the streaming-ingest path,
        #: ingest/pipeline.py): the raw workload ELF rides the spec
        #: base64-encoded with its claimed sha256.  ``plan`` then
        #: carries only scenario axes (structures, trial counts, seed)
        #: — the scheduler fills ``simpoints`` from the artifact store
        #: after the journaled ingest pipeline runs.  ``ingest`` is the
        #: optional ingest-axes dict (interval/k/seed/...), normalized
        #: and digest-keyed by the pipeline.
        self.binary_b64 = str(binary_b64)
        self.binary_digest = str(binary_digest)
        self.ingest = dict(ingest) if ingest else None

    def binary_bytes(self) -> bytes:
        """Decode the carried binary (raises ValueError on bad base64)."""
        import base64
        import binascii

        try:
            return base64.b64decode(self.binary_b64, validate=True)
        except (binascii.Error, ValueError) as e:
            raise ValueError(f"tenant {self.name!r}: binary_b64 does "
                             f"not decode: {e}")

    def verify_binary(self) -> bytes:
        """Decode AND verify the carried binary against its claimed
        digest; raises ValueError on any mismatch.  A spec whose payload
        no longer hashes to its digest is deterministically poisoned
        (rot or tamper in the spool) — ``claim()`` routes that to
        ``bad/`` exactly like a checksum-failed document."""
        import hashlib

        data = self.binary_bytes()
        got = hashlib.sha256(data).hexdigest()
        if got != self.binary_digest:
            raise ValueError(
                f"tenant {self.name!r}: binary digest mismatch "
                f"(claimed {self.binary_digest[:12]}, payload hashes "
                f"to {got[:12]}) — poisoned submission")
        return data

    def build_plan(self):
        from shrewd_tpu.campaign.plan import CampaignPlan

        return CampaignPlan.from_dict(self.plan)

    def to_dict(self) -> dict:
        d = {"name": self.name, "plan": dict(self.plan),
             "priority": self.priority, "weight": self.weight,
             "quota_batches": self.quota_batches,
             "submitted_at": self.submitted_at,
             "slo_s": self.slo_s, "shards": self.shards}
        # binary fields ride only when set, so plan-only submission
        # documents stay byte-identical to pre-ingest releases
        if self.binary_digest:
            d["binary_b64"] = self.binary_b64
            d["binary_digest"] = self.binary_digest
            if self.ingest is not None:
                d["ingest"] = dict(self.ingest)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        return cls(name=d["name"], plan=d["plan"],
                   priority=d.get("priority", 0),
                   weight=d.get("weight", 1.0),
                   quota_batches=d.get("quota_batches", 0),
                   submitted_at=d.get("submitted_at", 0.0),
                   slo_s=d.get("slo_s", 0.0),
                   shards=d.get("shards", 1),
                   binary_b64=d.get("binary_b64", ""),
                   binary_digest=d.get("binary_digest", ""),
                   ingest=d.get("ingest"))


class SubmissionQueue:
    """The durable spool (see module docstring)."""

    def __init__(self, root: str):
        self.root = root
        self.pending_dir = os.path.join(root, "pending")
        self.claimed_dir = os.path.join(root, "claimed")
        self.done_dir = os.path.join(root, "done")
        self.bad_dir = os.path.join(root, "bad")
        for d in (self.pending_dir, self.claimed_dir, self.done_dir,
                  self.bad_dir):
            os.makedirs(d, exist_ok=True)

    # --- submission ------------------------------------------------------

    def _next_seq(self) -> int:
        seq = 0
        for d in (self.pending_dir, self.claimed_dir, self.done_dir,
                  self.bad_dir):
            for name in os.listdir(d):
                m = _TICKET_RE.match(name)
                if m:
                    seq = max(seq, int(m.group(1)) + 1)
        return seq

    def submit(self, spec: TenantSpec) -> str:
        """Spool one tenant; returns the ticket name.  The sequence
        number is reserved with an O_EXCL placeholder (two racing
        submitters cannot share a ticket), then the real document
        atomically replaces it — a poll between the two sees an invalid
        document and skips it, never a half-spec."""
        doc = spec.to_dict()
        if not doc.get("submitted_at"):
            # submission timestamp feeds the queue-latency observability
            # stat only; scheduling decisions are pure functions of
            # admission order and batch counts, and tallies are
            # frozen-key pure either way.  Routed through the sanctioned
            # obs.clock seam (GL106).
            doc["submitted_at"] = obs_clock.now()
        # content checksum: a claimed doc that PARSES but fails this is
        # definitively poisoned (bit-rot, tampering) and takes the bad/
        # quarantine path, never the in-flight-skip path
        doc["checksum"] = doc_checksum(doc)
        seq = self._next_seq()
        while True:
            ticket = f"{seq:06d}_{sanitize(spec.name)}.json"
            path = os.path.join(self.pending_dir, ticket)
            try:
                os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
                break
            except FileExistsError:
                seq += 1
        write_json_atomic(path, doc)
        debug.dprintf("Fleet", "submitted %s (priority=%d weight=%g)",
                      ticket, spec.priority, spec.weight)
        return ticket

    # --- the scheduler side ----------------------------------------------

    def pending(self) -> list[str]:
        return sorted(n for n in os.listdir(self.pending_dir)
                      if _TICKET_RE.match(n))

    def claim(self) -> list[tuple[str, TenantSpec]]:
        """Claim every currently-valid pending submission, in ticket
        order.  The claim is an atomic rename into ``claimed/`` — a
        racing second server loses with OSError and skips.

        Documents that do not PARSE (in-flight placeholder, torn write)
        stay pending for a later poll — they become claimable once
        their atomic replace lands.  Documents that parse but are
        poisoned (checksum mismatch, spec the validator rejects) can
        never heal: they move to ``bad/`` with a reason doc instead of
        wedging the poll loop forever or raising out of the scheduler."""
        out = []
        for ticket in self.pending():
            src = os.path.join(self.pending_dir, ticket)
            try:
                with open(src) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue             # placeholder / in-flight: not ours yet
            try:
                if not isinstance(doc, dict):
                    raise ValueError("submission is not a JSON object")
                want = doc.get("checksum")
                if want is not None and doc_checksum(doc) != want:
                    raise ValueError("checksum mismatch "
                                     "(corrupt submission)")
                spec = TenantSpec.from_dict(doc)
                if spec.binary_digest:
                    # the PR-8 checksum split, applied to the payload: a
                    # binary that no longer hashes to its claimed digest
                    # is poison (bad/ + reason), never an in-flight skip
                    spec.verify_binary()
            except Exception as e:  # noqa: BLE001 — a complete-but-
                # poisoned document is deterministically bad; quarantine
                # it so the spool keeps serving
                self.quarantine_bad(ticket, e)
                continue
            dst = os.path.join(self.claimed_dir, ticket)
            try:
                # graftlint: allow-fsync-rename -- cross-dir move of an
                # already-durable document (content fsync'd at submit);
                # a power loss that drops the rename re-pends the
                # ticket, and re-claim is safe: admission refuses
                # duplicate tenant names loudly
                os.rename(src, dst)
            except OSError:
                continue             # lost the claim race
            out.append((ticket, spec))
            debug.dprintf("Fleet", "claimed %s", ticket)
        return out

    def quarantine_bad(self, ticket: str, err: Exception) -> None:
        """Move a poisoned pending submission to ``bad/`` (atomic
        rename — a racing server loses and skips) and publish the
        refusal evidence next to it as ``<ticket>.reason``."""
        src = os.path.join(self.pending_dir, ticket)
        dst = os.path.join(self.bad_dir, ticket)
        try:
            # graftlint: allow-fsync-rename -- cross-dir move of an
            # already-durable (if poisoned) document; losing the rename
            # re-pends the ticket and the next poll re-quarantines it —
            # the decision is deterministic, so replaying it is free
            os.rename(src, dst)
        except OSError:
            return                   # raced away (claimed or re-quarantined)
        write_json_atomic(dst + ".reason", {
            "ticket": ticket, "error": f"{type(err).__name__}: {err}"})
        debug.dprintf("Fleet", "quarantined bad submission %s: %s",
                      ticket, err)

    def bad_count(self) -> int:
        """Poisoned submissions quarantined in ``bad/`` (the
        ``campaign.fleet.submissions_bad`` stat)."""
        return len([n for n in os.listdir(self.bad_dir)
                    if _TICKET_RE.match(n)])

    def mark_done(self, ticket: str, result: dict) -> None:
        """Publish the tenant's final result document (atomic, like every
        persisted artifact) and retire the claimed ticket."""
        write_json_atomic(os.path.join(self.done_dir, ticket), dict(result))
        try:
            os.unlink(os.path.join(self.claimed_dir, ticket))
        except OSError:
            pass

    def done(self, ticket: str) -> dict | None:
        try:
            return load_json_verified(os.path.join(self.done_dir, ticket))
        except (OSError, ValueError):
            return None


# --- single-server guard ----------------------------------------------------

class LockHeld(RuntimeError):
    """Another live server owns the fleet's lock file."""


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False            # never signal pgid 0 / invalid pids
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True             # exists, owned by someone else
    except OSError:
        return False
    return True


class ServerLock:
    """O_EXCL + pid lock file: one server per spool/fleet directory.

    Two ``fleet.py --serve`` processes racing one spool would each win
    half the atomic claims and split the fleet's tenants across two
    schedulers with two journals — silently.  The lock makes the race
    loud: the file is created with ``O_CREAT|O_EXCL`` (atomic on POSIX)
    and records the holder's pid; a second server fails with
    ``LockHeld``.

    A **stale** lock — the recorded pid is not alive (the previous
    server was SIGKILLed, which is exactly the hard-kill scenario the
    journal exists for), or the content is unreadable (torn pid write)
    — is reaped and re-raced through the same O_EXCL create, so crash
    recovery never needs a human to rm a lock file.  Same-host pid
    liveness only: a multi-host spool needs the elastic heartbeat
    membership instead, and says so in README.
    """

    def __init__(self, root: str, name: str = "server.lock"):
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, name)
        self._owned = False

    def _holder(self) -> int | None:
        try:
            with open(self.path) as f:
                return int(f.read().strip() or "0")
        except (OSError, ValueError):
            return None

    def _reap_stale(self) -> None:
        """Remove a stale lock under a reap MUTEX (its own O_EXCL file):
        the holder re-reads the lock content before unlinking, so a
        reaper acting on an old read can never unlink a lock another
        server just validly acquired (the naive read-then-unlink TOCTOU
        would split the fleet across two owners — the exact hazard the
        lock exists to prevent)."""
        reap = self.path + ".reap"
        try:
            fd = os.open(reap, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # another reaper holds the mutex; if ITS holder died between
            # unlink(lock) and unlink(reap), clear the orphan
            try:
                with open(reap) as f:
                    rpid = int(f.read().strip() or "0")
            except (OSError, ValueError):
                rpid = 0
            if not _pid_alive(rpid):
                try:
                    os.unlink(reap)
                except OSError:
                    pass
            return
        try:
            os.write(fd, f"{os.getpid()}\n".encode())
        finally:
            os.close(fd)
        try:
            # re-read under the mutex: only unlink if STILL stale
            pid = self._holder()
            if pid is None or not _pid_alive(pid):
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
        finally:
            try:
                os.unlink(reap)
            except OSError:
                pass

    def acquire(self) -> "ServerLock":
        for _ in range(8):
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                pid = self._holder()
                if pid is not None and _pid_alive(pid):
                    raise LockHeld(
                        f"{self.path}: held by live pid {pid}")
                # stale (dead pid / unreadable content): reap under the
                # reap mutex, then re-race the O_EXCL create
                self._reap_stale()
                continue
            try:
                os.write(fd, f"{os.getpid()}\n".encode())
                os.fsync(fd)
            finally:
                os.close(fd)
            self._owned = True
            debug.dprintf("Fleet", "server lock %s (pid %d)",
                          self.path, os.getpid())
            return self
        raise LockHeld(f"{self.path}: could not settle lock ownership")

    def release(self) -> None:
        if not self._owned:
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self._owned = False

    def __enter__(self) -> "ServerLock":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()
