"""The fleet's write-ahead journal: crash-safe scheduler state.

``fleet.json`` (the scheduler snapshot) is written only at checkpoints —
a SIGKILL/OOM/node loss between them would lose the fleet's admission
ledger, fair-share virtual times and quota accounting even though every
tenant's tallies are individually recoverable from its namespaced
campaign checkpoint.  The journal closes that window: every scheduler
state transition (admit, tick-complete with its vtime/quota deltas,
status change, failure, quarantine, shutdown) is appended here BEFORE
the in-memory ledgers are trusted, so ``CampaignScheduler.recover()``
can replay snapshot + journal after a hard kill at ANY instruction
boundary and resume every tenant bit-identically.

Append discipline (the WAL contract):

- one JSON record per line, each carrying a monotonic ``seq`` and a
  content ``checksum`` (``resilience.doc_checksum``);
- every append is ``flush`` + ``fsync`` before it is acknowledged — a
  record the scheduler acted on is durable;
- a torn tail (power loss / SIGKILL mid-append) reads as an invalid
  last line; ``replay_path`` drops it and everything after the first
  invalid record, because bytes after a torn record are untrusted;
- **compaction**: once a snapshot covering ``seq <= journal_seq`` is
  durable (``fleet.json`` via ``write_json_atomic``), the journal is
  atomically replaced with an empty file.  The ordering is
  snapshot-fsync THEN truncate, so a crash between the two leaves
  duplicate records (skipped by ``seq`` at replay), never a gap.

A clean shutdown therefore leaves an EMPTY journal behind a current
snapshot; ``is_dirty`` detecting records (or a torn tail) beyond the
snapshot's ``journal_seq`` is the hard-kill signature that routes
``tools/fleet.py`` to ``--recover``.

Service-level chaos rides the same seam: ``torn_journal`` tears an
append exactly the way a power loss would (prefix bytes, fsync'd, then
process death through the engine's ``kill_action``), and ``kill_fleet``
with ``at_journal`` fires right after a record lands — both on the
deterministic chaos schedule, never a clock.

Import discipline: jax-free (pure host-side durability; the journal
must work in the spool-only processes that never build a mesh).
"""

from __future__ import annotations

import json
import os

from shrewd_tpu import resilience as resil
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.utils import debug

#: the journal file inside ``<outdir>/fleet_ckpt/``
JOURNAL_NAME = "journal.jsonl"


def journal_path(outdir: str) -> str:
    return os.path.join(outdir, "fleet_ckpt", JOURNAL_NAME)


class FleetJournal:
    """Append-only, fsync'd, checksummed record log (see module doc).

    ``next_seq`` continues from the larger of the caller's floor (the
    snapshot's ``journal_seq + 1``) and the last valid record already in
    the file, so sequence numbers stay monotonic across reopen,
    compaction and recovery.  Opening a file with a torn tail truncates
    the untrusted bytes first — appends never follow garbage.
    """

    def __init__(self, path: str, next_seq: int = 0, chaos=None):
        self.path = path
        self.chaos = chaos
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        records, torn, valid = ([], 0, 0)
        if os.path.exists(path):
            records, torn, valid = self.replay_path(path)
            if torn:
                # a torn tail is by definition not durable state: drop it
                # before appending, or the new records would sit behind
                # garbage and be dropped at the next replay
                with open(path, "r+b") as f:
                    f.truncate(valid)
                    f.flush()
                    os.fsync(f.fileno())
        self.torn_dropped = torn
        self.next_seq = max(int(next_seq),
                            records[-1]["seq"] + 1 if records else 0)
        self.since_compact = len(records)
        self.appended = 0        # records fsync'd by THIS process
        self.compactions = 0
        self._f = open(path, "a")

    # --- replay -----------------------------------------------------------

    @staticmethod
    def replay_path(path: str) -> tuple[list[dict], int, int]:
        """``(records, torn, valid_bytes)``: every checksummed record up
        to the first invalid one.  ``torn`` counts the invalid record
        (0 or 1 — everything after the first bad line is untrusted and
        not inspected); ``valid_bytes`` is the byte offset the trusted
        prefix ends at (the truncation point)."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return [], 0, 0
        records: list[dict] = []
        pos = valid = 0
        torn = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                torn = 1             # unterminated tail: torn mid-append
                break
            try:
                rec = json.loads(data[pos:nl])
                if not isinstance(rec, dict):
                    raise ValueError("record is not a JSON object")
                want = rec.get("checksum")
                if want is None or resil.doc_checksum(rec) != want:
                    raise ValueError("checksum mismatch")
                int(rec["seq"])
            except (ValueError, KeyError, TypeError):
                torn = 1
                break
            records.append(rec)
            pos = valid = nl + 1
        return records, torn, valid

    # --- append -----------------------------------------------------------

    def append(self, kind: str, data: dict | None = None) -> int:
        """Durably append one record; returns its ``seq``.  The record
        is fsync'd before this returns — a caller that proceeds may
        trust a hard kill cannot un-happen the transition."""
        rec: dict = {"seq": self.next_seq, "kind": str(kind)}
        if data:
            rec.update(data)
        rec["checksum"] = resil.doc_checksum(rec)
        line = json.dumps(rec, default=str) + "\n"
        if self.chaos is not None:
            torn = self.chaos.take_torn_journal(rec["seq"])
            if torn is not None:
                # a torn append IS a process death mid-write: persist the
                # prefix a power loss would leave, then die through the
                # kill seam (default os._exit; tests install a raising
                # action so the "dead" fleet can assert recovery
                # in-process)
                keep = float(torn.get("keep_fraction", 0.5))
                self._f.write(line[:max(1, int(len(line) * keep))])
                self._f.flush()
                os.fsync(self._f.fileno())
                self.chaos.kill_now(torn.get("rc"))
                return rec["seq"]    # only under a non-exiting test action
        self._f.write(line)
        self._f.flush()
        os.fsync(self._f.fileno())
        # the durability boundary: a crash from here on replays this
        # record (the crashcheck model checker enumerates these)
        resil.notify_durability("append", self.path, seq=rec["seq"],
                                kind=rec["kind"])
        self.next_seq += 1
        self.appended += 1
        self.since_compact += 1
        obs_trace.tracer().emit("journal_append", cat="journal",
                                kind=rec["kind"], seq=rec["seq"])
        if self.chaos is not None:
            # kill_fleet at a journal ordinal: the boundary right after
            # record ``seq`` became durable (mid-tick, from the
            # scheduler's point of view)
            self.chaos.maybe_kill_fleet(journal_seq=rec["seq"])
        return rec["seq"]

    # --- compaction / lifecycle -------------------------------------------

    def compact(self) -> None:
        """Truncate the journal after a durable snapshot now owns every
        record.  Atomic (empty tmp + rename + dir-fsync): a crash
        mid-compaction leaves either the old journal (duplicates —
        skipped by seq) or the empty one, never a partial file."""
        self._f.close()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        resil.fsync_dir(os.path.dirname(os.path.abspath(self.path)))
        resil.notify_durability("compact", self.path,
                                next_seq=self.next_seq)
        self._f = open(self.path, "a")
        self.compactions += 1
        self.since_compact = 0
        obs_trace.tracer().emit("journal_compact", cat="journal",
                                next_seq=self.next_seq)
        debug.dprintf("Fleet", "journal compacted (next seq %d)",
                      self.next_seq)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def is_dirty(outdir: str) -> bool:
    """The hard-kill signature: the journal holds records (or a torn
    tail) beyond the snapshot's ``journal_seq``.  A clean shutdown
    compacts the journal behind a current snapshot, so any trailing
    state means the fleet died without draining."""
    path = journal_path(outdir)
    if not os.path.exists(path):
        return False
    records, torn, _valid = FleetJournal.replay_path(path)
    if torn:
        return True
    if not records:
        return False
    try:
        snap = resil.load_json_verified(
            os.path.join(outdir, "fleet_ckpt", "fleet.json"))
        snap_seq = int(snap.get("journal_seq", -1))
    except (OSError, ValueError):
        # journal records with no readable snapshot: everything is
        # unsnapshotted state
        return True
    return any(r["seq"] > snap_seq for r in records)
