"""The resident multi-tenant campaign scheduler (see package docstring).

One ``CampaignScheduler`` owns one mesh for its whole life and ticks many
campaigns through it: each admitted ``TenantSpec`` becomes an
``Orchestrator`` + ``StepDriver`` pair, and every scheduler tick advances
exactly one tenant by one batch (serial) or one sync interval
(pipelined).  Interleaving is where the throughput comes from: while
tenant A's tick runs host-side work (stopping rule, invariants, stats,
checkpoints), tenant B's in-flight intervals keep computing on the
device, and the content-keyed executable cache (``parallel/exec_cache``)
dedupes compiles across tenants sharing a window — the second tenant on
a shared window compiles zero new steps (asserted in the fleet test).

Scheduling is deterministic by construction: policies consume only
admission order, per-tenant trial counts and weights — never wall clock
— so a fleet's schedule log is reproducible, and each tenant's tallies
are bit-identical to its solo serial run regardless of interleaving
(frozen per-batch PRNG keys; the invariant every layer of this codebase
preserves).

Policies (``policy=``):

- ``"fair"`` (default) — strict priority classes; within the runnable
  class with the highest priority, weighted fair-share stride
  scheduling: pick the tenant with the smallest virtual time
  ``trials / weight`` (ties break on admission order).
- ``"priority"`` — strict priority, FIFO within a class (admission
  order), for drain-one-tenant-first operation.

The **global dispatch-depth budget** bounds how much device work the
whole fleet keeps in flight: each running tenant's pipelined engine
depth is clamped to ``max(1, depth_budget // n_running)`` (re-balanced
as tenants come and go), with the per-tenant plan depth as ceiling and a
floor of 1 — the fleet cannot over-subscribe the mesh the way N
independent processes would.

**Quota revocation** (the sanctioned early-stop seam): a supervising
controller — the scenario-matrix Pareto loop (``shrewd_tpu/scenario/``)
is the canonical caller — may call ``revoke_quota(tenant, reason)`` to
withdraw a tenant's remaining service.  The decision is journaled as a
``revoke`` record BEFORE any state changes (so replay after a hard kill
re-applies it exactly), a running tenant drains its in-flight batch to
a resumable checkpoint, and the tenant lands in the terminal status
``pruned`` — excluded from fair share like quarantine, but *not* a
failure: its partial tallies/results stay first-class (they are the
provenance a Pareto artifact cites).

Failure isolation: every tenant owns its watchdog, ladder, integrity
monitor and chaos engine, so a wedge or corrupt tally quarantines and
recovers INSIDE the afflicted tenant.  A chaos ``kill_worker`` is
rescoped at admission (``ChaosEngine.kill_action``): in a fleet the
"worker" is the tenant's step driver, so the kill tears down only that
tenant's orchestrator — the scheduler rebuilds it from its last
per-tenant checkpoint (or from scratch; frozen keys make both
bit-identical) while every other tenant keeps running.

**Survivability** (the write-ahead layer): ``fleet.json`` alone is only
written at checkpoints, so every state transition — admit, tick-complete
with its vtime/quota deltas, failure, quarantine, status change — is
ALSO appended to a crash-safe journal (``service/journal.py``: fsync'd,
checksummed, compacted into the snapshot) before the in-memory ledgers
are trusted.  ``recover()`` replays snapshot+journal after a hard kill
(SIGKILL/OOM) at any instruction boundary and resumes every tenant from
its namespaced checkpoint bit-identically.  A **poison tenant** whose
tick raises repeatedly gets a deterministic retry budget (tick-counted
exponential backoff — no wall clock) and then a durable ``quarantined``
status with its exception ledger persisted, never stalling the fleet or
burning its fair share; a **livelocked** tenant is preempted by the
per-tenant tick watchdog (``resilience.DeviceWatchdog`` deadlines) and
routed down the same quarantine path.  All of it is provable on a
reproducible schedule through the service-level chaos kinds
(``kill_fleet`` / ``torn_journal`` / ``corrupt_submission``).

Import discipline: jax-free at module import (the scheduler is pure
host-side control; jax enters when a tenant's orchestrator is built).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from shrewd_tpu import chaos as chaos_mod
from shrewd_tpu import resilience as resil
from shrewd_tpu import stats as statsmod
from shrewd_tpu.obs import clock as obs_clock
from shrewd_tpu.obs import metrics as obs_metrics
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.service.journal import FleetJournal, is_dirty, journal_path
from shrewd_tpu.service.queue import SubmissionQueue, TenantSpec, sanitize
from shrewd_tpu.utils import debug

FLEET_CKPT_VERSION = 2

#: snapshot versions ``recover``/``resume`` accept (v1 = pre-journal;
#: its documents simply lack the survivability fields)
_CKPT_VERSIONS = (1, FLEET_CKPT_VERSION)

#: exception-ledger cap per tenant (the snapshot/journal carry it)
_MAX_ERRORS = 32

POLICIES = ("fair", "priority")

#: ``step()`` sentinel: nothing runnable but the fleet is resident
#: (spool attached, ``idle_exit`` off) — the caller chooses whether to
#: sleep (``run()``) or go serve another pod (the federation driver)
IDLE = object()

#: certify escalation order (the fleet's admission-time certification
#: posture can tighten a tenant's plan, never loosen it)
_CERTIFY_ORDER = {"off": 0, "warn": 1, "strict": 2}

#: tenant terminal statuses a fleet resume re-admits (a resumable tenant
#: continues from its namespaced checkpoint; ``quota`` stays parked until
#: the operator resubmits with a bigger quota)
_RESUMABLE = ("queued", "running", "preempted")


class TenantKilled(RuntimeError):
    """A chaos ``kill_worker`` fired inside a tenant's tick (the
    fleet-scoped analog of ``os._exit``): the tenant's orchestrator is
    dead; the scheduler rebuilds and resumes it."""

    def __init__(self, tenant: str, rc: int):
        super().__init__(f"tenant {tenant!r} killed by chaos (rc {rc})")
        self.tenant = tenant
        self.rc = rc


class FleetKilled(RuntimeError):
    """The in-process stand-in for a fleet hard kill.

    The DEFAULT action of the ``kill_fleet``/``torn_journal`` chaos
    kinds is a true hard death (``os._exit`` — no drain, no checkpoint,
    no atexit), which is what the CI round-trip exercises in a
    subprocess.  Tests install ``engine.kill_action = raise FleetKilled``
    instead, so the "dead" fleet's process survives to run
    ``CampaignScheduler.recover()`` and assert bit-identity."""

    def __init__(self, rc: int = 137):
        super().__init__(f"fleet killed by chaos (rc {rc})")
        self.rc = rc


class TenantState:
    """One tenant's life in the fleet: spec + driver + ledgers."""

    def __init__(self, spec: TenantSpec, order: int, ticket: str = ""):
        self.spec = spec
        self.order = order           # admission order (the FIFO tiebreak)
        self.ticket = ticket         # spool ticket ("" = direct admit)
        self.status = "queued"
        self.orch = None
        self.driver = None
        self.trials = 0              # trials served (the fair-share unit)
        self.batches = 0             # trials // effective batch size
        self.ticks = 0               # scheduling quanta consumed
        self.kills = 0               # chaos kill_worker fires survived
        self.failures = 0            # tick/elaboration exceptions (lifetime)
        self.retry_at = 0            # fleet tick gating the next retry
        self.errors: list[dict] = []  # exception ledger {tick, error}
        self.revoked = ""            # quota-revocation reason ("" = none)
        self.evicted = ""            # migration-eviction reason ("" = none)
        self.rc: int | None = None
        self.queue_latency_s = 0.0   # submit → admission
        self.wall_s = 0.0            # admission → terminal
        self._t_admit: float | None = None
        self._plan_depth = 1         # the plan's own depth (budget ceiling)
        self.results: dict | None = None   # JSON-able per-structure summary

    @property
    def vtime(self) -> float:
        return self.trials / self.spec.weight

    def to_dict(self) -> dict:
        return {"spec": self.spec.to_dict(), "order": self.order,
                "ticket": self.ticket, "status": self.status,
                "trials": self.trials, "batches": self.batches,
                "ticks": self.ticks, "kills": self.kills,
                "failures": self.failures, "errors": list(self.errors),
                "revoked": self.revoked, "evicted": self.evicted,
                "rc": self.rc,
                "queue_latency_s": round(self.queue_latency_s, 3),
                "wall_s": round(self.wall_s, 3), "results": self.results}


class CampaignScheduler:
    """The resident scheduler (see module docstring).

    ``outdir`` namespaces everything per tenant:
    ``outdir/tenants/<name>/`` holds each tenant's m5out artifacts and
    its ``campaign_ckpt`` (the per-tenant checkpoint namespace), and
    ``outdir/fleet_ckpt/fleet.json`` + ``outdir/fleet_stats.json`` hold
    the fleet's own resumable state and stats dump."""

    def __init__(self, outdir: str | None = None, mesh=None,
                 depth_budget: int = 4, policy: str = "fair",
                 queue: SubmissionQueue | None = None, certify: str = "",
                 idle_exit: bool = True, poll_interval: float = 0.2,
                 on_tick=None, chaos=None, retry_budget: int = 3,
                 backoff_ticks: int = 2, tick_timeout: float = 0.0,
                 compact_every: int = 64, store_dir: str | None = None):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r} (one of {POLICIES})")
        if certify and certify not in _CERTIFY_ORDER:
            raise ValueError(f"unknown certify mode {certify!r}")
        self.outdir = outdir
        self._mesh = mesh
        self.depth_budget = max(1, int(depth_budget))
        self.policy = policy
        self.queue = queue
        self.certify = certify
        self.idle_exit = idle_exit
        self.poll_interval = float(poll_interval)
        self.on_tick = on_tick
        #: the FLEET-level chaos engine (kill_fleet / torn_journal /
        #: corrupt_submission) — distinct from each tenant's own engine,
        #: whose kills are rescoped to that tenant
        self.chaos = chaos
        #: tick-exception retries before durable quarantine; the i-th
        #: retry waits ``backoff_ticks * 2**(i-1)`` FLEET TICKS —
        #: deterministic, tick-counted, no wall clock in any decision
        self.retry_budget = max(0, int(retry_budget))
        self.backoff_ticks = max(1, int(backoff_ticks))
        #: per-tenant tick deadline (seconds; 0 = no watchdog): a
        #: livelocked tick is abandoned (DeviceWatchdog posture) and the
        #: tenant takes the failure/quarantine path instead of wedging
        #: the whole scheduler loop
        self.tick_timeout = float(tick_timeout)
        self.compact_every = max(1, int(compact_every))
        #: digest-keyed artifact-store root for binary-in submissions
        #: (ingest/store.py); None = ``<outdir>/store``.  The federation
        #: threads ONE root through every pod so a binary ingested on
        #: pod0 warm-starts in O(1) on pod1 after a migration/failover.
        self.store_dir = store_dir
        self._store = None
        self.recoveries = 0           # hard-kill recoveries survived
        self.journal_torn = 0         # torn journal records dropped
        self.ingest_lifts = 0         # windows lifted for binary tenants
        self.ingest_captures = 0      # host captures run for binary tenants
        self.tenants: dict[str, TenantState] = {}
        self.schedule_log: list[str] = []    # tenant name per tick
        self.ticks = 0
        self._drain = False
        self.preempted = False
        self._journal: FleetJournal | None = None
        self._journal_floor = 0       # next_seq floor (snapshot journal_seq+1)
        self._explicit_params: frozenset = frozenset()  # caller-pinned knobs
        self._watchdog = (resil.DeviceWatchdog(timeout=self.tick_timeout,
                                               name="fleet-tick")
                          if self.tick_timeout > 0 else None)
        self._t0 = obs_clock.monotonic()
        # abnormal exits (chaos hard kill, quarantine) dump the flight
        # recorder here — pre-registered because the kill seam fires
        # with no outdir in hand (obs/trace.py maybe_flight_dump)
        if outdir:
            obs_trace.tracer().set_flight_path(
                os.path.join(outdir, obs_trace.FLIGHT_NAME))
        self._build_stats()

    # --- mesh / stats -----------------------------------------------------

    @property
    def mesh(self):
        """The fleet's ONE mesh, built lazily (jax enters here): every
        tenant's campaigns shard over the same devices, which is what
        makes their executables cache-interchangeable.  A fleet wired
        to a SHARED artifact store (``store_dir`` — the federation
        threads one root through every pod) also points jax's
        persistent compilation cache at the store's exec-cache kind, so
        compile reuse crosses pod-process boundaries: a step compiled
        on any pod is a disk hit on every other, including pods an
        autoscaler spawns later.  Best-effort by contract — an old jax
        without the knobs degrades to in-process caching."""
        if self._mesh is None:
            from shrewd_tpu.parallel.mesh import make_mesh

            if self.store_dir:
                from shrewd_tpu.parallel import exec_cache

                exec_cache.enable_persistent_cache(self.store.exec_dir())
            self._mesh = make_mesh()
        return self._mesh

    @property
    def store(self):
        """The artifact store for binary-in submissions, built lazily
        (plan-only fleets never touch it)."""
        if self._store is None:
            from shrewd_tpu.ingest.store import ArtifactStore

            root = self.store_dir or (os.path.join(self.outdir, "store")
                                      if self.outdir else None)
            if root is None:
                raise RuntimeError("binary-in submission needs a "
                                   "store_dir (or an outdir)")
            self._store = ArtifactStore(root)
        return self._store

    def _build_stats(self) -> None:
        """``campaign.fleet.*`` — the multi-tenant ledger: who ran, how
        fairly, how fast, and how much compile work co-scheduling
        deduped.  Formulas read live scheduler state, like every other
        stats group in the tree."""
        from shrewd_tpu.parallel import exec_cache

        self.stats = statsmod.Group("campaign")
        fg = statsmod.Group("fleet")
        self.stats.fleet = fg
        fg.tenants_admitted = statsmod.Formula(
            "tenants_admitted", lambda: len(self.tenants),
            "tenants admitted to the fleet")
        fg.tenants_by_status = statsmod.Formula(
            "tenants_by_status", lambda: self._by_status(),
            "tenant count per terminal/live status")
        fg.ticks = statsmod.Formula(
            "ticks", lambda: self.ticks,
            "scheduling quanta dispatched fleet-wide")
        fg.depth_budget = statsmod.Formula(
            "depth_budget", lambda: self.depth_budget,
            "global dispatch-depth budget shared by running tenants")
        fg.tenant_trials = statsmod.Formula(
            "tenant_trials",
            lambda: {n: t.trials for n, t in self.tenants.items()},
            "trials served per tenant")
        fg.tenant_throughput = statsmod.Formula(
            "tenant_throughput",
            lambda: {n: round(t.trials / t.wall_s, 1)
                     for n, t in self.tenants.items() if t.wall_s > 0},
            "per-tenant trials/second (admission to terminal)")
        fg.queue_latency_s = statsmod.Formula(
            "queue_latency_s",
            lambda: {n: round(t.queue_latency_s, 3)
                     for n, t in self.tenants.items() if t.ticket},
            "spool-submit to admission latency per queued tenant")
        fg.fairness_index = statsmod.Formula(
            "fairness_index", lambda: self.fairness_index(),
            "Jain index over weight-normalized trials served "
            "(1.0 = perfectly weighted-fair)")
        fg.cache_hit_rate = statsmod.Formula(
            "cache_hit_rate",
            lambda: (lambda s: round(s["reused"]
                                     / max(s["reused"] + s["compiled"], 1),
                                     4))(exec_cache.cache().stats()),
            "process-wide executable-cache hit rate (cross-tenant "
            "compile dedupe)")
        fg.schedule_ticks = statsmod.Formula(
            "schedule_ticks",
            lambda: {n: t.ticks for n, t in self.tenants.items()},
            "scheduling quanta per tenant")
        fg.recoveries = statsmod.Formula(
            "recoveries", lambda: self.recoveries,
            "hard-kill recoveries this fleet has survived "
            "(snapshot + write-ahead-journal replay)")
        fg.quarantined = statsmod.Formula(
            "quarantined",
            lambda: sum(1 for t in self.tenants.values()
                        if t.status == "quarantined"),
            "poison tenants parked in durable quarantine")
        fg.ingest_lifts = statsmod.Formula(
            "ingest_lifts", lambda: self.ingest_lifts,
            "windows lifted for binary-in submissions (0 on a "
            "digest-store warm start)")
        fg.ingest_captures = statsmod.Formula(
            "ingest_captures", lambda: self.ingest_captures,
            "host captures run for binary-in submissions")
        fg.pruned = statsmod.Formula(
            "pruned",
            lambda: sum(1 for t in self.tenants.values()
                        if t.status == "pruned"),
            "tenants whose remaining quota was revoked (Pareto-"
            "dominated scenario cells; partial results stay first-class)")
        fg.evicted = statsmod.Formula(
            "evicted",
            lambda: sum(1 for t in self.tenants.values()
                        if t.status == "evicted"),
            "tenants released for migration (drained to their "
            "namespaced checkpoints; a federation gateway recovers "
            "them on another pod, bit-identically)")
        fg.tenant_failures = statsmod.Formula(
            "tenant_failures",
            lambda: {n: t.failures for n, t in self.tenants.items()
                     if t.failures},
            "tick/elaboration exceptions per tenant (the retry-budget "
            "ledger)")
        fg.journal_records = statsmod.Formula(
            "journal_records",
            lambda: self._journal.appended if self._journal else 0,
            "write-ahead journal records fsync'd this process")
        fg.journal_compactions = statsmod.Formula(
            "journal_compactions",
            lambda: self._journal.compactions if self._journal else 0,
            "journal compactions into the fleet snapshot this process")
        fg.journal_torn_dropped = statsmod.Formula(
            "journal_torn_dropped", lambda: self.journal_torn,
            "torn journal tail records dropped at the last recovery")
        fg.submissions_bad = statsmod.Formula(
            "submissions_bad",
            lambda: self.queue.bad_count() if self.queue else 0,
            "poisoned spool submissions quarantined to bad/")

    def _by_status(self) -> dict:
        out: dict[str, int] = {}
        for t in self.tenants.values():
            out[t.status] = out.get(t.status, 0) + 1
        return out

    def fairness_index(self) -> float:
        """Jain's fairness index over ``trials / weight`` of every tenant
        that ran: (Σx)² / (n·Σx²) ∈ (0, 1], 1.0 = perfectly weighted-fair
        allocation."""
        x = [t.trials / t.spec.weight for t in self.tenants.values()
             if t.trials > 0]
        if not x:
            return 1.0
        return float(sum(x) ** 2 / (len(x) * sum(v * v for v in x)))

    # --- the write-ahead journal ------------------------------------------

    def _open_journal(self) -> FleetJournal | None:
        """The fleet's WAL, opened lazily (no outdir → no journal, zero
        overhead).  The seq floor comes from the snapshot so sequence
        numbers stay monotonic across compactions and restarts."""
        if self._journal is None and self.outdir:
            floor = self._journal_floor
            if floor == 0:
                try:
                    snap = resil.load_json_verified(os.path.join(
                        self.outdir, "fleet_ckpt", "fleet.json"))
                    floor = int(snap.get("journal_seq", -1)) + 1
                except (OSError, ValueError):
                    pass
            self._journal = FleetJournal(journal_path(self.outdir),
                                         next_seq=floor, chaos=self.chaos)
            # the scheduler's own knobs must survive a kill BEFORE the
            # first snapshot exists, so each process journals its config
            # once at open (replay restores it; later records win)
            self._journal.append("config", {
                "policy": self.policy,
                "depth_budget": self.depth_budget,
                "retry_budget": self.retry_budget,
                "backoff_ticks": self.backoff_ticks,
                "tick_timeout": self.tick_timeout,
                "compact_every": self.compact_every})
        return self._journal

    def _jlog(self, kind: str, data: dict | None = None) -> None:
        """Durably journal one state transition BEFORE the in-memory
        ledgers are trusted (the WAL contract, statically certified as
        GL201: every mutation of journaled state is dominated by its
        _jlog).  Deliberately does NOT compact: a compaction riding the
        append would snapshot the PRE-mutation ledgers while truncating
        the very record that carries the transition — compaction runs
        only at loop-safe points (``_maybe_compact``), after the tick's
        mutations are applied."""
        j = self._open_journal()
        if j is None:
            return
        j.append(kind, data)

    def _maybe_compact(self) -> None:
        """Fold the WAL into a fresh snapshot once ``compact_every``
        records accumulate — called between ticks, never from inside
        ``_jlog`` (see there)."""
        j = self._journal
        if j is not None and j.since_compact >= self.compact_every:
            self.checkpoint()

    # --- admission --------------------------------------------------------

    def admit(self, spec: TenantSpec, ticket: str = "") -> TenantState:
        """Admit one tenant (direct or from the spool).  Names are the
        tenant identity — checkpoint namespace, stats key, chaos worker —
        so a duplicate is refused loudly rather than silently merging
        two tenants' state.  The ONE exception: a terminal ``evicted``
        tenant RELEASED its name — re-admission replaces the released
        roster entry (the returning-migration case: a federation
        gateway may place a tenant back on a pod it drained off
        earlier; the fresh admission resumes from whatever namespaced
        checkpoint the migration left)."""
        existing = self.tenants.get(spec.name)
        if existing is not None and existing.status != "evicted":
            raise ValueError(f"tenant {spec.name!r} already admitted")
        t = TenantState(spec, order=len(self.tenants), ticket=ticket)
        if spec.submitted_at:
            # queue latency is observability (submit → admission seconds
            # across processes); every scheduling decision reads only
            # admission order, trial counts and weights.  Routed through
            # the sanctioned obs.clock seam (GL106).
            t.queue_latency_s = max(0.0, obs_clock.now()
                                    - spec.submitted_at)
        self._jlog("admit", {"tenant": spec.name, "spec": spec.to_dict(),
                             "ticket": ticket, "order": t.order})
        self.tenants[spec.name] = t
        obs_trace.tracer().emit(
            "tenant_admit", cat="fleet", tenant=spec.name,
            order=t.order, priority=spec.priority, weight=spec.weight)
        debug.dprintf("Fleet", "admitted %s (priority=%d weight=%g%s)",
                      spec.name, spec.priority, spec.weight,
                      f" ticket={ticket}" if ticket else "")
        return t

    def tenant_outdir(self, name: str) -> str | None:
        if not self.outdir:
            return None
        return os.path.join(self.outdir, "tenants", sanitize(name))

    def _start(self, t: TenantState) -> None:
        """Elaborate one queued tenant: plan → orchestrator (resuming
        from its namespaced checkpoint when one exists) → step driver,
        with the fleet's certification posture applied and chaos kills
        rescoped to the tenant."""
        from shrewd_tpu.campaign.orchestrator import Orchestrator

        if t.spec.binary_digest:
            # binary-in submission: run (or resume, or warm-start) the
            # journaled ingest pipeline first; the resolved plan is an
            # ordinary pre-lifted plan pointing at store-resident
            # windows.  IngestQuarantine propagates to _note_failure,
            # which quarantines immediately (deterministic poison).
            from shrewd_tpu.campaign.plan import CampaignPlan

            plan = CampaignPlan.from_dict(self._ingest_plan(t))
        else:
            plan = t.spec.build_plan()
        if self.certify and (_CERTIFY_ORDER[self.certify]
                             > _CERTIFY_ORDER.get(plan.analysis.certify, 0)):
            # admission-time certification: the fleet's posture tightens
            # the tenant's — its executables are jaxpr/HLO-audited at
            # executable-cache admission before any trial runs
            plan.analysis.certify = self.certify
        outdir = self.tenant_outdir(t.spec.name)
        ckpt_dir = (os.path.join(outdir, "campaign_ckpt") if outdir
                    else None)
        resumable = False
        if ckpt_dir is not None:
            try:
                Orchestrator.load_checkpoint_doc(ckpt_dir)
                resumable = True
            except ValueError:
                resumable = False
        if resumable:
            t.orch = Orchestrator.resume(ckpt_dir, mesh=self.mesh,
                                         outdir=outdir)
            # the fleet posture must hold on resume too (resume rebuilds
            # the plan from the checkpoint document)
            if self.certify:
                t.orch.plan.analysis.certify = max(
                    (t.orch.plan.analysis.certify, self.certify),
                    key=lambda m: _CERTIFY_ORDER.get(m, 0))
        else:
            t.orch = Orchestrator(plan, mesh=self.mesh, outdir=outdir)
        self._scope_chaos(t)
        # the depth-budget ceiling is the SUBMITTED plan's depth, read
        # from the spec document: _rebalance mutates pcfg.depth in
        # place and the clamped value rides the tenant checkpoint, so
        # reading it back from a resumed/rebuilt orchestrator would
        # ratchet the tenant's depth down monotonically across resumes
        spec_depth = (t.spec.plan.get("pipeline") or {}).get(
            "depth", t.orch.pcfg.depth)
        t._plan_depth = max(1, int(spec_depth))
        t.driver = t.orch.stepper()
        self._jlog("status", {"tenant": t.spec.name, "status": "running"})
        t.status = "running"
        obs_trace.tracer().emit(
            "tenant_start", cat="fleet", tenant=t.spec.name,
            resumed=bool(resumable))
        if t._t_admit is None:
            t._t_admit = obs_clock.monotonic()
        self._rebalance()

    def _ingest_plan(self, t: TenantState) -> dict:
        """Binary → plan via the journaled streaming pipeline
        (ingest/pipeline.py).  The pipeline's WAL lives in the tenant's
        namespace (``tenants/<name>/ingest/``) so it rides checkpoint
        copies across migration/failover; artifacts land in the SHARED
        digest-keyed store, so a re-submission of the same binary —
        here or on any pod over the same store — warm-starts in O(1)
        with zero lifts."""
        from shrewd_tpu.ingest.pipeline import IngestPipeline

        data = t.spec.verify_binary()    # ValueError on poisoned spec
        digest = self.store.put_binary(data)
        outdir = self.tenant_outdir(t.spec.name)
        if outdir is None:
            raise RuntimeError("binary-in submission needs an outdir "
                               "(the ingest WAL is per-tenant state)")
        pipe = IngestPipeline(os.path.join(outdir, "ingest"),
                              self.store, digest, axes=t.spec.ingest,
                              chaos=self.chaos)
        pipe.run()
        self.ingest_lifts += pipe.lifts
        self.ingest_captures += pipe.captures
        return pipe.resolved_plan(t.spec.plan)

    def _scope_chaos(self, t: TenantState, engine=None) -> None:
        """Rescope a tenant's chaos engine to the fleet: the engine's
        "worker" is the tenant, and a kill_worker tears down the tenant's
        driver (``TenantKilled``), not the host process."""
        if engine is not None:
            t.orch.attach_chaos(engine)
        eng = t.orch.chaos
        if eng is None:
            return
        if not eng.worker:
            eng.worker = t.spec.name
        name = t.spec.name

        def _kill(rc: int):
            raise TenantKilled(name, rc)

        eng.kill_action = _kill

    def _rebalance(self) -> None:
        """Re-divide the global dispatch-depth budget over running
        tenants (floor 1, ceiling = each tenant's own plan depth) —
        engines read their depth live, so in-flight windows shrink/grow
        at the next fill."""
        running = [t for t in self.tenants.values()
                   if t.status == "running"]
        if not running:
            return
        share = max(1, self.depth_budget // len(running))
        for t in running:
            depth = max(1, min(t._plan_depth, share))
            t.orch.pcfg.depth = depth
            for eng in t.orch._engines.values():
                eng.depth = depth

    # --- the scheduling loop ---------------------------------------------

    def request_drain(self) -> None:
        """Graceful fleet preemption (idempotent): every running tenant
        finishes its in-flight batch, checkpoints into its namespace,
        and the fleet state is persisted resumable (rc 4)."""
        self._drain = True

    def install_signal_handlers(self):
        """SIGTERM/SIGINT → graceful fleet drain; a second signal raises
        KeyboardInterrupt (the operator escape hatch) — the
        ``Orchestrator.install_signal_handlers`` discipline extended to
        the whole fleet.  Returns a restore callable; no-op off the main
        thread."""
        import signal

        def _handler(signum, frame):
            if self._drain:
                raise KeyboardInterrupt
            self._drain = True
            debug.dprintf("Fleet", "signal %s: draining fleet to "
                          "checkpoints", signum)

        try:
            prev = {s: signal.signal(s, _handler)
                    for s in (signal.SIGTERM, signal.SIGINT)}
        except ValueError:            # not the main thread
            return lambda: None
        return lambda: [signal.signal(s, h) for s, h in prev.items()]

    def _poll_queue(self) -> None:
        if self.queue is None:
            return
        if self.chaos is not None:
            # corrupt_submission chaos: poison the scheduled pending doc
            # in place (parses, checksum fails) so the claim path's
            # bad-spool quarantine is provable on a schedule.  Documents
            # that do not parse yet (in-flight submit placeholders) are
            # not submissions: they neither consume the chaos ordinal
            # nor crash the loop the harness exists to protect.
            for ticket in self.queue.pending():
                path = os.path.join(self.queue.pending_dir, ticket)
                try:
                    with open(path) as f:
                        json.load(f)
                except (OSError, ValueError):
                    continue
                spec = self.chaos.take_corrupt_submission()
                if spec is not None:
                    chaos_mod.corrupt_json_checksum(path)
        for ticket, spec in self.queue.claim():
            try:
                self.admit(spec, ticket=ticket)
            except ValueError as e:
                # duplicate name etc: publish the refusal as the ticket's
                # result instead of wedging the spool
                debug.dprintf("Fleet", "refused %s: %s", ticket, e)
                self.queue.mark_done(ticket, {"tenant": spec.name,
                                              "status": "refused",
                                              "error": str(e)})

    def _candidates(self) -> list[TenantState]:
        out = []
        for t in self.tenants.values():
            if t.status == "queued" and t.revoked:
                # a revocation that outlived its tenant's start (journal
                # replay re-queued it, or the revoke landed while it sat
                # in backoff): prune WITHOUT elaborating — revocation
                # must never cost a plan build
                self._prune_queued(t)
                continue
            if t.status == "queued" and t.evicted:
                # an eviction that outlived its tenant's start (journal
                # replay re-queued it): release WITHOUT elaborating —
                # the new placement owns it now
                self._evict_queued(t)
                continue
            if t.status == "queued" and t.retry_at <= self.ticks:
                try:
                    self._start(t)
                except FleetKilled:
                    # a fleet-scoped chaos kill (kill_during_lift fires
                    # inside the ingest pipeline) is the whole process
                    # dying, not one tenant failing — it must NOT be
                    # swallowed into the retry/quarantine ledger
                    raise
                except Exception as e:  # noqa: BLE001 — tenant isolation:
                    # a plan that fails to elaborate (malformed dict,
                    # missing trace file, bad config) is THAT tenant's
                    # failure — it burns its retry budget and lands in
                    # quarantine with the evidence while everyone else
                    # keeps being served; a resident scheduler must
                    # never die on one bad submission
                    self._note_failure(t, e)
            if t.status == "running":
                out.append(t)
        return out

    def _in_backoff(self) -> bool:
        return any(t.status == "queued" and t.retry_at > self.ticks
                   for t in self.tenants.values())

    def _note_failure(self, t: TenantState, err: Exception) -> None:
        """One tick/elaboration exception: ledger it, tear down the dead
        driver, and either schedule a deterministic retry (exponential
        backoff counted in FLEET TICKS — no wall clock enters any
        decision) or quarantine the tenant for good.  The transition is
        journaled BEFORE any ledger mutates (GL201): a kill inside the
        append leaves the in-memory state untouched and the record
        absent — never a half-applied failure."""
        from shrewd_tpu.ingest.pipeline import IngestQuarantine

        entry = {"tick": self.ticks,
                 "error": f"{type(err).__name__}: {err}"}
        failures = t.failures + 1
        errors = (t.errors + [entry])[-_MAX_ERRORS:]
        if isinstance(err, IngestQuarantine):
            # an ingest poison verdict is deterministic — the binary
            # cannot heal, so retrying would re-run the whole capture
            # just to fail identically; quarantine NOW with the stage
            # evidence (the pipeline journaled its own verdict first)
            self._quarantine(t, failures, errors)
            return
        if failures > self.retry_budget:
            self._quarantine(t, failures, errors)
            return
        delay = self.backoff_ticks * (2 ** (failures - 1))
        retry_at = self.ticks + delay
        self._jlog("failure", {"tenant": t.spec.name,
                               "failures": failures,
                               "fleet_tick": self.ticks,
                               "retry_at": retry_at,
                               "error": entry["error"]})
        t.failures = failures
        t.errors = errors
        t.retry_at = retry_at
        t.orch = t.driver = None
        t.status = "queued"
        obs_trace.tracer().emit(
            "tenant_failure", cat="fleet", tenant=t.spec.name,
            failures=t.failures, fleet_tick=self.ticks,
            retry_at=t.retry_at)
        debug.dprintf("Fleet", "%s: failure %d/%d (%s) — retry at tick "
                      "%d", t.spec.name, t.failures, self.retry_budget,
                      err, t.retry_at)
        self._rebalance()

    def _quarantine(self, t: TenantState, failures: int | None = None,
                    errors: list | None = None) -> None:
        """Retry budget exhausted: the tenant is poison.  Park it in a
        DURABLE ``quarantined`` status — journal record (FIRST, before
        any ledger mutates), persisted exception ledger in its
        namespace, done-doc for its ticket — so it never stalls the
        fleet, never burns fair share, and never silently retries
        across a resume/recover."""
        failures = t.failures if failures is None else failures
        errors = list(t.errors) if errors is None else errors
        last = errors[-1]["error"] if errors else ""
        self._jlog("quarantine", {"tenant": t.spec.name,
                                  "failures": failures,
                                  "errors": list(errors)})
        t.status = "quarantined"
        t.failures = failures
        t.errors = errors
        t.orch = t.driver = None
        t.results = {"error": last, "failures": failures}
        t.wall_s = (obs_clock.monotonic() - t._t_admit) if t._t_admit \
            else 0.0
        obs_trace.tracer().emit(
            "tenant_quarantine", cat="fleet", tenant=t.spec.name,
            failures=t.failures, fleet_tick=self.ticks)
        outdir = self.tenant_outdir(t.spec.name)
        if outdir:
            os.makedirs(outdir, exist_ok=True)
            resil.write_json_atomic(
                os.path.join(outdir, "quarantine.json"),
                {"tenant": t.spec.name, "failures": t.failures,
                 "errors": list(t.errors)})
        if self.queue is not None and t.ticket:
            self.queue.mark_done(t.ticket, {
                "tenant": t.spec.name, "status": "quarantined",
                "failures": t.failures, "error": last})
        debug.dprintf("Fleet", "%s: QUARANTINED after %d failures (%s)",
                      t.spec.name, t.failures, last)
        self._rebalance()
        if self.outdir:
            self.checkpoint()
        # "why did this tenant quarantine" must be answerable from one
        # artifact: dump the recent-event window now, while the failing
        # tenant's dispatch/verdict/failure events are still in the
        # ring.  Guarded (GL204): the recorder is evidence, and an
        # exporter crash must never turn one failure into two.
        try:
            obs_trace.flight_dump(self.outdir, "tenant_quarantine",
                                  tenant=t.spec.name,
                                  failures=t.failures)
        except Exception as e:  # noqa: BLE001 — best-effort seam
            debug.dprintf("Fleet", "flight dump failed: %s", e)

    # --- quota revocation (the sanctioned early-stop seam) ----------------

    def revoke_quota(self, tenant: str, reason: str = "") -> bool:
        """Withdraw a tenant's remaining service (the scenario-matrix
        Pareto loop's prune seam).  Journaled BEFORE any state changes so
        a hard kill between the decision and the drain replays it
        exactly; a running tenant drains its in-flight batch to a
        resumable checkpoint and finalizes as ``pruned`` (terminal,
        excluded from fair share like quarantine — but its partial
        results stay first-class provenance, never an error).  Returns
        False when the tenant is already terminal or already revoked
        (idempotent: callers may re-decide every tick)."""
        t = self.tenants.get(tenant)
        if t is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        if t.revoked or t.status not in ("queued", "running"):
            return False
        # the DECISION is journaled before the ledger mutates (GL201):
        # a kill inside the append either replays the revocation or
        # leaves the tenant untouched — never a revoked-in-memory
        # tenant whose journal never heard about it
        reason = reason or "revoked"
        self._jlog("revoke", {"tenant": t.spec.name, "reason": reason,
                              "fleet_tick": self.ticks})
        t.revoked = reason
        obs_trace.tracer().emit(
            "tenant_revoke", cat="fleet", tenant=t.spec.name,
            reason=t.revoked, fleet_tick=self.ticks)
        debug.dprintf("Fleet", "%s: quota revoked (%s)", t.spec.name,
                      t.revoked)
        if t.status == "queued":
            self._prune_queued(t)
        else:
            t.driver.request_drain()
        return True

    # --- eviction (the migrate-out seam) ----------------------------------

    def evict(self, tenant: str, reason: str = "") -> bool:
        """Release a tenant for migration — the federation gateway's
        drain-HERE half of drain-here/recover-there: the tenant drains
        its in-flight batch to its namespaced resumable checkpoint and
        goes terminal ``evicted`` ON THIS POD (excluded from fair share,
        never re-run by this scheduler's resume/recover), while the
        checkpoint stays behind for whoever recovers it elsewhere —
        bit-identity makes the hand-off free.  The decision is journaled
        BEFORE any state changes (GL201): a hard kill between the
        decision and the drain replays the eviction exactly, so the
        gateway can never find a tenant it released still being served.
        Returns False when the tenant is already terminal, revoked or
        evicted (idempotent)."""
        t = self.tenants.get(tenant)
        if t is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        if t.evicted or t.revoked or t.status not in ("queued", "running"):
            return False
        reason = reason or "evicted"
        self._jlog("evict", {"tenant": t.spec.name, "reason": reason,
                             "fleet_tick": self.ticks})
        t.evicted = reason
        obs_trace.tracer().emit(
            "tenant_evict", cat="fleet", tenant=t.spec.name,
            reason=t.evicted, fleet_tick=self.ticks)
        debug.dprintf("Fleet", "%s: evicted for migration (%s)",
                      t.spec.name, t.evicted)
        if t.status == "queued":
            self._evict_queued(t)
        else:
            t.driver.request_drain()
        return True

    def _release_queued(self, t: TenantState, status: str,
                        reason: str) -> None:
        """A queued tenant goes terminal WITHOUT elaboration — the
        shared tail of revocation (``pruned``) and eviction
        (``evicted``): journal-first status record, done-doc with the
        reason, durable snapshot.  Releasing must never cost a plan
        build (a plan that cannot elaborate must still be releasable),
        and an evicted tenant's (possibly absent) checkpoint is already
        whatever the new placement will resume from."""
        wall_s = (obs_clock.monotonic() - t._t_admit) if t._t_admit \
            else 0.0
        self._jlog("status", {"tenant": t.spec.name, "status": status,
                              "trials": t.trials, "batches": t.batches,
                              "wall_s": round(wall_s, 3),
                              "results": t.results})
        t.status = status
        t.wall_s = wall_s
        obs_trace.tracer().emit(
            f"tenant_{status}", cat="fleet", tenant=t.spec.name,
            trials=t.trials, reason=reason)
        if self.queue is not None and t.ticket:
            self.queue.mark_done(t.ticket, {
                "tenant": t.spec.name, "status": status,
                "reason": reason, "trials": t.trials,
                "results": t.results})
        if self.outdir:
            self.checkpoint()

    def _evict_queued(self, t: TenantState) -> None:
        self._release_queued(t, "evicted", t.evicted)

    def _prune_queued(self, t: TenantState) -> None:
        self._release_queued(t, "pruned", t.revoked)

    def _pick(self, cands: list[TenantState]) -> TenantState:
        top = max(t.spec.priority for t in cands)
        cls = [t for t in cands if t.spec.priority == top]
        if self.policy == "priority":
            return min(cls, key=lambda t: t.order)
        return min(cls, key=lambda t: (t.vtime, t.order))

    def _handle_kill(self, t: TenantState, e: TenantKilled) -> None:
        """The fleet-scoped worker death: only THIS tenant's
        orchestrator died.  Rebuild it — from its namespaced checkpoint
        when one exists, else from scratch — carrying the SAME chaos
        engine (its schedule state, including the consumed kill, must
        survive the rebuild or the kill would re-fire forever), and
        keep running.  Frozen keys make the recovered tallies
        bit-identical either way."""
        kills = t.kills + 1
        debug.dprintf("Fleet", "%s: %s — rebuilding tenant", t.spec.name, e)
        self._jlog("tenant_kill", {"tenant": t.spec.name,
                                   "kills": kills})
        t.kills = kills
        obs_trace.tracer().emit("tenant_kill", cat="fleet",
                                tenant=t.spec.name, kills=t.kills)
        engine = t.orch.chaos
        t.status = "queued"
        t.orch = t.driver = None
        self._start(t)
        self._scope_chaos(t, engine=engine)

    def _tick_tenant(self, t: TenantState) -> None:
        # ambient tenant scope: every event the tick emits from nested
        # seams (exec cache, watchdog, integrity, chaos) lands in this
        # tenant's lane without threading identity through every call
        with obs_trace.tracer().scope(tenant=t.spec.name):
            self._tick_tenant_scoped(t)

    def _tick_tenant_scoped(self, t: TenantState) -> None:
        obs_trace.tracer().emit(
            "tenant_tick", cat="fleet", tenant=t.spec.name,
            fleet_tick=self.ticks, tick=t.ticks)
        try:
            if self._watchdog is not None:
                # per-tenant tick watchdog: a livelocked tick (wedged
                # host loop, runaway elaboration) is abandoned at the
                # deadline (DispatchTimeout) instead of wedging the
                # whole scheduler; the failure path below quarantines
                # repeat offenders
                self._watchdog.call(t.driver.tick)
            else:
                t.driver.tick()
        except TenantKilled as e:
            self._handle_kill(t, e)
            return
        except Exception as e:  # noqa: BLE001 — tenant isolation: an
            # exception escaping the event stream is unrecoverable FOR
            # THIS TENANT'S DRIVER (lazy elaboration of a bad plan at
            # first tick, a missing trace file, a config the models
            # reject, a livelock deadline — the ladder/integrity layers
            # already absorbed everything transient inside the
            # generator).  Ledger it; retry on a tick-counted backoff;
            # quarantine when the budget is gone.  The fleet keeps
            # serving either way.
            self._note_failure(t, e)
            return
        trials = sum(st.trials for st in t.orch.state.values())
        batches = trials // max(t.orch.batch_size, 1)
        self._jlog("tick", {"tenant": t.spec.name,
                            "fleet_tick": self.ticks,
                            "trials": trials, "batches": batches,
                            "ticks": t.ticks + 1, "kills": t.kills})
        t.ticks += 1
        t.trials = trials
        t.batches = batches
        if t.driver.done:
            self._finalize(t)
            return
        if (t.spec.quota_batches
                and t.batches >= t.spec.quota_batches):
            # quota exhausted: drain THIS tenant to a resumable
            # checkpoint (status "quota") — the next tick finishes its
            # in-flight batch and preempts it
            debug.dprintf("Fleet", "%s: quota %d batches reached — "
                          "draining", t.spec.name, t.spec.quota_batches)
            t.driver.request_drain()

    def _finalize(self, t: TenantState) -> None:
        rc = t.driver.rc
        from shrewd_tpu.campaign.orchestrator import Orchestrator

        if rc == Orchestrator.RC_ABORTED:
            # honesty outranks the revocation: an abort (integrity/
            # budget) during the drain is still an abort
            status = "aborted"
        elif t.revoked:
            # the journaled revocation decision is authoritative over
            # every cooperative ending — including a campaign whose
            # final in-flight batch happened to complete it during the
            # drain (rc 0): the quota WAS withdrawn first, and the
            # Pareto artifact's decision list must match the statuses
            status = "pruned"
        elif t.evicted and rc == Orchestrator.RC_PREEMPTED:
            # the drain the eviction requested completed: released for
            # migration, checkpoint left behind.  A campaign whose final
            # in-flight batch happened to COMPLETE it during the drain
            # (rc 0) stays "complete" — there is nothing left to
            # migrate, and the gateway reads the status to decide
            status = "evicted"
        elif rc == Orchestrator.RC_PREEMPTED:
            status = ("quota" if t.spec.quota_batches
                      and t.batches >= t.spec.quota_batches
                      else "preempted")
        else:
            status = "complete"
            if t.kills and t.orch.chaos is not None:
                # the killed tenant finished with believed tallies: the
                # injected kill was survived (the ledger the chaos stats
                # group reports)
                for _ in range(t.kills):
                    t.orch.chaos.note_survived("kill_worker")
        wall_s = (obs_clock.monotonic() - t._t_admit) if t._t_admit \
            else 0.0
        results = self._summarize(t)
        self._jlog("status", {"tenant": t.spec.name, "status": status,
                              "rc": rc, "trials": t.trials,
                              "batches": t.batches,
                              "wall_s": round(wall_s, 3),
                              "results": results})
        t.status = status
        t.rc = rc
        t.wall_s = wall_s
        t.results = results
        obs_trace.tracer().emit(
            "tenant_done", cat="fleet", tenant=t.spec.name,
            status=t.status, rc=t.rc, trials=t.trials)
        t.orch.write_outputs()
        if t.orch.outdir and t.status == "complete":
            t.orch.checkpoint()          # the final-state dump _drive writes
        if self.queue is not None and t.ticket:
            done = {
                "tenant": t.spec.name, "status": t.status, "rc": t.rc,
                "trials": t.trials, "batches": t.batches,
                "wall_s": round(t.wall_s, 3), "results": t.results}
            if t.revoked:
                # same done-doc shape as the queued-prune path: a
                # submitter whose cell was pruned mid-run learns the
                # dominator from its ticket too
                done["reason"] = t.revoked
            elif t.evicted:
                done["reason"] = t.evicted
            self.queue.mark_done(t.ticket, done)
        debug.dprintf("Fleet", "%s: %s (rc=%s, %d trials, %d ticks)",
                      t.spec.name, t.status, t.rc, t.trials, t.ticks)
        self._rebalance()
        if self.outdir:
            self.checkpoint()

    def _summarize(self, t: TenantState) -> dict:
        """JSON-able per-(simpoint, structure) final state: completed
        tenants summarize their StructureResults; preempted/aborted ones
        summarize their partial cumulative state (what the checkpoint
        holds).  The per-stratum tally history rides along (from the
        orchestrator's cumulative state, the one place it lives) so a
        stratified campaign's half-width can be recomputed from the
        summary with the SAME estimator the stopping rule used —
        downstream folds (the scenario Pareto loop) must not silently
        degrade to pooled Wilson on terminal tenants."""
        def strata_of(sp, st):
            s = t.orch.state.get((sp, st)) if t.orch is not None else None
            return (s.strata.tolist()
                    if s is not None and s.strata is not None else None)

        out = {}
        if t.driver.results is not None:
            for (sp, st), r in t.driver.results.items():
                out[f"{sp}/{st}"] = {
                    "tallies": np.asarray(r.tallies).tolist(),
                    "trials": int(r.trials), "avf": float(r.avf),
                    "converged": bool(r.converged),
                    "strata": strata_of(sp, st)}
        else:
            # partial cumulative state (preempted / pruned mid-run):
            # the tallies are exact counts over the consumed batch
            # prefix, so the AVF is exact too — a revocation-pruned
            # shard's done-doc is first-class provenance in the
            # gateway's sharded merge, never a null to be re-derived
            from shrewd_tpu.ops import classify as C

            for (sp, st), s in t.orch.state.items():
                vul = int(s.tallies[C.OUTCOME_SDC]
                          + s.tallies[C.OUTCOME_DUE])
                out[f"{sp}/{st}"] = {
                    "tallies": s.tallies.tolist(),
                    "trials": int(s.trials),
                    "avf": (vul / int(s.trials) if s.trials > 0
                            else None),
                    "converged": bool(s.converged),
                    "strata": (s.strata.tolist()
                               if s.strata is not None else None)}
        return out

    def step(self) -> object:
        """ONE scheduling quantum — the cooperative surface a federation
        driver round-robins N pod schedulers through in a single
        process (``shrewd_tpu/federation/``): every quantum runs to an
        instruction boundary and hands control back, so pods interleave
        deterministically without threads (bit-identity never depended
        on scheduling anyway — frozen per-batch keys — but a
        single-threaded round-robin makes the *schedule logs*
        reproducible too).  Returns ``None`` after a quantum of
        progress, ``IDLE`` when the fleet is resident-idle (spool
        attached, ``idle_exit`` off, nothing runnable — the caller
        decides whether to sleep or serve another pod), or the terminal
        fleet rc (int)."""
        if self._drain:
            return self._drain_all()
        if self.chaos is not None:
            # kill_fleet at a tick ordinal: the hard kill lands at
            # the instruction boundary between ticks — nothing
            # drains, nothing checkpoints; the journal is the only
            # survivor (which is the point)
            self.chaos.maybe_kill_fleet(tick=self.ticks)
        self._poll_queue()
        cands = self._candidates()
        if not cands:
            if self._in_backoff():
                # a tenant waits out its retry backoff and nothing
                # else is runnable: consume an idle quantum — the
                # backoff is counted in fleet ticks, so idling must
                # advance them (deterministic, clock-free)
                self.ticks += 1
                return None
            if self.queue is not None and not self.idle_exit:
                return IDLE
            return self._shutdown()
        t = self._pick(cands)
        self.schedule_log.append(t.spec.name)
        self.ticks += 1
        self._tick_tenant(t)
        self._maybe_compact()
        self._publish_metrics()
        if self.on_tick is not None:
            self.on_tick(self)
        return None

    def _shutdown(self) -> int:
        """Every tenant terminal and the spool (if any) drained: persist
        outputs + the shutdown journal record, report the fleet rc."""
        self.write_outputs()
        if self.outdir:
            self._jlog("shutdown", {"statuses": self._by_status()})
            self.checkpoint()
        if any(t.status == "aborted" for t in self.tenants.values()):
            return 3
        return 0

    def run(self) -> int:
        """Drive the fleet: poll the spool, pick, tick, finalize — until
        every tenant is terminal and (with ``idle_exit``) the spool is
        empty, or a drain is requested.  Exactly ``step()`` in a loop
        (one code path — the federation's cooperative stepping cannot
        drift from the resident loop).  Returns the fleet rc: 0 all
        served, 3 when any tenant aborted (budget/integrity), 4 when the
        fleet was drained (resumable)."""
        while True:
            rc = self.step()
            if rc is IDLE:
                time.sleep(self.poll_interval)
            elif rc is not None:
                return rc

    def _drain_all(self) -> int:
        """Graceful fleet preemption: every running tenant drains to a
        namespaced resumable checkpoint; queued tenants stay queued in
        the fleet checkpoint.  rc 4, resumable via ``resume()``."""
        self.preempted = True
        for t in self.tenants.values():
            if t.status == "running":
                t.driver.request_drain()
                while t.driver is not None and not t.driver.done:
                    self.ticks += 1
                    t.ticks += 1
                    try:
                        t.driver.tick()
                    except TenantKilled as e:
                        # belt-and-braces: the drain flag preempts at
                        # the next batch boundary before any compute,
                        # so a kill should not be reachable here — but
                        # if one ever is, it must not break the drain
                        # contract (every tenant checkpoints, fleet
                        # exits resumable): rebuild and re-drain
                        self._handle_kill(t, e)
                        t.driver.request_drain()
                    except Exception as e:  # noqa: BLE001 — isolation,
                        # as in _tick_tenant: a dead tenant must not
                        # stop the rest of the fleet from draining (it
                        # keeps its retry budget for the resumed fleet)
                        self._note_failure(t, e)
                        break
                if t.status == "running":
                    self._finalize(t)
        self.write_outputs()
        if self.outdir:
            self._jlog("shutdown", {"drained": True,
                                    "statuses": self._by_status()})
            self.checkpoint()
        debug.dprintf("Fleet", "fleet drained: %s", self._by_status())
        return 4

    def _publish_metrics(self) -> None:
        """Atomic per-tick metrics snapshot (``metrics.json`` +
        Prometheus text) — the live pull surface ``tools/obs.py --tail``
        and scrapers consume.  Best-effort: an observability write must
        never take the fleet down."""
        if not self.outdir:
            return
        try:
            obs_metrics.publish(self.outdir, self)
        except Exception as e:  # noqa: BLE001 — the publish path runs
            # real computation (half-widths, serialization) per tick; NO
            # exception from it may take the resident fleet down
            debug.dprintf("Fleet", "metrics publish failed: %s", e)

    # --- fleet state persistence / outputs --------------------------------

    def results(self) -> dict:
        return {n: t.results for n, t in self.tenants.items()}

    def tenant_tallies(self, name: str) -> dict:
        """{(simpoint, structure): int64 tallies} for one tenant — the
        bit-identity comparison surface the fleet tests pin against each
        tenant's solo serial run."""
        t = self.tenants[name]
        out = {}
        for key, row in (t.results or {}).items():
            sp, st = key.split("/", 1)
            out[(sp, st)] = np.asarray(row["tallies"], dtype=np.int64)
        return out

    def write_outputs(self) -> None:
        if not self.outdir:
            return
        os.makedirs(self.outdir, exist_ok=True)
        self._publish_metrics()     # terminal statuses visible to tailers
        with open(os.path.join(self.outdir, "fleet_stats.txt"), "w") as f:
            statsmod.dump_text(self.stats, f)
        with open(os.path.join(self.outdir, "fleet_stats.json"), "w") as f:
            statsmod.dump_json(self.stats, f)
        tracer = obs_trace.tracer()
        if tracer.enabled:
            from shrewd_tpu.obs import export as obs_export

            # fleet-level Perfetto export: per-tenant lanes on the pid
            # axis (the tenant scope every tick wraps its events in)
            resil.write_json_atomic(
                os.path.join(self.outdir, "trace.json"),
                obs_export.to_trace_event(tracer.snapshot()))

    def checkpoint(self) -> str:
        """Persist the fleet's own resumable state (atomic, checksummed —
        the campaign-checkpoint discipline): tenant specs, statuses,
        fair-share ledgers and result summaries.  Per-tenant campaign
        state lives in each tenant's namespaced checkpoint; this document
        only has to say who exists and where they stand.  A durable
        snapshot compacts the write-ahead journal behind it (the
        snapshot-first ordering makes a crash between the two leave
        duplicates — skipped by seq at replay — never a gap)."""
        ckpt_dir = os.path.join(self.outdir, "fleet_ckpt")
        os.makedirs(ckpt_dir, exist_ok=True)
        doc = {"version": FLEET_CKPT_VERSION, "policy": self.policy,
               "depth_budget": self.depth_budget, "ticks": self.ticks,
               "retry_budget": self.retry_budget,
               "backoff_ticks": self.backoff_ticks,
               "tick_timeout": self.tick_timeout,
               "compact_every": self.compact_every,
               "recoveries": self.recoveries,
               "journal_seq": (self._journal.next_seq - 1
                               if self._journal is not None else
                               self._journal_floor - 1),
               "tenants": [t.to_dict() for t in self.tenants.values()]}
        doc["checksum"] = resil.doc_checksum(doc)
        resil.write_json_atomic(os.path.join(ckpt_dir, "fleet.json"), doc)
        if self._journal is not None:
            self._journal.compact()
        return ckpt_dir

    def _admit_from_dict(self, td: dict) -> TenantState:
        """Rebuild one TenantState from a snapshot/journal document —
        the replay path, which must NOT re-journal the admission."""
        spec = TenantSpec.from_dict(td["spec"])
        t = TenantState(spec, order=int(td.get("order", len(self.tenants))),
                        ticket=td.get("ticket", ""))
        t.status = td.get("status", "queued")
        t.trials = int(td.get("trials", 0))
        t.batches = int(td.get("batches", 0))
        t.ticks = int(td.get("ticks", 0))
        t.kills = int(td.get("kills", 0))
        t.failures = int(td.get("failures", 0))
        t.errors = list(td.get("errors") or [])
        t.revoked = str(td.get("revoked") or "")
        t.evicted = str(td.get("evicted") or "")
        t.rc = td.get("rc")
        t.results = td.get("results")
        t.queue_latency_s = float(td.get("queue_latency_s", 0.0))
        t.wall_s = float(td.get("wall_s", 0.0))
        self.tenants[spec.name] = t
        return t

    def _apply_record(self, r: dict) -> None:
        """Replay one journal record onto the tenant table (idempotent:
        records carry absolute values, not deltas)."""
        kind = r.get("kind")
        if kind == "config":
            if "policy" in r and "policy" not in self._explicit_params:
                self.policy = str(r["policy"])
            for k, cast in (("depth_budget", int), ("retry_budget", int),
                            ("backoff_ticks", int), ("compact_every", int),
                            ("tick_timeout", float)):
                if k in r and k not in self._explicit_params:
                    setattr(self, k, cast(r[k]))
            self._watchdog = (resil.DeviceWatchdog(
                timeout=self.tick_timeout, name="fleet-tick")
                if self.tick_timeout > 0 else None)
            return
        if kind in ("shutdown", "recover"):
            # lifecycle markers: nothing to restore, but the dispatch
            # handles them EXPLICITLY so the GL202 exhaustiveness check
            # can prove every appended kind has a considered replay
            # story (an unlisted kind is a recovery gap, not noise)
            return
        if kind == "admit":
            existing = self.tenants.get(r.get("tenant", ""))
            if existing is None or existing.status == "evicted":
                # a re-admission over a RELEASED (evicted) name replays
                # as a replacement, mirroring admit()'s one exception
                self._admit_from_dict({"spec": r["spec"],
                                       "order": r.get("order", 0),
                                       "ticket": r.get("ticket", ""),
                                       "status": "queued"})
            return
        t = self.tenants.get(r.get("tenant", ""))
        if t is None:
            return
        if kind == "tick":
            t.trials = int(r.get("trials", t.trials))
            t.batches = int(r.get("batches", t.batches))
            t.ticks = int(r.get("ticks", t.ticks))
            t.kills = int(r.get("kills", t.kills))
            self.ticks = max(self.ticks, int(r.get("fleet_tick", 0)))
        elif kind == "failure":
            t.failures = int(r.get("failures", t.failures))
            t.errors.append({"tick": r.get("fleet_tick", 0),
                             "error": r.get("error", "")})
            del t.errors[:-_MAX_ERRORS]
            t.status = "queued"
            self.ticks = max(self.ticks, int(r.get("fleet_tick", 0)))
        elif kind == "quarantine":
            t.status = "quarantined"
            t.failures = int(r.get("failures", t.failures))
            t.errors = list(r.get("errors") or t.errors)
            last = t.errors[-1]["error"] if t.errors else ""
            t.results = {"error": last, "failures": t.failures}
        elif kind == "tenant_kill":
            t.kills = int(r.get("kills", t.kills))
        elif kind == "revoke":
            # the revocation DECISION is durable the instant it is made:
            # a kill between the decision and the drain replays it here,
            # and _candidates prunes the re-queued tenant without ever
            # elaborating it — the journaled decision, not the drain,
            # is what makes prune-replay exact
            t.revoked = str(r.get("reason") or "revoked")
            self.ticks = max(self.ticks, int(r.get("fleet_tick", 0)))
        elif kind == "evict":
            # like revoke: the DECISION is durable the instant it is
            # made — a kill between the decision and the drain replays
            # it here, and _candidates releases the re-queued tenant
            # without elaboration (the new placement owns it)
            t.evicted = str(r.get("reason") or "evicted")
            self.ticks = max(self.ticks, int(r.get("fleet_tick", 0)))
        elif kind == "status":
            t.status = r.get("status", t.status)
            if "rc" in r:
                t.rc = r["rc"]
            if "trials" in r:
                t.trials = int(r["trials"])
            if "batches" in r:
                t.batches = int(r["batches"])
            if "results" in r:
                t.results = r["results"]
            if "wall_s" in r:
                t.wall_s = float(r["wall_s"])

    @classmethod
    def recover(cls, outdir: str, mesh=None,
                queue: SubmissionQueue | None = None,
                **kw) -> "CampaignScheduler":
        """Rebuild a fleet after ANY shutdown — graceful drain or hard
        kill — by replaying ``fleet_ckpt/fleet.json`` plus every journal
        record beyond it.  Terminal tenants (complete/aborted/quota/
        quarantined) keep their recorded state; resumable ones are
        re-queued and continue from their namespaced campaign
        checkpoints on the next ``run()`` — bit-identical to an
        undisturbed fleet, because per-batch tallies are pure functions
        of their frozen PRNG keys no matter where the kill landed.  The
        (possibly torn) journal is immediately folded into a fresh
        snapshot, so recovery is itself crash-safe."""
        ckpt_dir = os.path.join(outdir, "fleet_ckpt")
        snap_path = os.path.join(ckpt_dir, "fleet.json")
        snap = None
        if os.path.exists(snap_path):
            snap = resil.load_json_verified(snap_path)
            if snap.get("version") not in _CKPT_VERSIONS:
                raise ValueError(
                    f"fleet checkpoint version {snap.get('version')} "
                    f"not in {_CKPT_VERSIONS}")
        jpath = journal_path(outdir)
        records, torn, _valid = (FleetJournal.replay_path(jpath)
                                 if os.path.exists(jpath) else ([], 0, 0))
        snap_seq = int(snap.get("journal_seq", -1)) if snap else -1
        fresh = [r for r in records if int(r["seq"]) > snap_seq]
        # a lone config record is just this-or-a-prior open's preamble,
        # not un-replayed fleet state
        dirty = any(r["kind"] != "config" for r in fresh) or torn > 0
        explicit = frozenset(
            k for k in ("depth_budget", "policy", "retry_budget",
                        "backoff_ticks", "tick_timeout", "compact_every")
            if k in kw)

        def _p(name, default):
            return kw.pop(name, snap.get(name, default) if snap
                          else default)

        sched = cls(outdir=outdir, mesh=mesh, queue=queue,
                    depth_budget=_p("depth_budget", 4),
                    policy=_p("policy", "fair"),
                    retry_budget=_p("retry_budget", 3),
                    backoff_ticks=_p("backoff_ticks", 2),
                    tick_timeout=_p("tick_timeout", 0.0),
                    compact_every=_p("compact_every", 64), **kw)
        sched._explicit_params = explicit
        sched.journal_torn = torn
        if snap:
            sched.recoveries = int(snap.get("recoveries", 0))
            sched.ticks = int(snap.get("ticks", 0))
            for td in sorted(snap["tenants"], key=lambda d: d["order"]):
                sched._admit_from_dict(td)
        for r in fresh:
            sched._apply_record(r)
        for t in sched.tenants.values():
            if t.status in _RESUMABLE:
                t.status = "queued"    # _start resumes from its ckpt
                t.retry_at = 0         # a recovery re-arms retries NOW;
                #                        the failure count survives, so a
                #                        poison tenant cannot mine a fresh
                #                        budget out of every crash
            elif (queue is not None and t.ticket
                    and t.status in ("complete", "aborted", "quota",
                                     "quarantined", "pruned", "evicted")
                    and queue.done(t.ticket) is None):
                # the kill landed between the terminal journal record
                # and mark_done: the replayed state is authoritative, so
                # publish the done-doc now or the submitter's ticket
                # would stay claimed (and unanswered) forever
                done = {
                    "tenant": t.spec.name, "status": t.status,
                    "rc": t.rc, "trials": t.trials,
                    "batches": t.batches, "failures": t.failures,
                    "wall_s": round(t.wall_s, 3), "results": t.results}
                if t.revoked:
                    done["reason"] = t.revoked
                elif t.evicted:
                    done["reason"] = t.evicted
                queue.mark_done(t.ticket, done)
        sched._journal_floor = max(
            snap_seq + 1, (records[-1]["seq"] + 1) if records else 0)
        sched._open_journal()
        if dirty:
            sched.recoveries += 1
            sched._jlog("recover", {"recoveries": sched.recoveries,
                                    "replayed": len(fresh),
                                    "torn_dropped": torn})
            obs_trace.tracer().emit(
                "fleet_recover", cat="fleet",
                recoveries=sched.recoveries, replayed=len(fresh),
                torn_dropped=torn)
            debug.dprintf("Fleet", "recovered dirty fleet: %d journal "
                          "records replayed, %d torn dropped",
                          len(fresh), torn)
        # fold the replayed state (and the recover record) into a fresh
        # snapshot and truncate the (possibly torn) journal before any
        # new work appends to it — recovery is itself crash-safe, and a
        # recovered-then-idle fleet reads as clean
        sched.checkpoint()
        return sched

    @classmethod
    def resume(cls, outdir: str, mesh=None,
               queue: SubmissionQueue | None = None,
               **kw) -> "CampaignScheduler":
        """Rebuild a CLEANLY drained fleet from its snapshot.  Refuses a
        dirty shutdown (journal records beyond the snapshot — the
        hard-kill signature) so un-replayed state is never silently
        discarded; ``recover()`` is the superset that handles both."""
        snap_path = os.path.join(outdir, "fleet_ckpt", "fleet.json")
        if is_dirty(outdir):
            raise ValueError(
                f"{outdir}: dirty shutdown detected (journal holds "
                "records beyond the snapshot) — resume would lose "
                "state; use CampaignScheduler.recover() / "
                "fleet.py --recover")
        if not os.path.exists(snap_path):
            raise FileNotFoundError(f"{snap_path}: no fleet checkpoint")
        return cls.recover(outdir, mesh=mesh, queue=queue, **kw)
