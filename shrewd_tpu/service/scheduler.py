"""The resident multi-tenant campaign scheduler (see package docstring).

One ``CampaignScheduler`` owns one mesh for its whole life and ticks many
campaigns through it: each admitted ``TenantSpec`` becomes an
``Orchestrator`` + ``StepDriver`` pair, and every scheduler tick advances
exactly one tenant by one batch (serial) or one sync interval
(pipelined).  Interleaving is where the throughput comes from: while
tenant A's tick runs host-side work (stopping rule, invariants, stats,
checkpoints), tenant B's in-flight intervals keep computing on the
device, and the content-keyed executable cache (``parallel/exec_cache``)
dedupes compiles across tenants sharing a window — the second tenant on
a shared window compiles zero new steps (asserted in the fleet test).

Scheduling is deterministic by construction: policies consume only
admission order, per-tenant trial counts and weights — never wall clock
— so a fleet's schedule log is reproducible, and each tenant's tallies
are bit-identical to its solo serial run regardless of interleaving
(frozen per-batch PRNG keys; the invariant every layer of this codebase
preserves).

Policies (``policy=``):

- ``"fair"`` (default) — strict priority classes; within the runnable
  class with the highest priority, weighted fair-share stride
  scheduling: pick the tenant with the smallest virtual time
  ``trials / weight`` (ties break on admission order).
- ``"priority"`` — strict priority, FIFO within a class (admission
  order), for drain-one-tenant-first operation.

The **global dispatch-depth budget** bounds how much device work the
whole fleet keeps in flight: each running tenant's pipelined engine
depth is clamped to ``max(1, depth_budget // n_running)`` (re-balanced
as tenants come and go), with the per-tenant plan depth as ceiling and a
floor of 1 — the fleet cannot over-subscribe the mesh the way N
independent processes would.

Failure isolation: every tenant owns its watchdog, ladder, integrity
monitor and chaos engine, so a wedge or corrupt tally quarantines and
recovers INSIDE the afflicted tenant.  A chaos ``kill_worker`` is
rescoped at admission (``ChaosEngine.kill_action``): in a fleet the
"worker" is the tenant's step driver, so the kill tears down only that
tenant's orchestrator — the scheduler rebuilds it from its last
per-tenant checkpoint (or from scratch; frozen keys make both
bit-identical) while every other tenant keeps running.

Import discipline: jax-free at module import (the scheduler is pure
host-side control; jax enters when a tenant's orchestrator is built).
"""

from __future__ import annotations

import os
import time

import numpy as np

from shrewd_tpu import resilience as resil
from shrewd_tpu import stats as statsmod
from shrewd_tpu.service.queue import SubmissionQueue, TenantSpec, sanitize
from shrewd_tpu.utils import debug

FLEET_CKPT_VERSION = 1

POLICIES = ("fair", "priority")

#: certify escalation order (the fleet's admission-time certification
#: posture can tighten a tenant's plan, never loosen it)
_CERTIFY_ORDER = {"off": 0, "warn": 1, "strict": 2}

#: tenant terminal statuses a fleet resume re-admits (a resumable tenant
#: continues from its namespaced checkpoint; ``quota`` stays parked until
#: the operator resubmits with a bigger quota)
_RESUMABLE = ("queued", "running", "preempted")


class TenantKilled(RuntimeError):
    """A chaos ``kill_worker`` fired inside a tenant's tick (the
    fleet-scoped analog of ``os._exit``): the tenant's orchestrator is
    dead; the scheduler rebuilds and resumes it."""

    def __init__(self, tenant: str, rc: int):
        super().__init__(f"tenant {tenant!r} killed by chaos (rc {rc})")
        self.tenant = tenant
        self.rc = rc


class TenantState:
    """One tenant's life in the fleet: spec + driver + ledgers."""

    def __init__(self, spec: TenantSpec, order: int, ticket: str = ""):
        self.spec = spec
        self.order = order           # admission order (the FIFO tiebreak)
        self.ticket = ticket         # spool ticket ("" = direct admit)
        self.status = "queued"
        self.orch = None
        self.driver = None
        self.trials = 0              # trials served (the fair-share unit)
        self.batches = 0             # trials // effective batch size
        self.ticks = 0               # scheduling quanta consumed
        self.kills = 0               # chaos kill_worker fires survived
        self.rc: int | None = None
        self.queue_latency_s = 0.0   # submit → admission
        self.wall_s = 0.0            # admission → terminal
        self._t_admit: float | None = None
        self._plan_depth = 1         # the plan's own depth (budget ceiling)
        self.results: dict | None = None   # JSON-able per-structure summary

    @property
    def vtime(self) -> float:
        return self.trials / self.spec.weight

    def to_dict(self) -> dict:
        return {"spec": self.spec.to_dict(), "order": self.order,
                "ticket": self.ticket, "status": self.status,
                "trials": self.trials, "batches": self.batches,
                "ticks": self.ticks, "kills": self.kills, "rc": self.rc,
                "queue_latency_s": round(self.queue_latency_s, 3),
                "wall_s": round(self.wall_s, 3), "results": self.results}


class CampaignScheduler:
    """The resident scheduler (see module docstring).

    ``outdir`` namespaces everything per tenant:
    ``outdir/tenants/<name>/`` holds each tenant's m5out artifacts and
    its ``campaign_ckpt`` (the per-tenant checkpoint namespace), and
    ``outdir/fleet_ckpt/fleet.json`` + ``outdir/fleet_stats.json`` hold
    the fleet's own resumable state and stats dump."""

    def __init__(self, outdir: str | None = None, mesh=None,
                 depth_budget: int = 4, policy: str = "fair",
                 queue: SubmissionQueue | None = None, certify: str = "",
                 idle_exit: bool = True, poll_interval: float = 0.2,
                 on_tick=None):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r} (one of {POLICIES})")
        if certify and certify not in _CERTIFY_ORDER:
            raise ValueError(f"unknown certify mode {certify!r}")
        self.outdir = outdir
        self._mesh = mesh
        self.depth_budget = max(1, int(depth_budget))
        self.policy = policy
        self.queue = queue
        self.certify = certify
        self.idle_exit = idle_exit
        self.poll_interval = float(poll_interval)
        self.on_tick = on_tick
        self.tenants: dict[str, TenantState] = {}
        self.schedule_log: list[str] = []    # tenant name per tick
        self.ticks = 0
        self._drain = False
        self.preempted = False
        self._t0 = time.monotonic()
        self._build_stats()

    # --- mesh / stats -----------------------------------------------------

    @property
    def mesh(self):
        """The fleet's ONE mesh, built lazily (jax enters here): every
        tenant's campaigns shard over the same devices, which is what
        makes their executables cache-interchangeable."""
        if self._mesh is None:
            from shrewd_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh()
        return self._mesh

    def _build_stats(self) -> None:
        """``campaign.fleet.*`` — the multi-tenant ledger: who ran, how
        fairly, how fast, and how much compile work co-scheduling
        deduped.  Formulas read live scheduler state, like every other
        stats group in the tree."""
        from shrewd_tpu.parallel import exec_cache

        self.stats = statsmod.Group("campaign")
        fg = statsmod.Group("fleet")
        self.stats.fleet = fg
        fg.tenants_admitted = statsmod.Formula(
            "tenants_admitted", lambda: len(self.tenants),
            "tenants admitted to the fleet")
        fg.tenants_by_status = statsmod.Formula(
            "tenants_by_status", lambda: self._by_status(),
            "tenant count per terminal/live status")
        fg.ticks = statsmod.Formula(
            "ticks", lambda: self.ticks,
            "scheduling quanta dispatched fleet-wide")
        fg.depth_budget = statsmod.Formula(
            "depth_budget", lambda: self.depth_budget,
            "global dispatch-depth budget shared by running tenants")
        fg.tenant_trials = statsmod.Formula(
            "tenant_trials",
            lambda: {n: t.trials for n, t in self.tenants.items()},
            "trials served per tenant")
        fg.tenant_throughput = statsmod.Formula(
            "tenant_throughput",
            lambda: {n: round(t.trials / t.wall_s, 1)
                     for n, t in self.tenants.items() if t.wall_s > 0},
            "per-tenant trials/second (admission to terminal)")
        fg.queue_latency_s = statsmod.Formula(
            "queue_latency_s",
            lambda: {n: round(t.queue_latency_s, 3)
                     for n, t in self.tenants.items() if t.ticket},
            "spool-submit to admission latency per queued tenant")
        fg.fairness_index = statsmod.Formula(
            "fairness_index", lambda: self.fairness_index(),
            "Jain index over weight-normalized trials served "
            "(1.0 = perfectly weighted-fair)")
        fg.cache_hit_rate = statsmod.Formula(
            "cache_hit_rate",
            lambda: (lambda s: round(s["reused"]
                                     / max(s["reused"] + s["compiled"], 1),
                                     4))(exec_cache.cache().stats()),
            "process-wide executable-cache hit rate (cross-tenant "
            "compile dedupe)")
        fg.schedule_ticks = statsmod.Formula(
            "schedule_ticks",
            lambda: {n: t.ticks for n, t in self.tenants.items()},
            "scheduling quanta per tenant")

    def _by_status(self) -> dict:
        out: dict[str, int] = {}
        for t in self.tenants.values():
            out[t.status] = out.get(t.status, 0) + 1
        return out

    def fairness_index(self) -> float:
        """Jain's fairness index over ``trials / weight`` of every tenant
        that ran: (Σx)² / (n·Σx²) ∈ (0, 1], 1.0 = perfectly weighted-fair
        allocation."""
        x = [t.trials / t.spec.weight for t in self.tenants.values()
             if t.trials > 0]
        if not x:
            return 1.0
        return float(sum(x) ** 2 / (len(x) * sum(v * v for v in x)))

    # --- admission --------------------------------------------------------

    def admit(self, spec: TenantSpec, ticket: str = "") -> TenantState:
        """Admit one tenant (direct or from the spool).  Names are the
        tenant identity — checkpoint namespace, stats key, chaos worker —
        so a duplicate is refused loudly rather than silently merging
        two tenants' state."""
        if spec.name in self.tenants:
            raise ValueError(f"tenant {spec.name!r} already admitted")
        t = TenantState(spec, order=len(self.tenants), ticket=ticket)
        if spec.submitted_at:
            # graftlint: allow-wall-clock -- queue latency is
            # observability (submit → admission seconds across
            # processes); every scheduling decision reads only admission
            # order, trial counts and weights
            t.queue_latency_s = max(0.0, time.time() - spec.submitted_at)
        self.tenants[spec.name] = t
        debug.dprintf("Fleet", "admitted %s (priority=%d weight=%g%s)",
                      spec.name, spec.priority, spec.weight,
                      f" ticket={ticket}" if ticket else "")
        return t

    def tenant_outdir(self, name: str) -> str | None:
        if not self.outdir:
            return None
        return os.path.join(self.outdir, "tenants", sanitize(name))

    def _start(self, t: TenantState) -> None:
        """Elaborate one queued tenant: plan → orchestrator (resuming
        from its namespaced checkpoint when one exists) → step driver,
        with the fleet's certification posture applied and chaos kills
        rescoped to the tenant."""
        from shrewd_tpu.campaign.orchestrator import Orchestrator

        plan = t.spec.build_plan()
        if self.certify and (_CERTIFY_ORDER[self.certify]
                             > _CERTIFY_ORDER.get(plan.analysis.certify, 0)):
            # admission-time certification: the fleet's posture tightens
            # the tenant's — its executables are jaxpr/HLO-audited at
            # executable-cache admission before any trial runs
            plan.analysis.certify = self.certify
        outdir = self.tenant_outdir(t.spec.name)
        ckpt_dir = (os.path.join(outdir, "campaign_ckpt") if outdir
                    else None)
        resumable = False
        if ckpt_dir is not None:
            try:
                Orchestrator.load_checkpoint_doc(ckpt_dir)
                resumable = True
            except ValueError:
                resumable = False
        if resumable:
            t.orch = Orchestrator.resume(ckpt_dir, mesh=self.mesh,
                                         outdir=outdir)
            # the fleet posture must hold on resume too (resume rebuilds
            # the plan from the checkpoint document)
            if self.certify:
                t.orch.plan.analysis.certify = max(
                    (t.orch.plan.analysis.certify, self.certify),
                    key=lambda m: _CERTIFY_ORDER.get(m, 0))
        else:
            t.orch = Orchestrator(plan, mesh=self.mesh, outdir=outdir)
        self._scope_chaos(t)
        # the depth-budget ceiling is the SUBMITTED plan's depth, read
        # from the spec document: _rebalance mutates pcfg.depth in
        # place and the clamped value rides the tenant checkpoint, so
        # reading it back from a resumed/rebuilt orchestrator would
        # ratchet the tenant's depth down monotonically across resumes
        spec_depth = (t.spec.plan.get("pipeline") or {}).get(
            "depth", t.orch.pcfg.depth)
        t._plan_depth = max(1, int(spec_depth))
        t.driver = t.orch.stepper()
        t.status = "running"
        if t._t_admit is None:
            t._t_admit = time.monotonic()
        self._rebalance()

    def _scope_chaos(self, t: TenantState, engine=None) -> None:
        """Rescope a tenant's chaos engine to the fleet: the engine's
        "worker" is the tenant, and a kill_worker tears down the tenant's
        driver (``TenantKilled``), not the host process."""
        if engine is not None:
            t.orch.attach_chaos(engine)
        eng = t.orch.chaos
        if eng is None:
            return
        if not eng.worker:
            eng.worker = t.spec.name
        name = t.spec.name

        def _kill(rc: int):
            raise TenantKilled(name, rc)

        eng.kill_action = _kill

    def _rebalance(self) -> None:
        """Re-divide the global dispatch-depth budget over running
        tenants (floor 1, ceiling = each tenant's own plan depth) —
        engines read their depth live, so in-flight windows shrink/grow
        at the next fill."""
        running = [t for t in self.tenants.values()
                   if t.status == "running"]
        if not running:
            return
        share = max(1, self.depth_budget // len(running))
        for t in running:
            depth = max(1, min(t._plan_depth, share))
            t.orch.pcfg.depth = depth
            for eng in t.orch._engines.values():
                eng.depth = depth

    # --- the scheduling loop ---------------------------------------------

    def request_drain(self) -> None:
        """Graceful fleet preemption (idempotent): every running tenant
        finishes its in-flight batch, checkpoints into its namespace,
        and the fleet state is persisted resumable (rc 4)."""
        self._drain = True

    def install_signal_handlers(self):
        """SIGTERM/SIGINT → graceful fleet drain; a second signal raises
        KeyboardInterrupt (the operator escape hatch) — the
        ``Orchestrator.install_signal_handlers`` discipline extended to
        the whole fleet.  Returns a restore callable; no-op off the main
        thread."""
        import signal

        def _handler(signum, frame):
            if self._drain:
                raise KeyboardInterrupt
            self._drain = True
            debug.dprintf("Fleet", "signal %s: draining fleet to "
                          "checkpoints", signum)

        try:
            prev = {s: signal.signal(s, _handler)
                    for s in (signal.SIGTERM, signal.SIGINT)}
        except ValueError:            # not the main thread
            return lambda: None
        return lambda: [signal.signal(s, h) for s, h in prev.items()]

    def _poll_queue(self) -> None:
        if self.queue is None:
            return
        for ticket, spec in self.queue.claim():
            try:
                self.admit(spec, ticket=ticket)
            except ValueError as e:
                # duplicate name etc: publish the refusal as the ticket's
                # result instead of wedging the spool
                debug.dprintf("Fleet", "refused %s: %s", ticket, e)
                self.queue.mark_done(ticket, {"tenant": spec.name,
                                              "status": "refused",
                                              "error": str(e)})

    def _candidates(self) -> list[TenantState]:
        out = []
        for t in self.tenants.values():
            if t.status == "queued":
                try:
                    self._start(t)
                except Exception as e:  # noqa: BLE001 — tenant isolation:
                    # a plan that fails to elaborate (malformed dict,
                    # missing trace file, bad config) is THAT tenant's
                    # failure — park it as failed with the evidence and
                    # keep serving everyone else; a resident scheduler
                    # must never die on one bad submission
                    self._fail(t, e)
            if t.status == "running":
                out.append(t)
        return out

    def _fail(self, t: TenantState, err: Exception) -> None:
        t.status = "failed"
        t.results = {"error": f"{type(err).__name__}: {err}"}
        debug.dprintf("Fleet", "%s: failed to elaborate (%s)",
                      t.spec.name, err)
        if self.queue is not None and t.ticket:
            self.queue.mark_done(t.ticket, {
                "tenant": t.spec.name, "status": "failed",
                "error": str(err)})
        self._rebalance()

    def _pick(self, cands: list[TenantState]) -> TenantState:
        top = max(t.spec.priority for t in cands)
        cls = [t for t in cands if t.spec.priority == top]
        if self.policy == "priority":
            return min(cls, key=lambda t: t.order)
        return min(cls, key=lambda t: (t.vtime, t.order))

    def _handle_kill(self, t: TenantState, e: TenantKilled) -> None:
        """The fleet-scoped worker death: only THIS tenant's
        orchestrator died.  Rebuild it — from its namespaced checkpoint
        when one exists, else from scratch — carrying the SAME chaos
        engine (its schedule state, including the consumed kill, must
        survive the rebuild or the kill would re-fire forever), and
        keep running.  Frozen keys make the recovered tallies
        bit-identical either way."""
        t.kills += 1
        debug.dprintf("Fleet", "%s: %s — rebuilding tenant", t.spec.name, e)
        engine = t.orch.chaos
        t.status = "queued"
        t.orch = t.driver = None
        self._start(t)
        self._scope_chaos(t, engine=engine)

    def _tick_tenant(self, t: TenantState) -> None:
        try:
            t.driver.tick()
        except TenantKilled as e:
            self._handle_kill(t, e)
            return
        except Exception as e:  # noqa: BLE001 — tenant isolation: an
            # exception escaping the event stream is unrecoverable FOR
            # THIS TENANT (lazy elaboration of a bad plan at first tick,
            # a missing trace file, a config the models reject — the
            # ladder/integrity layers already absorbed everything
            # transient inside the generator).  Park the tenant as
            # failed with the evidence; the fleet keeps serving.
            self._fail(t, e)
            return
        t.ticks += 1
        trials = sum(st.trials for st in t.orch.state.values())
        t.trials = trials
        t.batches = trials // max(t.orch.batch_size, 1)
        if t.driver.done:
            self._finalize(t)
            return
        if (t.spec.quota_batches
                and t.batches >= t.spec.quota_batches):
            # quota exhausted: drain THIS tenant to a resumable
            # checkpoint (status "quota") — the next tick finishes its
            # in-flight batch and preempts it
            debug.dprintf("Fleet", "%s: quota %d batches reached — "
                          "draining", t.spec.name, t.spec.quota_batches)
            t.driver.request_drain()

    def _finalize(self, t: TenantState) -> None:
        t.rc = t.driver.rc
        from shrewd_tpu.campaign.orchestrator import Orchestrator

        if t.rc == Orchestrator.RC_PREEMPTED:
            t.status = ("quota" if t.spec.quota_batches
                        and t.batches >= t.spec.quota_batches
                        else "preempted")
        elif t.rc == Orchestrator.RC_ABORTED:
            t.status = "aborted"
        else:
            t.status = "complete"
            if t.kills and t.orch.chaos is not None:
                # the killed tenant finished with believed tallies: the
                # injected kill was survived (the ledger the chaos stats
                # group reports)
                for _ in range(t.kills):
                    t.orch.chaos.note_survived("kill_worker")
        t.wall_s = (time.monotonic() - t._t_admit) if t._t_admit else 0.0
        t.results = self._summarize(t)
        t.orch.write_outputs()
        if t.orch.outdir and t.status == "complete":
            t.orch.checkpoint()          # the final-state dump _drive writes
        if self.queue is not None and t.ticket:
            self.queue.mark_done(t.ticket, {
                "tenant": t.spec.name, "status": t.status, "rc": t.rc,
                "trials": t.trials, "batches": t.batches,
                "wall_s": round(t.wall_s, 3), "results": t.results})
        debug.dprintf("Fleet", "%s: %s (rc=%s, %d trials, %d ticks)",
                      t.spec.name, t.status, t.rc, t.trials, t.ticks)
        self._rebalance()
        if self.outdir:
            self.checkpoint()

    def _summarize(self, t: TenantState) -> dict:
        """JSON-able per-(simpoint, structure) final state: completed
        tenants summarize their StructureResults; preempted/aborted ones
        summarize their partial cumulative state (what the checkpoint
        holds)."""
        out = {}
        if t.driver.results is not None:
            for (sp, st), r in t.driver.results.items():
                out[f"{sp}/{st}"] = {
                    "tallies": np.asarray(r.tallies).tolist(),
                    "trials": int(r.trials), "avf": float(r.avf),
                    "converged": bool(r.converged)}
        else:
            for (sp, st), s in t.orch.state.items():
                out[f"{sp}/{st}"] = {
                    "tallies": s.tallies.tolist(),
                    "trials": int(s.trials), "avf": None,
                    "converged": bool(s.converged)}
        return out

    def run(self) -> int:
        """Drive the fleet: poll the spool, pick, tick, finalize — until
        every tenant is terminal and (with ``idle_exit``) the spool is
        empty, or a drain is requested.  Returns the fleet rc: 0 all
        served, 3 when any tenant aborted (budget/integrity), 4 when the
        fleet was drained (resumable)."""
        while True:
            if self._drain:
                return self._drain_all()
            self._poll_queue()
            cands = self._candidates()
            if not cands:
                if self.queue is not None and not self.idle_exit:
                    time.sleep(self.poll_interval)
                    continue
                break
            t = self._pick(cands)
            self.schedule_log.append(t.spec.name)
            self.ticks += 1
            self._tick_tenant(t)
            if self.on_tick is not None:
                self.on_tick(self)
        self.write_outputs()
        if self.outdir:
            self.checkpoint()
        if any(t.status == "aborted" for t in self.tenants.values()):
            return 3
        return 0

    def _drain_all(self) -> int:
        """Graceful fleet preemption: every running tenant drains to a
        namespaced resumable checkpoint; queued tenants stay queued in
        the fleet checkpoint.  rc 4, resumable via ``resume()``."""
        self.preempted = True
        for t in self.tenants.values():
            if t.status == "running":
                t.driver.request_drain()
                while not t.driver.done:
                    self.ticks += 1
                    t.ticks += 1
                    try:
                        t.driver.tick()
                    except TenantKilled as e:
                        # belt-and-braces: the drain flag preempts at
                        # the next batch boundary before any compute,
                        # so a kill should not be reachable here — but
                        # if one ever is, it must not break the drain
                        # contract (every tenant checkpoints, fleet
                        # exits resumable): rebuild and re-drain
                        self._handle_kill(t, e)
                        t.driver.request_drain()
                    except Exception as e:  # noqa: BLE001 — isolation,
                        # as in _tick_tenant: a dead tenant must not
                        # stop the rest of the fleet from draining
                        self._fail(t, e)
                        break
                if t.status == "running":
                    self._finalize(t)
        self.write_outputs()
        if self.outdir:
            self.checkpoint()
        debug.dprintf("Fleet", "fleet drained: %s", self._by_status())
        return 4

    # --- fleet state persistence / outputs --------------------------------

    def results(self) -> dict:
        return {n: t.results for n, t in self.tenants.items()}

    def tenant_tallies(self, name: str) -> dict:
        """{(simpoint, structure): int64 tallies} for one tenant — the
        bit-identity comparison surface the fleet tests pin against each
        tenant's solo serial run."""
        t = self.tenants[name]
        out = {}
        for key, row in (t.results or {}).items():
            sp, st = key.split("/", 1)
            out[(sp, st)] = np.asarray(row["tallies"], dtype=np.int64)
        return out

    def write_outputs(self) -> None:
        if not self.outdir:
            return
        os.makedirs(self.outdir, exist_ok=True)
        with open(os.path.join(self.outdir, "fleet_stats.txt"), "w") as f:
            statsmod.dump_text(self.stats, f)
        with open(os.path.join(self.outdir, "fleet_stats.json"), "w") as f:
            statsmod.dump_json(self.stats, f)

    def checkpoint(self) -> str:
        """Persist the fleet's own resumable state (atomic, checksummed —
        the campaign-checkpoint discipline): tenant specs, statuses,
        fair-share ledgers and result summaries.  Per-tenant campaign
        state lives in each tenant's namespaced checkpoint; this document
        only has to say who exists and where they stand."""
        ckpt_dir = os.path.join(self.outdir, "fleet_ckpt")
        os.makedirs(ckpt_dir, exist_ok=True)
        doc = {"version": FLEET_CKPT_VERSION, "policy": self.policy,
               "depth_budget": self.depth_budget, "ticks": self.ticks,
               "tenants": [t.to_dict() for t in self.tenants.values()]}
        doc["checksum"] = resil.doc_checksum(doc)
        resil.write_json_atomic(os.path.join(ckpt_dir, "fleet.json"), doc)
        return ckpt_dir

    @classmethod
    def resume(cls, outdir: str, mesh=None,
               queue: SubmissionQueue | None = None,
               **kw) -> "CampaignScheduler":
        """Rebuild a drained fleet from ``outdir/fleet_ckpt/fleet.json``:
        terminal tenants keep their recorded results; resumable ones
        (queued/running/preempted) are re-admitted and continue from
        their namespaced checkpoints on the next ``run()``."""
        doc = resil.load_json_verified(
            os.path.join(outdir, "fleet_ckpt", "fleet.json"))
        if doc.get("version") != FLEET_CKPT_VERSION:
            raise ValueError(
                f"fleet checkpoint version {doc.get('version')} != "
                f"{FLEET_CKPT_VERSION}")
        sched = cls(outdir=outdir, mesh=mesh, queue=queue,
                    depth_budget=kw.pop("depth_budget",
                                        doc["depth_budget"]),
                    policy=kw.pop("policy", doc["policy"]), **kw)
        for td in sorted(doc["tenants"], key=lambda d: d["order"]):
            spec = TenantSpec.from_dict(td["spec"])
            t = sched.admit(spec, ticket=td.get("ticket", ""))
            t.trials = int(td.get("trials", 0))
            t.batches = int(td.get("batches", 0))
            t.kills = int(td.get("kills", 0))
            t.queue_latency_s = float(td.get("queue_latency_s", 0.0))
            status = td.get("status", "queued")
            if status in _RESUMABLE:
                t.status = "queued"      # _start resumes from its ckpt
            else:
                t.status = status
                t.rc = td.get("rc")
                t.results = td.get("results")
                t.wall_s = float(td.get("wall_s", 0.0))
        return sched
