"""The closed Pareto loop: live cell tallies → protection front → prune.

``search/protect.py`` evaluates protection *analytically* over measured
raw outcome distributions; before this module it ran post-hoc, over one
finished campaign.  Here the same algebra folds the fleet's **live**
per-cell tallies after scheduler ticks:

- every cell gets a *point*: its scheme's (area, SDC-rate) with
  conservative bounds — ``sdc_lo``/``sdc_hi`` bracket the rate the cell
  could still converge to, from a Wilson interval over the SDC count
  alone (the reported ``halfwidth`` stays the stopping rule's combined
  vulnerable-proportion estimator — see ``cell_point``);
- a still-running cell is **Pareto-dominated** when some *converged*
  scheme-mate (same measurement coordinates, ``Cell.prune_group``) is
  at least as good on both axes even against the runner's most
  optimistic bound — ``dom.area <= run.area`` and ``dom.sdc_hi <=
  run.sdc_lo`` with at least one strict — at which point its remaining
  service is withdrawn through the scheduler's journaled
  ``revoke_quota`` seam (status ``pruned``; the decision replays
  exactly after a hard kill because the journal record precedes any
  state change);
- converged cells re-fit ``StructureProfile``s per ``system_group``
  (workload × window × thermal) and ``DesignSpace.search`` emits the
  area-vs-system-SDC front over the full scheme assignment space —
  the reference's protection/area trade-off as a first-class campaign
  artifact (``PARETO_<tag>.json``, atomic).

Thermal envelopes enter as Arrhenius rate acceleration
(``models/noc.temperature_factor``) on ``fit_per_bit`` — hotter
envelopes weight the same raw distribution with a higher arrival rate
(and NoC cells additionally measured under the envelope's fault mix,
matrix.py).

Import discipline: jax-free at module import (numpy algebra here; jax
enters via search/protect inside ``design_search``).
"""

from __future__ import annotations

import os

import numpy as np

from shrewd_tpu.resilience import write_json_atomic
from shrewd_tpu.scenario.matrix import Cell, ScenarioMatrix
from shrewd_tpu.utils import debug

debug.register_flag("Scenario", "scenario matrix / Pareto closed loop")

PARETO_SCHEMA = 1


def artifact_path(outdir: str, tag: str) -> str:
    return os.path.join(outdir, f"PARETO_{tag}.json")


def thermal_factor(temp_c: float) -> float:
    """Arrhenius acceleration of the fault-arrival rate at one envelope
    (the models/noc curve — one definition, reused)."""
    from shrewd_tpu.models.noc import temperature_factor

    return float(temperature_factor(temp_c))


def cell_point(cell: Cell, tallies, trials: int, halfwidth: float,
               converged: bool, status: str,
               confidence: float = 0.95) -> dict:
    """One cell's live design point: the protect.py scheme algebra over
    its (possibly unconverged) raw tally, with conservative SDC-rate
    bounds from an SDC-specific Wilson interval.

    ``halfwidth`` is reported as the cell's convergence distance (the
    stopping rule's estimator over the COMBINED vulnerable proportion)
    but is NOT what brackets ``sdc_lo``/``sdc_hi``: at a large DUE
    share the combined interval is narrower than the SDC proportion's
    own, so bounds borrowed from it would not contain the rate the cell
    could still converge to — breaking the domination guarantee.  The
    prune bounds therefore come from ``stopping.wilson`` over the SDC
    count alone (always a valid CI on ``p_sdc``, stratified or not).

    Mirrors ``DesignSpace`` exactly: arrival rate = fit_per_bit × bits ×
    thermal factor × area factor (protection bits are targets too);
    residual SDC uses the outcome-conditioned detection probability when
    the scheme carries one."""
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel import stopping

    t = np.asarray(tallies, dtype=np.float64)
    n = float(max(trials, 1))
    p_sdc = float(t[C.OUTCOME_SDC]) / n
    p_due = float(t[C.OUTCOME_DUE]) / n
    hw = float(halfwidth)
    sc = cell.scheme
    d_sdc = float(sc.get("detect_sdc") if sc.get("detect_sdc") is not None
                  else sc.get("detect", 0.0))
    d_due = float(sc.get("detect_due") if sc.get("detect_due") is not None
                  else sc.get("detect", 0.0))
    cor = float(sc.get("correct", 0.0))
    areaf = float(sc.get("area", 1.0))
    tf = thermal_factor(float(cell.thermal["temperature_c"]))
    rate = cell.fit_per_bit * cell.bits * tf * areaf
    resid_sdc = max(0.0, 1.0 - d_sdc - cor)
    resid_due = max(0.0, 1.0 - d_due - cor)
    sdc = rate * resid_sdc * p_sdc
    iv = stopping.wilson(float(t[C.OUTCOME_SDC]), float(trials),
                         confidence)   # trials<=0 → [0, 1]
    return {
        "cell": cell.name, "status": status, "trials": int(trials),
        "converged": bool(converged), "halfwidth": hw,
        "tallies": np.asarray(tallies).astype(np.int64).tolist(),
        "p_sdc": p_sdc, "area": cell.bits * areaf,
        "sdc": sdc, "due": rate * resid_due * p_due,
        "sdc_lo": rate * resid_sdc * iv.lo,
        "sdc_hi": rate * resid_sdc * iv.hi,
        "thermal_factor": tf,
        "prune_group": list(cell.prune_group),
        "system_group": list(cell.system_group),
    }


def dominates(dom: dict, run: dict) -> bool:
    """Conservative Pareto domination: the converged point beats the
    running cell's *most optimistic* reachable position on both axes,
    strictly on at least one — the running cell can no longer earn a
    place on the front, whatever its remaining trials say."""
    if not (dom["area"] <= run["area"]
            and dom["sdc_hi"] <= run["sdc_lo"]):
        return False
    return dom["area"] < run["area"] or dom["sdc_hi"] < run["sdc_lo"]


#: tenant statuses a prune decision may still target (anything terminal
#: — complete/aborted/quota/quarantined/pruned — is past revoking)
_PRUNABLE = ("queued", "running")


def prune_decisions(cells: list[Cell], points: dict,
                    revoked: dict | None = None) -> list[dict]:
    """Deterministic prune set at the current tallies: for every
    still-prunable cell, the first converged prune-group mate (cell
    order — which is expansion order, stable) that dominates it.
    ``revoked`` maps already-revoked cell names (skipped: the journal,
    not this function, owns decisions already made)."""
    revoked = revoked or {}
    by_group: dict[tuple, list[Cell]] = {}
    for c in cells:
        by_group.setdefault(c.prune_group, []).append(c)
    out = []
    for c in cells:
        pt = points.get(c.name)
        if pt is None or c.name in revoked:
            continue
        if pt["status"] not in _PRUNABLE or pt["converged"]:
            continue
        for mate in by_group[c.prune_group]:
            if mate.name == c.name:
                continue
            mpt = points.get(mate.name)
            if mpt is None or not mpt["converged"]:
                continue
            if dominates(mpt, pt):
                out.append({"cell": c.name, "dominated_by": mate.name})
                break
    return out


def design_search(matrix: ScenarioMatrix, cells: list[Cell],
                  points: dict) -> dict:
    """Per system group (workload × window × thermal): re-fit
    ``StructureProfile``s from the converged cells and run the full
    ``DesignSpace`` assignment search — the area-vs-system-SDC front.

    Profile fit picks, per target, the converged cell with the most
    trials (scheme-mates share frozen keys, so any of them measures the
    same distribution; ties break on cell name).  Groups with no
    converged cell yet are skipped — the front grows as the matrix
    converges."""
    from shrewd_tpu.search.protect import (DesignSpace, Scheme,
                                           StructureProfile)

    schemes = [Scheme(name=s["name"],
                      detect=float(s.get("detect", 0.0)),
                      correct=float(s.get("correct", 0.0)),
                      area=float(s.get("area", 1.0)),
                      detect_sdc=s.get("detect_sdc"),
                      detect_due=s.get("detect_due"))
               for s in matrix.schemes]
    groups: dict[tuple, dict[str, Cell]] = {}
    for c in cells:
        pt = points.get(c.name)
        if pt is None or not pt["converged"]:
            continue
        best = groups.setdefault(c.system_group, {})
        cur = best.get(c.target)
        if cur is None or (points[cur.name]["trials"], cur.name) < (
                pt["trials"], c.name):
            best[c.target] = c
    out = {}
    for group, by_target in sorted(groups.items()):
        profiles = []
        provenance = {}
        for target in sorted(by_target):
            c = by_target[target]
            pt = points[c.name]
            tf = pt["thermal_factor"]
            profiles.append(StructureProfile.from_tally(
                target, c.bits, pt["tallies"],
                fit_per_bit=c.fit_per_bit * tf,
                halfwidth=pt["halfwidth"]))
            provenance[target] = c.name
        ds = DesignSpace(profiles, schemes=schemes)
        target_rate = (matrix.sdc_target if matrix.sdc_target > 0
                       else float("inf"))
        res = ds.search(target_rate)
        out["/".join(group)] = {
            "cells": provenance,
            "feasible": bool(res.feasible),
            "assignment": res.assignment,
            "area": res.area, "sdc_rate": res.sdc_rate,
            "due_rate": res.due_rate,
            "baseline_area": res.baseline_area,
            "baseline_sdc": res.baseline_sdc,
            "n_configs": res.n_configs,
            "pareto": [{"area": a, "sdc_rate": s, "assignment": asg}
                       for a, s, asg in res.pareto],
        }
    return out


def artifact(matrix: ScenarioMatrix, cells: list[Cell], points: dict,
             decisions: list[dict], fleet: dict | None = None) -> dict:
    """The PARETO document: front + per-cell provenance + the prune
    decisions that shaped the run (each one also a journaled ``revoke``
    record in the fleet WAL — the artifact cites, the journal proves)."""
    return {
        "schema": PARETO_SCHEMA,
        "tag": matrix.tag,
        "sdc_target": matrix.sdc_target,
        "axes": {
            "workloads": [w["name"] for w in matrix.workloads],
            "windows": sorted({c.window for c in cells}),
            "targets": [t["name"] for t in matrix.targets],
            "schemes": [s["name"] for s in matrix.schemes],
            "thermal": [dict(t) for t in matrix.thermal],
        },
        "cells": {name: points[name] for name in sorted(points)},
        "decisions": sorted(decisions, key=lambda d: d["cell"]),
        "search": design_search(matrix, cells, points),
        "fleet": dict(fleet or {}),
    }


def write_artifact(outdir: str, doc: dict) -> str:
    path = artifact_path(outdir, doc["tag"])
    write_json_atomic(path, doc)
    debug.dprintf("Scenario", "PARETO artifact -> %s (%d cells, %d "
                  "decisions)", path, len(doc["cells"]),
                  len(doc["decisions"]))
    return path
