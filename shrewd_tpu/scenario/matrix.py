"""Scenario matrices: one declarative plan → a cross-product tenant set.

The reference's headline artifact is a *campaign driver* that sweeps
workloads through candidate microarchitectures one gem5 process per
point (``x86_spec/x86-spec-cpu2017.py``).  A ``ScenarioMatrix`` is the
fleet-native form of that sweep: declarative axes —

- **workloads**   named windows (each a list of SimPoint specs, the
  plan's ``simpoints`` documents verbatim);
- **targets**     fault structures spanning every validated model family
  ({O3 regfile/ROB/IQ/LSQ, latch, cache:*, mesi:*, noc:router});
- **schemes**     protection options (the ``search/protect.py`` Scheme
  fields as a dict: detect/correct/area, optional outcome-conditioned
  detection);
- **thermal**     die-temperature envelopes feeding Arrhenius-scaled
  fault *rates* (``models/noc.temperature_factor``); only NoC cells bake
  the temperature into the plan (the flit fault-type mix shifts with
  it) — for every other family the envelope scales the analysis rate,
  never the campaign, so envelope-mates share executables;

— that ``expand()`` deterministically flattens into cells, each cell one
``TenantSpec`` for the resident fleet (``service/scheduler.py``).

Determinism contracts (pinned in ``tests/test_scenario.py``):

- **Stable cell names**: ``<tag>.<workload>.<window>.<target>.<scheme>.
  <thermal>`` (sanitized) — the cell name is the tenant identity, the
  checkpoint namespace, and the Pareto provenance key, so expansion
  order and naming may never drift between processes.
- **Shared measurement seeds**: a cell's campaign seed derives from its
  *measurement* coordinates (workload, window, target) only — scheme-
  and thermal-mates replay the same frozen PRNG keys over the same
  window content, so their raw tallies are directly comparable, their
  executables hit the PR-5/7 content-keyed exec cache (zero new
  compiles for cells sharing a window), and the scheme/thermal axes
  cost only the analytic fold, exactly the economy ``search/protect``
  is built on.
- **Coherence collapse**: plan-level targets (``mesi:*``/``noc:*``)
  measure plan-level synthetic traffic independent of any window, so
  the workload×window axes collapse to the reserved ``coherence`` cell
  coordinate — one cell per (target, scheme, thermal), never one per
  window (which would multiply identical campaigns).

Per-axis scheduling inheritance: any axis entry may carry ``priority``
(summed across axes), ``weight`` (multiplied), and ``quota_batches``
(tightest non-zero wins) — e.g. de-weight an expensive scheme so its
cells trail the cheap ones and the Pareto prune can kill them early.

Import discipline: jax-free (a matrix is pure host-side data; jax
enters when the scheduler elaborates a cell's plan).
"""

from __future__ import annotations

import zlib
from typing import NamedTuple

from shrewd_tpu.service.queue import TenantSpec, sanitize

MATRIX_SCHEMA = 1

#: the collapsed workload/window coordinate of plan-level (mesi:/noc:)
#: cells — matches campaign/plan.py COHERENCE_SP_NAME by design (the
#: orchestrator reports those tiers under the same pseudo-simpoint)
COHERENCE = "coherence"

_PLAN_LEVEL = ("mesi", "noc")

#: structure names a matrix may target (kept in sync with
#: models/o3.STRUCTURES + plan.TIER_STRUCTURES; re-validated against the
#: real tables at expand time via campaign.plan on first elaboration)
KNOWN_TARGETS = (
    "regfile", "fu", "rob", "iq", "lsq", "latch",
    "cache:data", "cache:tag", "cache:state",
    "mesi:state", "mesi:tag", "noc:router",
)


def _is_plan_level(target: str) -> bool:
    return target.split(":", 1)[0] in _PLAN_LEVEL


def cell_seed(base_seed: int, workload: str, window: str,
              target: str) -> int:
    """Deterministic campaign seed from the MEASUREMENT coordinates only
    (scheme/thermal excluded — see module docstring): crc32 keeps it
    stable across processes, platforms and matrix edits."""
    h = zlib.crc32(f"{base_seed}|{workload}|{window}|{target}".encode())
    return int(h & 0x7FFFFFFF)


def default_bits(target: str, plan: dict) -> int:
    """Storage-size proxy (bits) for one fault target, read from the
    cell's own plan document — the ``StructureProfile.bits`` the Pareto
    fold uses for fault-rate and area weighting.  Deliberately simple,
    deterministic formulas (the reference's per-structure entry counts
    scaled to bits); override per target-axis entry with ``bits`` when a
    design has real numbers."""
    machine = plan.get("machine") or {}
    rob = int(machine.get("rob_size", 192))
    iw = int(machine.get("issue_width", 8))
    sp0 = (plan.get("simpoints") or [{}])[0]
    nphys = int(((sp0.get("workload")) or {}).get("nphys", 64))
    cache = plan.get("cache") or {}
    c_sets = int(cache.get("n_sets", 64))
    c_ways = int(cache.get("n_ways", 4))
    c_words = int(cache.get("words_per_line", 8))
    mesi = plan.get("mesi") or {}
    m_cores = int(mesi.get("n_cores", 2))
    m_sets = int(mesi.get("n_sets", 4))
    m_ways = int(mesi.get("n_ways", 2))
    m_tag = int(mesi.get("tag_bits", 16))
    noc = plan.get("noc") or {}
    n_routers = int(noc.get("mesh_x", 2)) * int(noc.get("mesh_y", 2))
    vcs = int(noc.get("vcs_per_vnet", 4)) * int(noc.get("n_vnets", 3))
    flit = int(noc.get("flit_bits", 128))
    bufs = (int(noc.get("vcs_per_vnet", 4))
            * int(noc.get("buffers_per_data_vc", 4))
            + (vcs - int(noc.get("vcs_per_vnet", 4)))
            * int(noc.get("buffers_per_ctrl_vc", 1)))
    table = {
        "regfile": nphys * 32,
        "fu": iw * 128,                    # FU logic-area proxy
        "rob": rob * 8,                    # dst-index metadata per entry
        "iq": (rob // 2) * 16,             # 2 src indices per IQ entry
        "lsq": (rob // 4) * 48,            # addr+data per LSQ entry
        "latch": iw * 96,                  # inter-stage pipeline latches
        "cache:data": c_sets * c_ways * c_words * 32,
        "cache:tag": c_sets * c_ways * 20,
        "cache:state": c_sets * c_ways * 4,
        # L1 state/tag arrays per core + the directory's copy (the
        # sharers vector is the "+2"): mirrors models/mesi geometry
        "mesi:state": m_cores * m_sets * m_ways * 4
                      + m_sets * m_ways * (m_cores + 2),
        "mesi:tag": (m_cores + 1) * m_sets * m_ways * m_tag,
        # 5-port mesh router, one data-class vnet — a simplified
        # models/noc._geom_bits (buffer SRAM dominates, as there)
        "noc:router": n_routers * 5 * flit * bufs,
    }
    return int(table[target])


def _norm_entry(e, axis: str) -> dict:
    """Axis entries may be bare names (targets) or dicts; normalize to a
    dict with a ``name``."""
    if isinstance(e, str):
        e = {"name": e}
    if not isinstance(e, dict) or not e.get("name"):
        raise ValueError(f"{axis} entry needs a name: {e!r}")
    return dict(e)


def _validate_scheme(s: dict) -> dict:
    det = float(s.get("detect", 0.0))
    cor = float(s.get("correct", 0.0))
    area = float(s.get("area", 1.0))
    for d in (det, s.get("detect_sdc"), s.get("detect_due")):
        if d is None:
            continue
        if not (0.0 <= float(d) and 0.0 <= cor
                and float(d) + cor <= 1.0):
            raise ValueError(
                f"scheme {s['name']!r}: need detect+correct in [0,1]")
    if area < 1.0:
        raise ValueError(f"scheme {s['name']!r}: area multiplier < 1")
    return s


class Cell(NamedTuple):
    """One expanded matrix cell = one fleet tenant."""

    name: str            # stable tenant identity (see module docstring)
    workload: str
    window: str          # simpoint name (COHERENCE for mesi:/noc: cells)
    target: str          # fault structure
    scheme: dict         # protection-scheme document
    thermal: dict        # {"name", "temperature_c", ...}
    plan: dict           # the cell's full CampaignPlan document
    priority: int
    weight: float
    quota_batches: int
    bits: int            # StructureProfile storage proxy
    fit_per_bit: float

    @property
    def prune_group(self) -> tuple:
        """Cells comparable under Pareto domination: scheme-mates over
        one measurement (same raw distribution, same frozen keys)."""
        return (self.workload, self.window, self.target,
                self.thermal["name"])

    @property
    def system_group(self) -> tuple:
        """Cells composing one system design point: every target of one
        (workload, window, thermal) — the DesignSpace fit group."""
        return (self.workload, self.window, self.thermal["name"])

    def spec(self) -> TenantSpec:
        return TenantSpec(name=self.name, plan=self.plan,
                          priority=self.priority, weight=self.weight,
                          quota_batches=self.quota_batches)

    def build_plan(self):
        from shrewd_tpu.campaign.plan import CampaignPlan

        return CampaignPlan.from_dict(self.plan)

    def to_dict(self) -> dict:
        return {"name": self.name, "workload": self.workload,
                "window": self.window, "target": self.target,
                "scheme": dict(self.scheme),
                "thermal": dict(self.thermal),
                "priority": self.priority, "weight": self.weight,
                "quota_batches": self.quota_batches, "bits": self.bits,
                "fit_per_bit": self.fit_per_bit}


class ScenarioMatrix:
    """The declarative cross-product plan (see module docstring)."""

    def __init__(self, tag: str, workloads: list, targets: list,
                 schemes: list, thermal: list | None = None,
                 base: dict | None = None, seed: int = 0,
                 fit_per_bit: float = 1.0e-3, sdc_target: float = 0.0,
                 tenant: dict | None = None):
        if not tag:
            raise ValueError("matrix needs a non-empty tag")
        self.tag = str(tag)
        self.seed = int(seed)
        self.fit_per_bit = float(fit_per_bit)
        self.sdc_target = float(sdc_target)
        self.base = dict(base or {})
        self.tenant = {"priority": 0, "weight": 1.0, "quota_batches": 0}
        self.tenant.update(tenant or {})
        self.workloads = [self._norm_workload(w) for w in workloads]
        self.targets = [_norm_entry(t, "target") for t in targets]
        self.schemes = [_validate_scheme(_norm_entry(s, "scheme"))
                        for s in schemes]
        self.thermal = [_norm_entry(t, "thermal") for t in (
            thermal or [{"name": "tnom"}])]
        for th in self.thermal:
            th.setdefault("temperature_c", 71.0)   # NoC baseline temp
        for t in self.targets:
            if t["name"] not in KNOWN_TARGETS:
                raise ValueError(f"unknown target {t['name']!r} "
                                 f"(known: {sorted(KNOWN_TARGETS)})")
        for axis, entries in (("workload", self.workloads),
                              ("target", self.targets),
                              ("scheme", self.schemes),
                              ("thermal", self.thermal)):
            if not entries:
                raise ValueError(f"matrix {self.tag!r}: empty {axis} axis")
            names = [e["name"] for e in entries]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate {axis} names: {names}")
        if (any(not _is_plan_level(t["name"]) for t in self.targets)
                and not any(w["simpoints"] for w in self.workloads)):
            # raised even when plan-level targets would still expand:
            # silently dropping the per-window coverage (a misspelled
            # or missing "simpoints" key) must never produce a matrix
            # that runs and emits an artifact anyway
            raise ValueError("per-window targets need at least one "
                             "workload simpoint")

    @staticmethod
    def _norm_workload(w) -> dict:
        w = _norm_entry(w, "workload")
        w["simpoints"] = [dict(s) for s in (w.get("simpoints") or [])]
        for s in w["simpoints"]:
            if not s.get("name"):
                raise ValueError(
                    f"workload {w['name']!r}: simpoint needs a name")
        return w

    # --- expansion --------------------------------------------------------

    def _inherit(self, *entries) -> tuple[int, float, int]:
        pri = int(self.tenant["priority"])
        weight = float(self.tenant["weight"])
        quotas = [int(self.tenant["quota_batches"])]
        for e in entries:
            pri += int(e.get("priority", 0))
            weight *= float(e.get("weight", 1.0))
            quotas.append(int(e.get("quota_batches", 0)))
        live = [q for q in quotas if q > 0]
        return pri, weight, (min(live) if live else 0)

    def _cell_plan(self, target: str, simpoint: dict | None,
                   thermal: dict, seed: int) -> dict:
        import copy

        plan = copy.deepcopy(self.base)
        plan["structures"] = [target]
        plan["simpoints"] = [dict(simpoint)] if simpoint else []
        plan["seed"] = seed
        if target.startswith("noc:"):
            # the flit fault-type mix is temperature-dependent, so NoC
            # cells bake the envelope into the plan; every other family
            # keeps one plan across envelopes (executables shared) and
            # the envelope scales only the analytic rate
            noc = dict(plan.get("noc") or {})
            noc["temperature_c"] = float(thermal["temperature_c"])
            plan["noc"] = noc
        return plan

    def _name(self, *parts: str) -> str:
        return ".".join(sanitize(p) for p in (self.tag,) + parts)

    def expand(self) -> list[Cell]:
        """The full deterministic cross-product, in axis order
        (workloads → windows → targets → schemes → thermal), coherence
        cells after the windowed ones — identical output for identical
        documents, every time (pinned)."""
        cells: list[Cell] = []
        per_win = [t for t in self.targets
                   if not _is_plan_level(t["name"])]
        coh = [t for t in self.targets if _is_plan_level(t["name"])]

        def emit(wl_name: str, win_name: str, tg: dict, sc: dict,
                 th: dict, simpoint: dict | None, *inherit_extra):
            target = tg["name"]
            seed = cell_seed(self.seed, wl_name, win_name, target)
            plan = self._cell_plan(target, simpoint, th, seed)
            pri, weight, quota = self._inherit(tg, sc, th,
                                               *inherit_extra)
            cells.append(Cell(
                name=self._name(wl_name, win_name, target, sc["name"],
                                th["name"]),
                workload=wl_name, window=win_name, target=target,
                scheme=dict(sc), thermal=dict(th), plan=plan,
                priority=pri, weight=weight, quota_batches=quota,
                bits=int(tg.get("bits") or default_bits(target, plan)),
                fit_per_bit=float(tg.get("fit_per_bit",
                                         self.fit_per_bit))))

        for wl in self.workloads:
            for sp in wl["simpoints"]:
                for tg in per_win:
                    for sc in self.schemes:
                        for th in self.thermal:
                            emit(wl["name"], sp["name"], tg, sc, th,
                                 sp, wl)
        for tg in coh:
            for sc in self.schemes:
                for th in self.thermal:
                    emit(COHERENCE, COHERENCE, tg, sc, th, None)
        names = [c.name for c in cells]
        if len(set(names)) != len(names):
            dup = sorted(n for n in set(names) if names.count(n) > 1)
            raise ValueError(f"cell-name collision after sanitize: {dup}")
        return cells

    def tenant_specs(self) -> list[TenantSpec]:
        return [c.spec() for c in self.expand()]

    # --- round trip -------------------------------------------------------

    def to_dict(self) -> dict:
        return {"schema": MATRIX_SCHEMA, "tag": self.tag,
                "seed": self.seed, "fit_per_bit": self.fit_per_bit,
                "sdc_target": self.sdc_target, "base": dict(self.base),
                "tenant": dict(self.tenant),
                "workloads": [dict(w) for w in self.workloads],
                "targets": [dict(t) for t in self.targets],
                "schemes": [dict(s) for s in self.schemes],
                "thermal": [dict(t) for t in self.thermal]}

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioMatrix":
        d = dict(d)
        schema = d.pop("schema", MATRIX_SCHEMA)
        if schema != MATRIX_SCHEMA:
            raise ValueError(f"matrix schema {schema} != {MATRIX_SCHEMA}")
        return cls(tag=d["tag"], workloads=d.get("workloads", []),
                   targets=d["targets"], schemes=d["schemes"],
                   thermal=d.get("thermal"), base=d.get("base"),
                   seed=d.get("seed", 0),
                   fit_per_bit=d.get("fit_per_bit", 1.0e-3),
                   sdc_target=d.get("sdc_target", 0.0),
                   tenant=d.get("tenant"))
