"""Scenario-matrix execution: the matrix as a fleet tenant set.

``ScenarioRunner`` admits a matrix's expanded cells through the resident
``CampaignScheduler`` and closes the loop: every ``pareto_every`` fleet
ticks it folds the live per-cell tallies (the same estimator surfaces
the PR-10 metrics publish uses), revokes the quota of Pareto-dominated
cells through the scheduler's journaled seam, and re-emits the
``PARETO_<tag>.json`` artifact atomically.

Partial-matrix survivability: the matrix document itself is persisted
into the fleet outdir (``matrix.json``) before any cell runs, so a
hard-killed fleet recovers the WHOLE matrix — ``ScenarioRunner.
recover`` replays the fleet WAL (completed cells keep their recorded
results, running cells resume from their namespaced checkpoints,
journaled prune decisions re-apply exactly) and re-admits any cell the
kill landed before, then continues to the same bit-identical end state
an undisturbed run reaches.

Determinism: the fold cadence is counted in fleet ticks (never wall
clock), decisions depend only on converged tallies (bit-identical by
the frozen-key invariant) and static areas, and revocation is
journaled before any state change — so the prune *set* of a recovered
matrix equals the undisturbed run's, pinned in tests.

Import discipline: jax-free at module import (jax enters when the
scheduler elaborates cells).
"""

from __future__ import annotations

import json
import os

from shrewd_tpu.resilience import (doc_checksum, load_json_verified,
                                   write_json_atomic)
from shrewd_tpu.scenario import pareto
from shrewd_tpu.scenario.matrix import COHERENCE, ScenarioMatrix
from shrewd_tpu.service.scheduler import CampaignScheduler
from shrewd_tpu.utils import debug

MATRIX_DOC = "matrix.json"

#: prefix of the revoke reason the Pareto loop writes — decisions are
#: recoverable from tenant state alone (reason = "pareto:<dominator>")
PRUNE_REASON = "pareto:"


class ScenarioRunner:
    """Drive one matrix through one fleet (see module docstring)."""

    def __init__(self, matrix: ScenarioMatrix, outdir: str,
                 prune: bool = True, pareto_every: int = 4,
                 on_tick=None, **sched_kw):
        self.matrix = matrix
        self.cells = matrix.expand()
        self._by_name = {c.name: c for c in self.cells}
        self.outdir = outdir
        self.prune = bool(prune)
        self.pareto_every = max(1, int(pareto_every))
        self._user_on_tick = on_tick
        self._sched_kw = dict(sched_kw)
        self.sched: CampaignScheduler | None = None

    # --- construction -----------------------------------------------------

    def _persist_matrix(self) -> None:
        """The matrix document is a RECOVERY INPUT (a hard-killed fleet
        rebuilds the whole matrix from it), so it carries a content
        checksum like every other crash-surface artifact — recovery
        verifies it rather than trusting whatever bytes survived."""
        os.makedirs(self.outdir, exist_ok=True)
        doc = self.matrix.to_dict()
        doc["checksum"] = doc_checksum(doc)
        write_json_atomic(os.path.join(self.outdir, MATRIX_DOC), doc)

    def _admit_missing(self) -> int:
        """Admit every cell the scheduler does not already know — all of
        them on a fresh serve, only the not-yet-admitted remainder after
        a recovery (cells already in the replayed roster keep their
        recorded state untouched)."""
        n = 0
        for cell in self.cells:
            if cell.name not in self.sched.tenants:
                self.sched.admit(cell.spec())
                n += 1
        return n

    def serve(self) -> int:
        """Fresh matrix: persist the document, admit every cell, run the
        fleet to completion, emit the final artifact."""
        self._persist_matrix()
        self.sched = CampaignScheduler(outdir=self.outdir,
                                       on_tick=self._on_tick,
                                       **self._sched_kw)
        self._admit_missing()
        return self.run()

    @classmethod
    def recover(cls, outdir: str, prune: bool = True,
                pareto_every: int = 4, on_tick=None,
                **sched_kw) -> "ScenarioRunner":
        """Rebuild a matrix fleet after ANY shutdown from its persisted
        matrix document + the fleet WAL (``CampaignScheduler.recover``
        semantics; journaled prune decisions replay exactly).  The
        matrix document is checksum-verified: recovering a whole matrix
        from torn bytes would be worse than refusing."""
        matrix = ScenarioMatrix.from_dict(
            load_json_verified(os.path.join(outdir, MATRIX_DOC)))
        runner = cls(matrix, outdir, prune=prune,
                     pareto_every=pareto_every, on_tick=on_tick,
                     **sched_kw)
        runner.sched = CampaignScheduler.recover(
            outdir, on_tick=runner._on_tick, **runner._sched_kw)
        runner._admit_missing()
        return runner

    def run(self) -> int:
        rc = self.sched.run()
        try:
            self.emit_artifact()
        except Exception as e:  # noqa: BLE001 — the artifact is DERIVED
            # state (journal + per-tenant results are the ground truth,
            # and tools/scenario.py --pareto can re-fold any time): a
            # fold that cannot compute must not discard the fleet rc of
            # a fully served matrix.  The --pareto one-shot surface
            # calls emit_artifact() directly and DOES raise.
            debug.dprintf("Scenario", "final pareto fold failed: %s", e)
            import sys

            print(f"scenario: final pareto fold failed ({e}) — re-fold "
                  "with tools/scenario.py --pareto", file=sys.stderr)
        return rc

    # --- the closed loop --------------------------------------------------

    def _on_tick(self, sched) -> None:
        if self._user_on_tick is not None:
            self._user_on_tick(sched)
        if sched.ticks % self.pareto_every:
            return
        try:
            self._fold(sched)
        except Exception as e:  # noqa: BLE001 — the Pareto loop is a
            # supervisor over the fleet, never a dependency of it: a
            # fold that cannot compute (a cell mid-elaboration, a model
            # import failing) skips this tick and the fleet keeps
            # serving; decisions are monotonic so a later fold makes
            # the same calls
            debug.dprintf("Scenario", "pareto fold skipped: %s", e)

    def _fold(self, sched) -> dict:
        points = self.points(sched)
        decisions = self.decisions(sched)
        if self.prune:
            for d in pareto.prune_decisions(self.cells, points,
                                            revoked=dict(decisions)):
                if sched.revoke_quota(
                        d["cell"], PRUNE_REASON + d["dominated_by"]):
                    decisions[d["cell"]] = d["dominated_by"]
                    debug.dprintf("Scenario", "pruned %s (dominated by "
                                  "%s)", d["cell"], d["dominated_by"])
        doc = pareto.artifact(
            self.matrix, self.cells, points,
            [{"cell": c, "dominated_by": by}
             for c, by in sorted(decisions.items())],
            fleet={"ticks": sched.ticks,
                   "by_status": sched._by_status()})
        pareto.write_artifact(self.outdir, doc)
        return doc

    def emit_artifact(self) -> dict:
        """The final fold (also the ``--pareto`` one-shot surface)."""
        return self._fold(self.sched)

    def decisions(self, sched) -> dict:
        """Prune decisions already made, recovered from tenant state
        alone — the revoke reasons the WAL replayed carry the dominator,
        so a recovered matrix reports the exact decision set of its
        killed predecessor."""
        out = {}
        for name, t in sched.tenants.items():
            if name in self._by_name and t.revoked.startswith(
                    PRUNE_REASON):
                out[name] = t.revoked[len(PRUNE_REASON):]
        return out

    # --- live cell state --------------------------------------------------

    def points(self, sched) -> dict:
        """Every cell's live design point: terminal cells from their
        recorded results, running cells from their orchestrator's live
        state, with the half-width computed by the SAME estimator
        selection the stopping rule and the metrics publish use
        (``stopping.live_halfwidth``)."""
        import numpy as np

        from shrewd_tpu.ops import classify as C
        from shrewd_tpu.parallel import stopping

        out = {}
        for cell in self.cells:
            t = sched.tenants.get(cell.name)
            if t is None:
                continue
            sp_name = (COHERENCE if cell.window == COHERENCE
                       else cell.plan["simpoints"][0]["name"])
            lane = f"{sp_name}/{cell.target}"
            tallies = trials = None
            strata = None
            converged = False
            if t.results and lane in t.results:
                row = t.results[lane]
                tallies = row["tallies"]
                trials = int(row["trials"])
                strata = row.get("strata")
                converged = bool(row["converged"])
            elif t.orch is not None:
                st = t.orch.state.get((sp_name, cell.target))
                if st is not None:
                    tallies = st.tallies
                    trials = st.trials
                    strata = st.strata
                    converged = bool(st.converged)
            if tallies is None:
                continue
            vul = int(np.asarray(tallies)[C.OUTCOME_SDC]
                      + np.asarray(tallies)[C.OUTCOME_DUE])
            conf = float(cell.plan.get("confidence", 0.95))
            hw = (stopping.live_halfwidth(
                vul, trials, strata,
                bool(cell.plan.get("stratify", False)), conf)
                if trials > 0 else 1.0)
            out[cell.name] = pareto.cell_point(
                cell, tallies, trials, hw, converged, t.status,
                confidence=conf)
        return out

    # --- read-only status -------------------------------------------------

    @staticmethod
    def status(outdir: str) -> dict:
        """Read-only matrix status from the persisted surfaces (matrix
        document + per-tick ``metrics.json`` + the fleet snapshot) — no
        lock, no journal replay, safe against a live server."""
        from shrewd_tpu.obs import metrics as obs_metrics

        mdoc = load_json_verified(os.path.join(outdir, MATRIX_DOC))
        out = {"tag": mdoc["tag"], "outdir": outdir, "tenants": {},
               "fleet": {}}
        try:
            snap = obs_metrics.read(outdir)
            out["fleet"] = snap.get("fleet", {})
            out["tenants"] = snap.get("tenants", {})
        except (OSError, ValueError):
            pass
        apath = pareto.artifact_path(outdir, mdoc["tag"])
        if os.path.exists(apath):
            with open(apath) as f:
                doc = json.load(f)
            out["decisions"] = doc.get("decisions", [])
            out["search"] = {g: {"area": r["area"],
                                 "sdc_rate": r["sdc_rate"],
                                 "front": len(r["pareto"])}
                             for g, r in doc.get("search", {}).items()}
        return out
