"""Scenario-matrix execution: the matrix as a fleet tenant set.

``ScenarioRunner`` admits a matrix's expanded cells through the resident
``CampaignScheduler`` and closes the loop: every ``pareto_every`` fleet
ticks it folds the live per-cell tallies (the same estimator surfaces
the PR-10 metrics publish uses), revokes the quota of Pareto-dominated
cells through the scheduler's journaled seam, and re-emits the
``PARETO_<tag>.json`` artifact atomically.

``FederatedScenarioRunner`` is the same loop with the FEDERATION as the
fleet: cells admitted through the ``Gateway`` spread across an elastic
pod pool (autoscaled when an ``Autoscaler`` is attached), the fold runs
on the driver's per-round seam, and prunes execute fleet-wide through
whichever pod hosts the dominated cell.  The Pareto front is pinned
bit-identical to the solo runner's — prune timing may differ across
pool schedules, converged rows (frozen keys) cannot.

Partial-matrix survivability: the matrix document itself is persisted
into the fleet outdir (``matrix.json``) before any cell runs, so a
hard-killed fleet recovers the WHOLE matrix — ``ScenarioRunner.
recover`` replays the fleet WAL (completed cells keep their recorded
results, running cells resume from their namespaced checkpoints,
journaled prune decisions re-apply exactly) and re-admits any cell the
kill landed before, then continues to the same bit-identical end state
an undisturbed run reaches.

Determinism: the fold cadence is counted in fleet ticks (never wall
clock), decisions depend only on converged tallies (bit-identical by
the frozen-key invariant) and static areas, and revocation is
journaled before any state change — so the prune *set* of a recovered
matrix equals the undisturbed run's, pinned in tests.

Import discipline: jax-free at module import (jax enters when the
scheduler elaborates cells).
"""

from __future__ import annotations

import json
import os

from shrewd_tpu.resilience import (doc_checksum, load_json_verified,
                                   write_json_atomic)
from shrewd_tpu.scenario import pareto
from shrewd_tpu.scenario.matrix import COHERENCE, ScenarioMatrix
from shrewd_tpu.service.scheduler import CampaignScheduler
from shrewd_tpu.utils import debug

MATRIX_DOC = "matrix.json"

#: prefix of the revoke reason the Pareto loop writes — decisions are
#: recoverable from tenant state alone (reason = "pareto:<dominator>")
PRUNE_REASON = "pareto:"


def _cell_lane(cell) -> str:
    """The one result lane a cell measures (simpoint/target)."""
    sp_name = (COHERENCE if cell.window == COHERENCE
               else cell.plan["simpoints"][0]["name"])
    return f"{sp_name}/{cell.target}"


def _live_point(cell, tallies, trials, strata, converged: bool,
                status: str) -> dict:
    """One cell's design point from raw row state, with the half-width
    computed by the SAME estimator selection the stopping rule and the
    metrics publish use (``stopping.live_halfwidth``) — shared by the
    solo and federated folds so both report identical points for
    identical rows (the frozen-key invariant makes the rows identical;
    this keeps the folds from diverging on arithmetic)."""
    import numpy as np

    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel import stopping

    trials = int(trials)
    vul = int(np.asarray(tallies)[C.OUTCOME_SDC]
              + np.asarray(tallies)[C.OUTCOME_DUE])
    conf = float(cell.plan.get("confidence", 0.95))
    hw = (stopping.live_halfwidth(
        vul, trials, strata,
        bool(cell.plan.get("stratify", False)), conf)
        if trials > 0 else 1.0)
    return pareto.cell_point(cell, tallies, trials, hw, bool(converged),
                             status, confidence=conf)


class ScenarioRunner:
    """Drive one matrix through one fleet (see module docstring)."""

    def __init__(self, matrix: ScenarioMatrix, outdir: str,
                 prune: bool = True, pareto_every: int = 4,
                 on_tick=None, **sched_kw):
        self.matrix = matrix
        self.cells = matrix.expand()
        self._by_name = {c.name: c for c in self.cells}
        self.outdir = outdir
        self.prune = bool(prune)
        self.pareto_every = max(1, int(pareto_every))
        self._user_on_tick = on_tick
        self._sched_kw = dict(sched_kw)
        self.sched: CampaignScheduler | None = None

    # --- construction -----------------------------------------------------

    def _persist_matrix(self) -> None:
        """The matrix document is a RECOVERY INPUT (a hard-killed fleet
        rebuilds the whole matrix from it), so it carries a content
        checksum like every other crash-surface artifact — recovery
        verifies it rather than trusting whatever bytes survived."""
        os.makedirs(self.outdir, exist_ok=True)
        doc = self.matrix.to_dict()
        doc["checksum"] = doc_checksum(doc)
        write_json_atomic(os.path.join(self.outdir, MATRIX_DOC), doc)

    def _admit_missing(self) -> int:
        """Admit every cell the scheduler does not already know — all of
        them on a fresh serve, only the not-yet-admitted remainder after
        a recovery (cells already in the replayed roster keep their
        recorded state untouched)."""
        n = 0
        for cell in self.cells:
            if cell.name not in self.sched.tenants:
                self.sched.admit(cell.spec())
                n += 1
        return n

    def serve(self) -> int:
        """Fresh matrix: persist the document, admit every cell, run the
        fleet to completion, emit the final artifact."""
        self._persist_matrix()
        self.sched = CampaignScheduler(outdir=self.outdir,
                                       on_tick=self._on_tick,
                                       **self._sched_kw)
        self._admit_missing()
        return self.run()

    @classmethod
    def recover(cls, outdir: str, prune: bool = True,
                pareto_every: int = 4, on_tick=None,
                **sched_kw) -> "ScenarioRunner":
        """Rebuild a matrix fleet after ANY shutdown from its persisted
        matrix document + the fleet WAL (``CampaignScheduler.recover``
        semantics; journaled prune decisions replay exactly).  The
        matrix document is checksum-verified: recovering a whole matrix
        from torn bytes would be worse than refusing."""
        matrix = ScenarioMatrix.from_dict(
            load_json_verified(os.path.join(outdir, MATRIX_DOC)))
        runner = cls(matrix, outdir, prune=prune,
                     pareto_every=pareto_every, on_tick=on_tick,
                     **sched_kw)
        runner.sched = CampaignScheduler.recover(
            outdir, on_tick=runner._on_tick, **runner._sched_kw)
        runner._admit_missing()
        return runner

    def run(self) -> int:
        rc = self.sched.run()
        try:
            self.emit_artifact()
        except Exception as e:  # noqa: BLE001 — the artifact is DERIVED
            # state (journal + per-tenant results are the ground truth,
            # and tools/scenario.py --pareto can re-fold any time): a
            # fold that cannot compute must not discard the fleet rc of
            # a fully served matrix.  The --pareto one-shot surface
            # calls emit_artifact() directly and DOES raise.
            debug.dprintf("Scenario", "final pareto fold failed: %s", e)
            import sys

            print(f"scenario: final pareto fold failed ({e}) — re-fold "
                  "with tools/scenario.py --pareto", file=sys.stderr)
        return rc

    # --- the closed loop --------------------------------------------------

    def _on_tick(self, sched) -> None:
        if self._user_on_tick is not None:
            self._user_on_tick(sched)
        if sched.ticks % self.pareto_every:
            return
        try:
            self._fold(sched)
        except Exception as e:  # noqa: BLE001 — the Pareto loop is a
            # supervisor over the fleet, never a dependency of it: a
            # fold that cannot compute (a cell mid-elaboration, a model
            # import failing) skips this tick and the fleet keeps
            # serving; decisions are monotonic so a later fold makes
            # the same calls
            debug.dprintf("Scenario", "pareto fold skipped: %s", e)

    def _fold(self, sched) -> dict:
        points = self.points(sched)
        decisions = self.decisions(sched)
        if self.prune:
            for d in pareto.prune_decisions(self.cells, points,
                                            revoked=dict(decisions)):
                if sched.revoke_quota(
                        d["cell"], PRUNE_REASON + d["dominated_by"]):
                    decisions[d["cell"]] = d["dominated_by"]
                    debug.dprintf("Scenario", "pruned %s (dominated by "
                                  "%s)", d["cell"], d["dominated_by"])
        doc = pareto.artifact(
            self.matrix, self.cells, points,
            [{"cell": c, "dominated_by": by}
             for c, by in sorted(decisions.items())],
            fleet={"ticks": sched.ticks,
                   "by_status": sched._by_status()})
        pareto.write_artifact(self.outdir, doc)
        return doc

    def emit_artifact(self) -> dict:
        """The final fold (also the ``--pareto`` one-shot surface)."""
        return self._fold(self.sched)

    def decisions(self, sched) -> dict:
        """Prune decisions already made, recovered from tenant state
        alone — the revoke reasons the WAL replayed carry the dominator,
        so a recovered matrix reports the exact decision set of its
        killed predecessor."""
        out = {}
        for name, t in sched.tenants.items():
            if name in self._by_name and t.revoked.startswith(
                    PRUNE_REASON):
                out[name] = t.revoked[len(PRUNE_REASON):]
        return out

    # --- live cell state --------------------------------------------------

    def points(self, sched) -> dict:
        """Every cell's live design point: terminal cells from their
        recorded results, running cells from their orchestrator's live
        state (the half-width arithmetic is shared with the federated
        fold — ``_live_point``)."""
        out = {}
        for cell in self.cells:
            t = sched.tenants.get(cell.name)
            if t is None:
                continue
            lane = _cell_lane(cell)
            sp_name = lane.split("/", 1)[0]
            tallies = trials = None
            strata = None
            converged = False
            if t.results and lane in t.results:
                row = t.results[lane]
                tallies = row["tallies"]
                trials = int(row["trials"])
                strata = row.get("strata")
                converged = bool(row["converged"])
            elif t.orch is not None:
                st = t.orch.state.get((sp_name, cell.target))
                if st is not None:
                    tallies = st.tallies
                    trials = st.trials
                    strata = st.strata
                    converged = bool(st.converged)
            if tallies is None:
                continue
            out[cell.name] = _live_point(cell, tallies, trials, strata,
                                         converged, t.status)
        return out

    # --- read-only status -------------------------------------------------

    @staticmethod
    def status(outdir: str) -> dict:
        """Read-only matrix status from the persisted surfaces (matrix
        document + per-tick ``metrics.json`` + the fleet snapshot) — no
        lock, no journal replay, safe against a live server."""
        from shrewd_tpu.obs import metrics as obs_metrics

        mdoc = load_json_verified(os.path.join(outdir, MATRIX_DOC))
        out = {"tag": mdoc["tag"], "outdir": outdir, "tenants": {},
               "fleet": {}}
        try:
            snap = obs_metrics.read(outdir)
            out["fleet"] = snap.get("fleet", {})
            out["tenants"] = snap.get("tenants", {})
        except (OSError, ValueError):
            pass
        apath = pareto.artifact_path(outdir, mdoc["tag"])
        if os.path.exists(apath):
            with open(apath) as f:
                doc = json.load(f)
            out["decisions"] = doc.get("decisions", [])
            out["search"] = {g: {"area": r["area"],
                                 "sdc_rate": r["sdc_rate"],
                                 "front": len(r["pareto"])}
                             for g, r in doc.get("search", {}).items()}
        return out


class FederatedScenarioRunner:
    """Drive one matrix through one FEDERATION: the same closed Pareto
    loop as ``ScenarioRunner``, but the fleet is the elastic pod pool.

    Cells are admitted through the ``Gateway`` (its ETA-weighted
    routing spreads the matrix across pods; an attached ``Autoscaler``
    grows and shrinks the pool under the matrix's pressure), the fold
    runs once per federation round on the driver's ``on_round`` seam,
    and a prune decision executes FLEET-WIDE through whichever pod
    currently hosts the dominated cell — the pod's journaled
    ``revoke_quota`` seam, so the decision survives that pod's crash
    exactly like a solo fleet's would.  Decisions already executed are
    recovered from the gateway ledger alone (the pruned done-doc's
    ``reason`` carries the dominator), so a recovered federation
    reports the exact decision set of its killed predecessor without
    consulting any pod.

    Front equality with the solo runner is structural, not incidental:
    scheme-mates share frozen PRNG keys on their measurement
    coordinates, so every converged row is bit-identical wherever (and
    on however many pods) it ran, and ``pareto.design_search`` builds
    the front from converged rows only — prune *timing* may differ
    across pool schedules, the front cannot.  The CI gate pins exactly
    that: the ``PARETO_FED_<tag>.json`` front equals the solo run's.

    The artifact and the matrix document live at the federation ROOT
    (beside ``gateway/`` and ``pods/``) — one recovery surface for the
    whole matrix, whatever the pool did."""

    def __init__(self, matrix: ScenarioMatrix, root: str,
                 pod_names=("pod0", "pod1", "pod2"), prune: bool = True,
                 pareto_every: int = 1, on_round=None, **fed_kw):
        self.matrix = matrix
        self.cells = matrix.expand()
        self._by_name = {c.name: c for c in self.cells}
        self.root = root
        self.pod_names = tuple(pod_names)
        self.prune = bool(prune)
        self.pareto_every = max(1, int(pareto_every))
        self._user_on_round = on_round
        self._fed_kw = dict(fed_kw)
        self.fed = None               # federation.driver.Federation

    # --- construction -----------------------------------------------------

    def _persist_matrix(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        doc = self.matrix.to_dict()
        doc["checksum"] = doc_checksum(doc)
        write_json_atomic(os.path.join(self.root, MATRIX_DOC), doc)

    def _admit_missing(self) -> int:
        n = 0
        for cell in self.cells:
            if cell.name not in self.fed.gateway.entries:
                self.fed.submit(cell.spec())
                n += 1
        return n

    def serve(self) -> int:
        """Fresh matrix: persist the document, admit every cell through
        the gateway, serve the federation to convergence, emit the
        final artifact."""
        from shrewd_tpu.federation.driver import Federation

        self._persist_matrix()
        self.fed = Federation(self.root, pod_names=self.pod_names,
                              on_round=self._on_round, **self._fed_kw)
        self._admit_missing()
        return self.run()

    @classmethod
    def recover(cls, root: str, pod_names=("pod0", "pod1", "pod2"),
                prune: bool = True, pareto_every: int = 1,
                on_round=None, **fed_kw) -> "FederatedScenarioRunner":
        """Rebuild a federated matrix after ANY shutdown: verify the
        matrix document, recover the federation (gateway WAL replay —
        including every pool transition — then pod WALs lazily),
        re-admit cells the kill landed before their accept record.
        Prune decisions replay from the ledger; the pool replays from
        its journaled scale/retire records."""
        from shrewd_tpu.federation.driver import Federation

        matrix = ScenarioMatrix.from_dict(
            load_json_verified(os.path.join(root, MATRIX_DOC)))
        runner = cls(matrix, root, pod_names=pod_names, prune=prune,
                     pareto_every=pareto_every, on_round=on_round,
                     **fed_kw)
        runner.fed = Federation.recover(
            root, pod_names=runner.pod_names,
            on_round=runner._on_round, **runner._fed_kw)
        runner._admit_missing()
        return runner

    def run(self) -> int:
        rc = self.fed.serve()
        try:
            self.emit_artifact()
        except Exception as e:  # noqa: BLE001 — same posture as the
            # solo runner: the artifact is DERIVED state; a final fold
            # that cannot compute must not discard the rc of a served
            # matrix
            debug.dprintf("Scenario", "final pareto fold failed: %s", e)
            import sys

            print(f"scenario: final pareto fold failed ({e}) — re-fold "
                  "with tools/scenario.py --pareto", file=sys.stderr)
        return rc

    # --- the closed loop --------------------------------------------------

    def _on_round(self, fed) -> None:
        if self._user_on_round is not None:
            self._user_on_round(fed)
        if fed.round % self.pareto_every:
            return
        try:
            self._fold(fed)
        except Exception as e:  # noqa: BLE001 — the Pareto loop is a
            # supervisor over the federation, never a dependency of it
            # (same contract as the solo runner's _on_tick): decisions
            # are monotonic, a later fold makes the same calls
            debug.dprintf("Scenario", "pareto fold skipped: %s", e)

    def _fold(self, fed) -> dict:
        points = self.points(fed)
        decisions = self.decisions(fed)
        if self.prune:
            for d in pareto.prune_decisions(self.cells, points,
                                            revoked=dict(decisions)):
                if self._revoke(fed, d["cell"], d["dominated_by"]):
                    decisions[d["cell"]] = d["dominated_by"]
                    debug.dprintf("Scenario", "pruned %s fleet-wide "
                                  "(dominated by %s)", d["cell"],
                                  d["dominated_by"])
        doc = pareto.artifact(
            self.matrix, self.cells, points,
            [{"cell": c, "dominated_by": by}
             for c, by in sorted(decisions.items())],
            fleet={"rounds": fed.round,
                   "by_status": fed.gateway._by_status(),
                   "pool": fed.gateway.pool_status()})
        pareto.write_artifact(self.root, doc)
        return doc

    def _revoke(self, fed, cell: str, dominator: str) -> bool:
        """Execute one prune on whichever pod hosts the cell — the
        pod-side journaled seam, exactly the division of authority the
        driver uses for shard-convergence revocations.  A cell not yet
        placed (or whose pod is dead/partitioned this round) is simply
        retried next fold: decisions are re-derived from converged
        tallies, which never un-converge."""
        e = fed.gateway.entries.get(cell)
        if e is None or e.status != "placed" or not e.pod:
            return False
        pod = fed.pods.get(e.pod)
        if pod is None or pod.dead or pod.partitioned \
                or pod.sched is None or cell not in pod.sched.tenants:
            return False
        return pod.sched.revoke_quota(cell, PRUNE_REASON + dominator)

    def emit_artifact(self) -> dict:
        """The final fold (also the ``--pareto`` one-shot surface)."""
        return self._fold(self.fed)

    def decisions(self, fed) -> dict:
        """Prune decisions already made, fleet-wide: executed ones from
        the gateway ledger (the pruned done-doc's ``reason`` carries
        the dominator — survives every pod), in-flight ones from the
        hosting pods' live tenant state (revoked, drain pending)."""
        out = {}
        for name, e in fed.gateway.entries.items():
            if name not in self._by_name:
                continue
            reason = str((e.result or {}).get("reason") or "")
            if reason.startswith(PRUNE_REASON):
                out[name] = reason[len(PRUNE_REASON):]
        for pod in fed.pods.values():
            if pod.sched is None or pod.dead:
                continue
            for name, t in pod.sched.tenants.items():
                if name in self._by_name \
                        and t.revoked.startswith(PRUNE_REASON):
                    out.setdefault(name,
                                   t.revoked[len(PRUNE_REASON):])
        return out

    # --- live cell state --------------------------------------------------

    def points(self, fed) -> dict:
        """Every cell's live design point, fleet-wide: done cells from
        the gateway ledger's authoritative done-doc (each tenant
        counted exactly once, per the routing ledger — whichever pods
        its history visited), placed cells from their hosting pod's
        live scheduler state.  Point arithmetic is shared with the solo
        runner (``_live_point``)."""
        out = {}
        for cell in self.cells:
            e = fed.gateway.entries.get(cell.name)
            if e is None:
                continue
            lane = _cell_lane(cell)
            sp_name = lane.split("/", 1)[0]
            tallies = trials = None
            strata = None
            converged = False
            status = "queued"
            res = (e.result or {}).get("results") or {}
            if lane in res:
                row = res[lane]
                tallies = row["tallies"]
                trials = int(row["trials"])
                strata = row.get("strata")
                converged = bool(row.get("converged", False))
                status = str((e.result or {}).get("status")
                             or "complete")
            elif e.pod:
                pod = fed.pods.get(e.pod)
                t = (pod.sched.tenants.get(cell.name)
                     if pod is not None and not pod.dead
                     and pod.sched is not None else None)
                if t is not None:
                    status = t.status
                    if t.results and lane in t.results:
                        row = t.results[lane]
                        tallies = row["tallies"]
                        trials = int(row["trials"])
                        strata = row.get("strata")
                        converged = bool(row["converged"])
                    elif t.orch is not None:
                        st = t.orch.state.get((sp_name, cell.target))
                        if st is not None:
                            tallies = st.tallies
                            trials = st.trials
                            strata = st.strata
                            converged = bool(st.converged)
            if tallies is None:
                continue
            out[cell.name] = _live_point(cell, tallies, trials, strata,
                                         converged, status)
        return out
