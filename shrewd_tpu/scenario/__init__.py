"""Scenario-matrix campaigns: cross-product coverage with a closed-loop
protection search (ROADMAP item 3).

One declarative ``ScenarioMatrix`` expands to the full (workloads ×
SimPoint windows × fault targets × protection schemes × thermal
envelopes) cross-product as a fleet tenant set (``matrix.py``);
``ScenarioRunner`` admits it through the resident ``CampaignScheduler``
and closes the loop (``runner.py``); ``pareto.py`` folds the live
per-cell tallies into ``search/protect.py``'s design-space algebra
after fleet ticks — pruning Pareto-dominated cells through the
scheduler's journaled ``revoke_quota`` seam and emitting the
``PARETO_<tag>.json`` area-vs-system-SDC front as an atomic campaign
artifact.

Import discipline: jax-free at package import (matrices are host-side
data; jax enters when the scheduler elaborates cells or the Pareto fold
calls into ``search/protect``)."""

from shrewd_tpu.scenario.matrix import (COHERENCE, KNOWN_TARGETS,
                                        MATRIX_SCHEMA, Cell,
                                        ScenarioMatrix, cell_seed)
from shrewd_tpu.scenario.pareto import (PARETO_SCHEMA, artifact,
                                        artifact_path, cell_point,
                                        design_search, dominates,
                                        prune_decisions, write_artifact)
from shrewd_tpu.scenario.runner import (MATRIX_DOC, PRUNE_REASON,
                                        FederatedScenarioRunner,
                                        ScenarioRunner)

__all__ = [
    "COHERENCE", "KNOWN_TARGETS", "MATRIX_SCHEMA", "Cell",
    "ScenarioMatrix", "cell_seed",
    "PARETO_SCHEMA", "artifact", "artifact_path", "cell_point",
    "design_search", "dominates", "prune_decisions", "write_artifact",
    "MATRIX_DOC", "PRUNE_REASON", "FederatedScenarioRunner",
    "ScenarioRunner",
]
