"""Statistics framework.

Re-imagines gem5's stats core (``src/base/statistics.hh``: ``Scalar`` :1929,
``Vector`` :921, ``Distribution``/``Histogram``, ``Formula`` :1552; hierarchy
``base/stats/group.hh``; text writer ``base/stats/text.cc``) for a batched
campaign: device code produces *tally arrays* (jnp reductions under psum);
host-side stat objects absorb them at batch granularity, and dump in a
stats.txt-compatible layout so existing gem5 diffing tooling works on the new
framework's output.

The hierarchy mirrors the reference: every model owns a ``Group``; groups nest
(``statistics::Group`` bound to the SimObject tree, reference
``python/m5/simulate.py:143-145``); ``dump()`` walks the tree.
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "Scalar", "Vector", "Distribution", "Histogram", "Formula", "Text",
    "Group", "dump_text", "dump_json", "to_dict",
]


class StatBase:
    def __init__(self, name: str, desc: str = ""):
        self.name = name
        self.desc = desc

    def reset(self) -> None:
        raise NotImplementedError

    # Each stat yields (name, value, desc) rows for the text writer.
    def rows(self, prefix: str) -> Iterator[tuple[str, Any, str]]:
        raise NotImplementedError

    def to_value(self) -> Any:
        raise NotImplementedError


class Scalar(StatBase):
    """A single accumulating number (``statistics::Scalar``)."""

    def __init__(self, name: str, desc: str = "", init: float = 0):
        super().__init__(name, desc)
        self._init = init
        self.value: float = init

    def __iadd__(self, x) -> "Scalar":
        self.value += float(x)
        return self

    def set(self, x) -> None:
        self.value = float(x)

    def reset(self) -> None:
        self.value = self._init

    def rows(self, prefix):
        yield f"{prefix}{self.name}", self.value, self.desc

    def to_value(self):
        return self.value


class Vector(StatBase):
    """Fixed-length vector of counters with optional subnames
    (``statistics::Vector``); dumps per-element rows plus a total."""

    def __init__(self, name: str, size: int, desc: str = "",
                 subnames: list[str] | None = None):
        super().__init__(name, desc)
        if subnames is not None and len(subnames) != size:
            raise ValueError(f"{name}: {len(subnames)} subnames for size {size}")
        self.subnames = subnames
        self.value = np.zeros(size, dtype=np.float64)

    def __iadd__(self, x) -> "Vector":
        arr = np.asarray(x, dtype=np.float64)
        if arr.shape != self.value.shape:
            raise ValueError(f"{self.name}: shape {arr.shape} != {self.value.shape}")
        self.value += arr
        return self

    def __getitem__(self, i) -> float:
        return float(self.value[i])

    def add(self, i: int, x: float = 1) -> None:
        self.value[i] += x

    def total(self) -> float:
        return float(self.value.sum())

    def reset(self) -> None:
        self.value[:] = 0

    def rows(self, prefix):
        for i, v in enumerate(self.value):
            sub = self.subnames[i] if self.subnames else str(i)
            yield f"{prefix}{self.name}::{sub}", float(v), self.desc
        yield f"{prefix}{self.name}::total", self.total(), self.desc

    def to_value(self):
        out = {(self.subnames[i] if self.subnames else str(i)): float(v)
               for i, v in enumerate(self.value)}
        out["total"] = self.total()
        return out


class Distribution(StatBase):
    """Fixed-range bucketed distribution with moments
    (``statistics::Distribution``)."""

    def __init__(self, name: str, lo: float, hi: float, n_buckets: int,
                 desc: str = ""):
        super().__init__(name, desc)
        self.lo, self.hi, self.n_buckets = lo, hi, n_buckets
        self.bucket_size = (hi - lo) / n_buckets
        self.reset()

    def reset(self) -> None:
        self.counts = np.zeros(self.n_buckets, dtype=np.float64)
        self.underflow = 0.0
        self.overflow = 0.0
        self.sum = 0.0
        self.sum_sq = 0.0
        self.min_val = math.inf
        self.max_val = -math.inf

    def sample(self, values, weights=None) -> None:
        """Absorb a batch of samples (array-friendly: one host call/batch)."""
        v = np.atleast_1d(np.asarray(values, dtype=np.float64))
        if weights is None:
            w = np.ones_like(v)
        else:
            try:
                w = np.broadcast_to(
                    np.asarray(weights, dtype=np.float64), v.shape).copy()
            except ValueError:
                raise ValueError(
                    f"{self.name}: weights shape "
                    f"{np.shape(weights)} does not broadcast to {v.shape}")
        if v.size == 0:
            return
        self.underflow += w[v < self.lo].sum()
        self.overflow += w[v >= self.hi].sum()
        in_range = (v >= self.lo) & (v < self.hi)
        if in_range.any():
            idx = ((v[in_range] - self.lo) / self.bucket_size).astype(np.int64)
            # float division can round a value just below hi onto n_buckets
            idx = np.clip(idx, 0, self.n_buckets - 1)
            np.add.at(self.counts, idx, w[in_range])
        self.sum += float((v * w).sum())
        self.sum_sq += float((v * v * w).sum())
        self.min_val = min(self.min_val, float(v.min()))
        self.max_val = max(self.max_val, float(v.max()))

    @property
    def samples(self) -> float:
        return float(self.counts.sum() + self.underflow + self.overflow)

    def mean(self) -> float:
        n = self.samples
        return self.sum / n if n else float("nan")

    def stdev(self) -> float:
        n = self.samples
        if n < 2:
            return float("nan")
        var = (self.sum_sq - self.sum * self.sum / n) / (n - 1)
        return math.sqrt(max(var, 0.0))

    def rows(self, prefix):
        base = f"{prefix}{self.name}"
        yield f"{base}::samples", self.samples, self.desc
        yield f"{base}::mean", self.mean(), self.desc
        yield f"{base}::stdev", self.stdev(), self.desc
        yield f"{base}::underflows", self.underflow, self.desc
        for i, c in enumerate(self.counts):
            lo = self.lo + i * self.bucket_size
            hi = lo + self.bucket_size
            yield f"{base}::{lo:g}-{hi:g}", float(c), self.desc
        yield f"{base}::overflows", self.overflow, self.desc
        yield f"{base}::min_value", self.min_val, self.desc
        yield f"{base}::max_value", self.max_val, self.desc

    def to_value(self):
        return {
            "samples": self.samples, "mean": self.mean(), "stdev": self.stdev(),
            "underflow": self.underflow, "overflow": self.overflow,
            "min": self.min_val, "max": self.max_val,
            "counts": self.counts.tolist(),
            "lo": self.lo, "hi": self.hi,
        }


class Histogram(Distribution):
    """Auto-ranging histogram (``statistics::Histogram``): doubles its range
    by merging adjacent buckets when a sample lands above ``hi``."""

    def __init__(self, name: str, n_buckets: int, desc: str = ""):
        if n_buckets % 2:
            raise ValueError("Histogram needs an even bucket count")
        super().__init__(name, 0.0, float(n_buckets), n_buckets, desc)

    def reset(self) -> None:
        # restore the original range/granularity, like HistStor::reset
        self.hi = float(self.n_buckets)
        self.bucket_size = 1.0
        super().reset()

    def sample(self, values, weights=None) -> None:
        v = np.atleast_1d(np.asarray(values, dtype=np.float64))
        if v.size == 0:
            return
        if not np.isfinite(v).all():
            raise ValueError(f"{self.name}: non-finite sample")
        if (v < 0).any():
            raise ValueError(f"{self.name}: Histogram range starts at 0; "
                             f"negative sample rejected (use Distribution)")
        while float(v.max()) >= self.hi:
            # merge pairs: counts[i] = counts[2i] + counts[2i+1]; double range
            merged = self.counts.reshape(-1, 2).sum(axis=1)
            self.counts = np.concatenate(
                [merged, np.zeros(self.n_buckets // 2)])
            self.hi = self.lo + 2 * (self.hi - self.lo)
            self.bucket_size *= 2
        super().sample(v, weights)


class Formula(StatBase):
    """Derived stat evaluated lazily at dump time (``statistics::Formula``),
    e.g. AVF = sdc_count / trials."""

    def __init__(self, name: str, fn: Callable[[], Any], desc: str = ""):
        super().__init__(name, desc)
        self.fn = fn

    def reset(self) -> None:
        pass

    def rows(self, prefix):
        val = self.fn()
        if isinstance(val, dict):
            for k, v in val.items():
                yield f"{prefix}{self.name}::{k}", v, self.desc
        else:
            yield f"{prefix}{self.name}", val, self.desc

    def to_value(self):
        return self.fn()


class Text(StatBase):
    """A string-valued stat (the reference's ``statistics::Info`` prose
    fields): run identity, posture labels, abort reasons.  Every dump
    backend is string-safe for it — ``dump_hdf5`` writes a variable-
    length string dataset (the same fallback dict-valued Formulas with
    string leaves already get), so a prose value never trips the
    numeric-only Formula contract."""

    def __init__(self, name: str, value: str = "", desc: str = ""):
        super().__init__(name, desc)
        self.value = str(value)

    def set(self, value) -> None:
        self.value = str(value)

    def reset(self) -> None:
        self.value = ""

    def rows(self, prefix):
        yield f"{prefix}{self.name}", self.value, self.desc

    def to_value(self):
        return self.value


class Group:
    """Hierarchical stat container (``statistics::Group``).

    Stats and subgroups register by attribute assignment::

        g = Group("o3")
        g.trials = Scalar("trials", "total trials run")
        g.outcomes = Vector("outcomes", 4, subnames=[...])
    """

    def __init__(self, name: str):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "_stats", {})
        object.__setattr__(self, "_groups", {})

    def __setattr__(self, key, value):
        # Validate BEFORE mutating the registry so a rejected rebind leaves
        # the existing registrations intact.
        old = getattr(self, key, None)
        if isinstance(value, StatBase):
            clash = self._stats.get(value.name)
            if clash is not None and clash is not old:
                raise ValueError(
                    f"duplicate stat name {value.name!r} in group {self.name!r}")
        elif isinstance(value, Group):
            clash = self._groups.get(value.name)
            if clash is not None and clash is not old:
                raise ValueError(
                    f"duplicate subgroup name {value.name!r} in group {self.name!r}")
        # rebinding an attribute drops its previous registration (only if the
        # registration actually points at the object being replaced)
        if isinstance(old, StatBase) and self._stats.get(old.name) is old:
            del self._stats[old.name]
        elif isinstance(old, Group) and self._groups.get(old.name) is old:
            del self._groups[old.name]
        if isinstance(value, StatBase):
            self._stats[value.name] = value
        elif isinstance(value, Group):
            self._groups[value.name] = value
        object.__setattr__(self, key, value)

    def add(self, stat_or_group):
        setattr(self, "_anon_%d" % (len(self._stats) + len(self._groups)),
                stat_or_group)
        return stat_or_group

    def reset(self) -> None:
        """m5.stats.reset() analog (reference python/m5/stats/__init__.py:433)."""
        for s in self._stats.values():
            s.reset()
        for g in self._groups.values():
            g.reset()

    def rows(self, prefix: str = "") -> Iterator[tuple[str, Any, str]]:
        base = f"{prefix}{self.name}." if self.name else prefix
        for s in self._stats.values():
            yield from s.rows(base)
        for g in self._groups.values():
            yield from g.rows(base)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {s.name: s.to_value() for s in self._stats.values()}
        for g in self._groups.values():
            out[g.name] = g.to_dict()
        return out


# --- writers (base/stats/text.cc + gem5stats JSON analogs) ---

_BEGIN = "---------- Begin Simulation Statistics ----------"
_END = "---------- End Simulation Statistics   ----------"


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        if v != v:
            return "nan"
        if math.isinf(v):
            return "inf" if v > 0 else "-inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return f"{v:.6f}"
    return str(v)


def dump_text(group: Group, fileobj=None, desc: bool = True) -> str:
    """stats.txt-format dump: ``name  value  # desc`` between Begin/End
    markers, matching the reference's text layout so diff tooling carries
    over (``base/stats/text.cc``)."""
    lines = [_BEGIN, ""]
    for name, value, d in group.rows():
        row = f"{name:<50} {_fmt(value):>20}"
        if desc and d:
            row += f"  # {d}"
        lines.append(row)
    lines += ["", _END, ""]
    text = "\n".join(lines)
    if fileobj is not None:
        fileobj.write(text)
    return text


def to_dict(group: Group) -> dict:
    return group.to_dict()


def _json_safe(v: Any) -> Any:
    """Strict-JSON projection: ``NaN``/``±inf`` (e.g. ``Distribution.mean``
    with zero samples) become ``null`` — ``json.dumps``'s non-strict
    default would emit bare ``NaN``/``Infinity`` tokens that strict
    parsers reject."""
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (np.floating, np.integer)):
        v = v.item()
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


def dump_json(group: Group, fileobj=None) -> str:
    """Structured dump (the ``get_simstat`` analog,
    reference ``python/m5/stats/gem5stats.py:351``).  Strict JSON:
    non-finite values serialize as ``null`` (``allow_nan=False`` enforces
    the contract — a regression reappearing fails loudly here, not in the
    consumer's parser)."""
    text = json.dumps(_json_safe(group.to_dict()), indent=2, default=float,
                      allow_nan=False)
    if fileobj is not None:
        fileobj.write(text)
    return text


def dump_hdf5(group: Group, path: str) -> None:
    """HDF5 dump (the reference's ``--stats-file=h5://`` backend,
    ``src/base/stats/hdf5.cc``): one HDF5 group per stats Group, one
    dataset per stat.  Scalars/Formulas land as 0-d float datasets,
    Vectors as 1-d arrays with a ``subnames`` attribute, Distributions/
    Histograms as bucket-count arrays with lo/hi/underflow/overflow/
    moment attributes.  One dump per call (overwrite semantics)."""
    import h5py

    def write_dict(h5g, d: dict, path: str) -> None:
        """Dict-valued Formula payloads, possibly nested (e.g. the
        per-content-key executable-cache ledger) and possibly carrying
        string leaves — strings land as variable-length string scalars,
        numbers as float64.  Non-numeric leaves raise with the full
        stat path, like the scalar branch below."""
        for key, val in d.items():
            leaf = f"{path}.{key}"
            if isinstance(val, dict):
                write_dict(h5g.require_group(str(key)), val, leaf)
            elif isinstance(val, str):
                h5g.create_dataset(str(key), data=val)
            else:
                try:
                    fv = float(val)
                except (TypeError, ValueError):
                    raise TypeError(
                        f"stat {leaf!r}: Formula must be numeric, got "
                        f"{type(val).__name__} ({val!r}) — return a "
                        "number (NaN is fine), a dict of numbers/"
                        "strings, or use stats.Text for prose") from None
                h5g.create_dataset(str(key), data=fv)

    def write_group(h5g, g: Group, prefix: str) -> None:
        for s in g._stats.values():
            stat_path = f"{prefix}{s.name}"
            if isinstance(s, Distribution):      # includes Histogram
                v = s.to_value()
                ds = h5g.create_dataset(
                    s.name, data=np.asarray(v["counts"], np.float64))
                for key in ("lo", "hi", "underflow", "overflow",
                            "samples", "mean", "stdev", "min", "max"):
                    ds.attrs[key] = float(v[key])
            elif isinstance(s, Vector):
                ds = h5g.create_dataset(
                    s.name, data=np.asarray(s.value, np.float64))
                if s.subnames:
                    ds.attrs["subnames"] = [str(x) for x in s.subnames]
            else:                                 # Scalar / Formula / Text
                v = s.to_value()
                if isinstance(v, dict):           # dict-valued Formula
                    write_dict(h5g.require_group(s.name), v, stat_path)
                elif isinstance(v, str):          # Text / prose Formula:
                    # the same string-safe fallback write_dict gives
                    # nested string leaves
                    h5g.create_dataset(s.name, data=v)
                else:
                    try:
                        fv = float(v)
                    except (TypeError, ValueError):
                        # name the offending stat: the bare float(v)
                        # TypeError ("Formula must be numeric") gave no
                        # path, which once cost a session 17 tests of
                        # archaeology
                        raise TypeError(
                            f"stat {stat_path!r}: Formula must be "
                            f"numeric, got {type(v).__name__} ({v!r}) — "
                            "return a number (NaN is fine), a dict of "
                            "numbers/strings, or use stats.Text for "
                            "prose") from None
                    h5g.create_dataset(s.name, data=fv)
            h5g[s.name].attrs["description"] = s.desc
        for sub in g._groups.values():
            write_group(h5g.require_group(sub.name), sub,
                        f"{prefix}{sub.name}.")

    with h5py.File(path, "w") as f:
        root = f.require_group(group.name) if group.name else f["/"]
        write_group(root, group, f"{group.name}." if group.name else "")


__all__.append("dump_hdf5")
