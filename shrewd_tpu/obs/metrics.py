"""Live fleet metrics: atomic per-tick snapshots + Prometheus exposition.

The resident scheduler (``service/scheduler.py``) calls ``publish`` each
tick: a JSON snapshot lands atomically at ``<outdir>/metrics.json``
(readers never observe a torn document) and the same numbers render as
Prometheus text exposition at ``<outdir>/metrics.prom`` — the pull
surface a scraper or ``tools/obs.py --tail`` consumes without touching
scheduler internals.

Per tenant: status, trials served, trials/s, scheduling quanta, virtual
time (the fair-share position), queue latency, failures/kills, and the
live Wilson half-width per (simpoint, structure) — the half-width
trajectory that says how far each tenant is from convergence.  Fleet-
wide: tick count, fairness index, executable-cache hit rate (the
cross-tenant compile-dedupe observable), write-ahead-journal depth, and
recovery/quarantine counts.

Wall-clock reads route through ``obs.clock`` (GL106): rates are
*observability*, never scheduling inputs — every scheduling decision
still consumes only admission order, trial counts and weights.

Import discipline: jax-free at module import (the half-width helper
lazy-imports the stopping module — by publish time the scheduler has
long since built its mesh).
"""

from __future__ import annotations

import os

from shrewd_tpu.obs import clock

METRICS_JSON = "metrics.json"
METRICS_PROM = "metrics.prom"

#: the federation gateway's pool-ledger surfaces (published under the
#: GATEWAY outdir, not a pod's — pool membership is gateway state)
POOL_JSON = "pool.json"
POOL_PROM = "pool.prom"

#: exposition prefix — one namespace for every gauge this module emits
_PROM_NS = "shrewd_fleet"


def _convergence(orch) -> tuple[dict, float, dict]:
    """``({lane: halfwidth}, eta_trials, {lane: cumulative counts})`` of
    one tenant's orchestrator — the convergence-distance trajectory,
    computed by the SAME estimator selection the stopping rule applies
    (post-stratified when the strata history covers the trials, pooled
    Wilson otherwise) so the published distance never disagrees with the
    rule that decides stopping.  ``eta_trials`` sums
    ``stopping.eta_trials`` (the planner's own trials-needed trajectory)
    over the tenant's lanes — the number the federation gateway routes
    on: convergence distance, not instantaneous throughput.  The third
    element is the per-lane cumulative {tallies, trials, strata} counts
    — the live numbers the gateway's sharded-merge fold sums across
    sub-tenants (``stopping.merged_fold``)."""
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel import stopping

    hws = {}
    eta = 0.0
    lanes = {}
    for (sp, st), s in orch.state.items():
        lane = f"{sp}/{st}"
        lanes[lane] = {
            "tallies": [int(x) for x in s.tallies],
            "trials": int(s.trials),
            "strata": (None if s.strata is None else
                       [[int(x) for x in row] for row in s.strata]),
        }
        if s.trials <= 0:
            # an unstarted lane still owes its whole min_trials floor
            # (bounded by the cap — a sharded sub-tenant's slice may be
            # smaller than the parent's min_trials floor)
            eta += float(min(orch.plan.min_trials, orch.plan.max_trials))
            continue
        vul = int(s.tallies[C.OUTCOME_SDC] + s.tallies[C.OUTCOME_DUE])
        hws[lane] = round(float(stopping.live_halfwidth(
            vul, s.trials, s.strata, orch.plan.stratify,
            orch.plan.confidence)), 6)
        if not s.done and not s.converged:
            # `done` and not `converged` = the lane hit its max_trials
            # cap with the CI still wide: it will never run again, so
            # it owes NO further trials — counting its (permanently
            # positive) trajectory distance would leave phantom ETA
            # mass on the pod and misroute the federation gateway.
            # The live trajectory distance is clamped at the remaining
            # max_trials budget for the same reason: trials past the
            # cap will never be served, and for a sharded sub-tenant
            # the remaining budget IS its share of the remaining batch
            # space — an unclamped trajectory would overstate a sharded
            # campaign's finish time by the shard count.
            eta += min(
                stopping.eta_trials(
                    vul, s.trials, s.strata, orch.plan.stratify,
                    orch.plan.confidence, orch.plan.target_halfwidth,
                    orch.plan.min_trials),
                max(0.0, float(orch.plan.max_trials) - s.trials))
    return hws, eta, lanes


def snapshot(sched) -> dict:
    """One JSON-able metrics snapshot of a ``CampaignScheduler``."""
    from shrewd_tpu.parallel import exec_cache

    now_mono = clock.monotonic()
    tenants = {}
    for name, t in sched.tenants.items():
        wall = t.wall_s
        if not wall and t._t_admit is not None:
            wall = now_mono - t._t_admit
        row = {
            "status": t.status,
            "priority": t.spec.priority,
            "weight": t.spec.weight,
            "trials": t.trials,
            "batches": t.batches,
            "ticks": t.ticks,
            "vtime": round(t.vtime, 3),
            "trials_per_s": (round(t.trials / wall, 2) if wall > 0
                             else 0.0),
            "queue_latency_s": round(t.queue_latency_s, 3),
            "failures": t.failures,
            "kills": t.kills,
            "rc": t.rc,
        }
        if t.orch is not None:
            hws, eta, lanes = _convergence(t.orch)
            row["halfwidth"] = hws
            # per-lane cumulative counts: the gateway's sharded-merge
            # fold consumes these live (stopping.merged_fold) — tallies
            # are a few ints per lane, so the snapshot stays small
            row["lanes"] = lanes
            # the half-width-trajectory ETA: trials still needed to
            # reach the stopping rule's target, plus its projections
            # onto scheduling quanta and wall seconds (the deadline-
            # estimate inputs of the federation gateway)
            row["eta_trials"] = round(eta, 1)
            per_tick = t.trials / t.ticks if t.ticks > 0 else 0.0
            row["eta_ticks"] = (round(eta / per_tick, 1)
                                if per_tick > 0 else None)
            row["eta_s"] = (round(eta / row["trials_per_s"], 2)
                            if row["trials_per_s"] > 0 else None)
        tenants[name] = row
    cs = exec_cache.cache().stats()
    fleet = {
        "ticks": sched.ticks,
        "tenants": len(sched.tenants),
        "by_status": sched._by_status(),
        "fairness_index": round(sched.fairness_index(), 4),
        "depth_budget": sched.depth_budget,
        "cache_compiled": cs["compiled"],
        "cache_reused": cs["reused"],
        "cache_hit_rate": round(
            cs["reused"] / max(cs["reused"] + cs["compiled"], 1), 4),
        "journal_depth": (sched._journal.since_compact
                          if sched._journal is not None else 0),
        "recoveries": sched.recoveries,
        "quarantined": sum(1 for t in sched.tenants.values()
                           if t.status == "quarantined"),
        "pruned": sum(1 for t in sched.tenants.values()
                      if t.status == "pruned"),
        "evicted": sum(1 for t in sched.tenants.values()
                       if t.status == "evicted"),
    }
    return {"schema": 1, "tick": sched.ticks, "wall_time": clock.now(),
            "tenants": tenants, "fleet": fleet}


def _label_escape(v) -> str:
    """Prometheus label-value escaping (exposition format: backslash,
    double quote and newline must be escaped — an unescaped tenant name
    would make the scraper reject the whole exposition)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(snap: dict) -> str:
    """Prometheus text exposition (gauge-only) of one snapshot."""
    lines = []

    def gauge(name: str, value, labels: dict | None = None,
              help_: str = ""):
        full = f"{_PROM_NS}_{name}"
        if help_:
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
        lab = ""
        if labels:
            body = ",".join(f'{k}="{_label_escape(v)}"'
                            for k, v in sorted(labels.items()))
            lab = "{" + body + "}"
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        lines.append(f"{full}{lab} {v:g}")

    fleet = snap.get("fleet", {})
    gauge("ticks", fleet.get("ticks", 0),
          help_="scheduling quanta dispatched fleet-wide")
    gauge("fairness_index", fleet.get("fairness_index", 1.0),
          help_="Jain index over weight-normalized trials served")
    gauge("cache_hit_rate", fleet.get("cache_hit_rate", 0.0),
          help_="process-wide executable-cache hit rate")
    gauge("journal_depth", fleet.get("journal_depth", 0),
          help_="write-ahead journal records since last compaction")
    gauge("recoveries", fleet.get("recoveries", 0),
          help_="hard-kill recoveries survived")
    gauge("tenants_quarantined", fleet.get("quarantined", 0),
          help_="poison tenants parked in durable quarantine")
    # metric-family-OUTER, tenant-inner: the exposition format requires
    # every sample of one family contiguous under a single HELP/TYPE —
    # interleaving per tenant makes promtool reject the whole document
    tenants = sorted(snap.get("tenants", {}).items())
    families = dict(
        trials="trials served", trials_per_s="serving rate",
        ticks="scheduling quanta", vtime="fair-share virtual time",
        queue_latency_s="submit-to-admission seconds",
        failures="tick/elaboration exceptions",
        eta_trials="half-width-trajectory trials still needed",
        eta_ticks="scheduling quanta to projected convergence",
        eta_s="seconds to projected convergence")
    for key, hp in families.items():
        first = True
        for name, row in tenants:
            gauge(f"tenant_{key}", row.get(key, 0), {"tenant": name},
                  help_=hp if first else "")
            first = False
    first = True
    for name, row in tenants:
        for lane, hw in sorted((row.get("halfwidth") or {}).items()):
            gauge("tenant_halfwidth", hw, {"tenant": name, "lane": lane},
                  help_="live Wilson half-width" if first else "")
            first = False
    return "\n".join(lines) + "\n"


def publish(outdir: str, sched) -> dict:
    """Snapshot + write both surfaces atomically; returns the snapshot.

    Atomic means RENAME-atomic only — readers racing the scheduler never
    see a torn document — but deliberately UNSYNCED: publish runs on
    every scheduler tick, the snapshot is overwritten by the next tick,
    and an fsync per tick would serialize disk latency into the dispatch
    hot loop for durability nobody needs (crash recovery reads the WAL,
    never metrics)."""
    import json

    snap = snapshot(sched)
    os.makedirs(outdir, exist_ok=True)
    tmp = os.path.join(outdir, METRICS_JSON + ".tmp")
    with open(tmp, "w") as f:
        # graftlint: allow-raw-write -- per-tick metrics snapshot:
        # atomic rename, deliberately unsynced (overwritten next tick;
        # a per-tick fsync would stall the scheduling loop, and crash
        # recovery reads the journal, never this file)
        json.dump(snap, f, default=str)
    os.replace(tmp, os.path.join(outdir, METRICS_JSON))
    prom = prometheus_text(snap)
    tmp = os.path.join(outdir, METRICS_PROM + ".tmp")
    with open(tmp, "w") as f:
        f.write(prom)
    os.replace(tmp, os.path.join(outdir, METRICS_PROM))
    return snap


def pool_prometheus_text(pool: dict) -> str:
    """Prometheus exposition of the gateway's pool ledger
    (``Gateway.pool_status()`` — pure WAL-derived state: the gauges
    below are a rendering of the journaled ``pool_scale_up`` /
    ``pool_retire_begin`` / ``pool_retire_done`` records, never a
    second count of pod processes)."""
    lines = []

    def gauge(name: str, value, labels: dict | None = None,
              help_: str = ""):
        full = f"{_PROM_NS}_{name}"
        if help_:
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
        lab = ""
        if labels:
            body = ",".join(f'{k}="{_label_escape(v)}"'
                            for k, v in sorted(labels.items()))
            lab = "{" + body + "}"
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        lines.append(f"{full}{lab} {v:g}")

    gauge("pool_size", pool.get("size", 0),
          help_="pods in the gateway's journaled pool ledger")
    gauge("pool_live", pool.get("live", 0),
          help_="pods eligible for placement (not dead, not retiring)")
    gauge("pool_pending_scale_decisions",
          pool.get("pending_scale_decisions", 0),
          help_="journaled pool transitions not yet completed "
                "(retires begun without a pool_retire_done)")
    gauge("pool_scale_seq", pool.get("scale_seq", 0),
          help_="journaled scale ordinal (pool WAL records so far)")
    first = True
    for pod, rounds in sorted(
            (pool.get("retire_drain_rounds") or {}).items()):
        gauge("pool_retire_drain_rounds", rounds, {"pod": pod},
              help_="federation rounds from pool_retire_begin to "
                    "pool_retire_done" if first else "")
        first = False
    return "\n".join(lines) + "\n"


def publish_pool(outdir: str, pool: dict) -> None:
    """Write the pool ledger's observability surfaces (rename-atomic,
    deliberately unsynced like ``publish`` — recovery replays the
    gateway WAL, never these files)."""
    import json

    os.makedirs(outdir, exist_ok=True)
    tmp = os.path.join(outdir, POOL_JSON + ".tmp")
    with open(tmp, "w") as f:
        # graftlint: allow-raw-write -- per-round pool snapshot: atomic
        # rename, deliberately unsynced (overwritten next round; crash
        # recovery replays the gateway WAL, never this file)
        json.dump(pool, f, default=str)
    os.replace(tmp, os.path.join(outdir, POOL_JSON))
    tmp = os.path.join(outdir, POOL_PROM + ".tmp")
    with open(tmp, "w") as f:
        f.write(pool_prometheus_text(pool))
    os.replace(tmp, os.path.join(outdir, POOL_PROM))


def read(outdir: str) -> dict:
    """Load the latest snapshot (``tools/obs.py --tail``)."""
    import json

    with open(os.path.join(outdir, METRICS_JSON)) as f:
        return json.load(f)


def read_pool(outdir: str) -> dict:
    """Load the latest pool-ledger surface (``GET /pool``)."""
    import json

    with open(os.path.join(outdir, POOL_JSON)) as f:
        return json.load(f)
