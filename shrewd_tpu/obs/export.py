"""Trace exporters: Perfetto/Chrome ``trace_event`` JSON, stream
normalization, and text summaries.

The Chrome trace-event format (also loaded by Perfetto's legacy
importer) renders the async pipeline timeline the tracer records:
span events (``ph`` B/E) become *async* begin/end pairs — overlapping
in-flight intervals draw as parallel tracks instead of a malformed
stack — instants stay instants, counters stay counters.  Lanes:

- **pid** = tenant (``args.tenant``; events with no tenant land in the
  shared "campaign" process) — the per-tenant lanes of fleet mode;
- **tid** = ``sp/structure`` when the event carries campaign
  coordinates, else the event category — one thread track per campaign
  lane (dispatch, integrity, chaos, fleet, ...).

Both are assigned in first-seen order (deterministic: the stream itself
is deterministic) and named via metadata events.

``normalize`` strips the only wall-clock-bearing fields (``ts``/
``dur``) so byte-identity of two runs' streams is checkable
(``canonical_bytes``); events with no timestamp export with their
deterministic ``seq`` as the time axis, so a clock-free trace still
renders in order.

Import discipline: stdlib-only.
"""

from __future__ import annotations

import json

#: trace-event phases the tracer emits -> the async phases exported
_ASYNC = {"B": "b", "E": "e"}


def normalize(events: list[dict]) -> list[dict]:
    """Timestamp-normalized view: everything except ``ts``/``dur`` —
    exactly the deterministic identity of the stream."""
    return [{k: v for k, v in ev.items() if k not in ("ts", "dur")}
            for ev in events]


def canonical_bytes(events: list[dict]) -> bytes:
    """Canonical serialization of the normalized stream (sorted keys,
    tight separators): the byte-identity comparison surface of the
    trace-determinism tests."""
    return json.dumps(normalize(events), sort_keys=True,
                      separators=(",", ":"), default=str).encode()


def _lane(ev: dict) -> str:
    a = ev.get("args", {})
    sp, st = a.get("sp"), a.get("structure")
    if sp is not None and st is not None:
        return f"{sp}/{st}"
    return ev.get("cat", "events")


def _span_id(ev: dict) -> str:
    """Deterministic async-pair id from semantic coordinates: B and E of
    one span carry the same name+coords, so they get the same id."""
    a = ev.get("args", {})
    parts = [ev.get("name", "")]
    for key in ("tenant", "sp", "structure", "b0", "batch_id", "seq_no"):
        if key in a:
            parts.append(f"{key}={a[key]}")
    return ":".join(parts)


def to_trace_event(events: list[dict]) -> dict:
    """Chrome/Perfetto ``trace_event`` document for the event stream."""
    out: list[dict] = []
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    t0 = None
    for ev in events:
        ts = ev.get("ts")
        if ts is not None:
            t0 = ts if t0 is None else min(t0, ts)

    def pid_of(tenant: str) -> int:
        if tenant not in pids:
            pids[tenant] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name",
                        "pid": pids[tenant], "tid": 0,
                        "args": {"name": tenant}})
        return pids[tenant]

    def tid_of(pid: int, lane: str) -> int:
        key = (pid, lane)
        if key not in tids:
            tids[key] = len(tids) + 1
            out.append({"ph": "M", "name": "thread_name",
                        "pid": pid, "tid": tids[key],
                        "args": {"name": lane}})
        return tids[key]

    for ev in events:
        tenant = str(ev.get("args", {}).get("tenant", "campaign"))
        pid = pid_of(tenant)
        tid = tid_of(pid, _lane(ev))
        ts = ev.get("ts")
        # clock-free traces render on the deterministic seq axis (µs
        # ticks); timed ones on microseconds from the earliest event
        us = (float(ev["seq"]) if ts is None
              else (ts - (t0 or 0.0)) * 1e6)
        ph = ev.get("ph", "i")
        rec = {"name": ev.get("name", ""), "cat": ev.get("cat", ""),
               "pid": pid, "tid": tid, "ts": us,
               "args": dict(ev.get("args", {}))}
        if ph in _ASYNC:
            rec["ph"] = _ASYNC[ph]
            rec["id"] = _span_id(ev)
        elif ph == "C":
            rec["ph"] = "C"
            val = rec["args"].pop("value", 0)
            rec["args"] = {ev.get("name", "value"): val}
        else:
            rec["ph"] = "i"
            rec["s"] = "t"
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def summarize(events: list[dict]) -> dict:
    """Counts + span statistics: events by name/category, span
    wall-durations (where both ends carried timestamps), and the
    distinct tenants/lanes seen — ``tools/obs.py --summarize``."""
    by_name: dict[str, int] = {}
    by_cat: dict[str, int] = {}
    tenants: set = set()
    lanes: set = set()
    open_spans: dict[str, float | None] = {}
    durs: dict[str, list[float]] = {}
    for ev in events:
        by_name[ev["name"]] = by_name.get(ev["name"], 0) + 1
        by_cat[ev.get("cat", "")] = by_cat.get(ev.get("cat", ""), 0) + 1
        a = ev.get("args", {})
        if "tenant" in a:
            tenants.add(str(a["tenant"]))
        lanes.add(_lane(ev))
        ph = ev.get("ph")
        if ph == "B":
            open_spans[_span_id(ev)] = ev.get("ts")
        elif ph == "E":
            t_b = open_spans.pop(_span_id(ev), None)
            ts = ev.get("ts")
            if t_b is not None and ts is not None:
                durs.setdefault(ev["name"], []).append(ts - t_b)
    span_stats = {
        name: {"count": len(ds),
               "total_s": round(sum(ds), 6),
               "max_s": round(max(ds), 6)}
        for name, ds in sorted(durs.items())}
    return {"events": sum(by_name.values()),
            "by_name": dict(sorted(by_name.items())),
            "by_cat": dict(sorted(by_cat.items())),
            "tenants": sorted(tenants),
            "lanes": sorted(lanes),
            "spans": span_stats,
            "unclosed_spans": len(open_spans)}


def render_text(events: list[dict], width: int = 100) -> str:
    """Human-readable timeline of an event stream / flight-recorder
    window: one line per event, seq-ordered, with span nesting marks."""
    lines = []
    for ev in events:
        a = ev.get("args", {})
        coord = " ".join(f"{k}={a[k]}" for k in sorted(a))
        mark = {"B": "+", "E": "-", "C": "#"}.get(ev.get("ph"), ".")
        ts = ev.get("ts")
        stamp = f"{ts:.6f}" if ts is not None else f"@{ev['seq']}"
        line = (f"{ev['seq']:>6} {stamp:>14} {mark} "
                f"{ev.get('cat', ''):<10} {ev['name']:<24} {coord}")
        lines.append(line[:width])
    return "\n".join(lines)
