"""The process-wide tracer + flight recorder (see package docstring).

Event model — one flat dict per event, JSON-able by construction::

    {"seq": 17,                  # per-tracer append ordinal (deterministic)
     "name": "batch",            # what happened
     "cat": "dispatch",          # which subsystem lane
     "ph": "B" | "E" | "i" | "C",  # span begin/end, instant, counter
     "args": {...},              # semantic coordinates (sp, structure,
                                 #  batch_id, tenant, seq, ...) — identity
     "ts": 12.34 | None}         # obs.clock timestamp (None = clock off)

Determinism contract: ``seq``, ``name``, ``cat``, ``ph`` and ``args``
are pure functions of campaign coordinates and host control flow, so two
identical runs emit byte-identical streams once ``ts``/``dur`` are
stripped (``obs.export.normalize``); the trace-determinism tests pin it.
Emission never reads PRNG state, never branches campaign control flow,
and holds no locks around device work — tracing on vs. off is
bit-identical in every tally (also pinned).

The **disabled tracer is a no-op constant**: ``tracer()`` returns the
module-level ``NULL_TRACER`` singleton whose methods are empty and whose
``span``/``scope`` return a shared reusable null context manager — no
allocation, no branching on the caller side, ≈zero overhead (pinned in
``bench.py``'s ``obs_overhead`` stage).

The **flight recorder** is the tracer's bounded ring: ``flight_dump``
writes the recent-event window atomically (``resilience.
write_json_atomic``) to ``<outdir>/flightrec.json`` with the abnormal-
exit reason, and ``set_flight_path``/``maybe_flight_dump`` let seams
that know no outdir (the chaos hard-kill path) dump to a pre-registered
location before the process dies.
"""

from __future__ import annotations

from collections import deque

from shrewd_tpu.obs import clock

#: default bounded-ring capacity (events kept for the flight recorder);
#: the cap bounds memory AND flight-dump size, never correctness — the
#: dropped count is part of every dump, so truncation is observable
DEFAULT_RING = 8192

FLIGHT_NAME = "flightrec.json"


class _NullCtx:
    """Reusable no-op context manager (the null tracer's span/scope)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _Scope:
    """Ambient-coordinate scope: merged into every event emitted while
    entered (the scheduler wraps each tenant tick so nested seams —
    exec cache, watchdog, integrity — land in that tenant's lane
    without threading tenant identity through every call)."""

    __slots__ = ("_tracer", "_coords", "_saved")

    def __init__(self, tracer, coords):
        self._tracer = tracer
        self._coords = coords
        self._saved = None

    def __enter__(self):
        self._saved = self._tracer._scope
        merged = dict(self._saved)
        merged.update(self._coords)
        self._tracer._scope = merged
        return self

    def __exit__(self, *exc):
        self._tracer._scope = self._saved
        return False


class _Span:
    """Context-manager span: ``B`` on enter, ``E`` on exit (same name/
    cat/coords, so exporters pair them without object identity)."""

    __slots__ = ("_tracer", "_name", "_cat", "_coords")

    def __init__(self, tracer, name, cat, coords):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._coords = coords

    def __enter__(self):
        self._tracer.emit(self._name, cat=self._cat, ph="B", **self._coords)
        return self

    def __exit__(self, *exc):
        self._tracer.emit(self._name, cat=self._cat, ph="E", **self._coords)
        return False


class _NullTracer:
    """The disabled tracer: a no-op constant.  Every counter the stats
    bridge reads exists (zeros), every method is empty, and the context
    managers are one shared reusable object."""

    __slots__ = ()

    enabled = False
    emitted = 0
    dropped = 0
    flight_dumps = 0
    by_name: dict = {}
    flight_path = None

    def emit(self, name, cat="campaign", ph="i", **coords) -> None:
        pass

    def counter(self, name, value, cat="campaign", **coords) -> None:
        pass

    def span(self, name, cat="campaign", **coords):
        return _NULL_CTX

    def scope(self, **coords):
        return _NULL_CTX

    def snapshot(self) -> list:
        return []

    def set_flight_path(self, path) -> None:
        pass

    def flight_dump(self, path, reason, **extra) -> None:
        pass

    def maybe_flight_dump(self, reason, **extra) -> None:
        pass


class Tracer:
    """The live tracer: bounded ring + append counters + flight dump.

    Emission is append-only onto a ``deque`` (GIL-atomic; dispatch is
    single-threaded per process, and the few background threads —
    heartbeats, reprobe — do not emit)."""

    enabled = True

    def __init__(self, ring: int = DEFAULT_RING, timestamps: bool = True):
        self._ring: deque = deque(maxlen=int(ring))
        self._timestamps = bool(timestamps)
        self._scope: dict = {}
        self.seq = 0           # next event ordinal (deterministic)
        self.emitted = 0
        self.dropped = 0       # ring overwrites (emitted - retained)
        self.by_name: dict[str, int] = {}
        self.flight_path: str | None = None
        self.flight_dumps = 0

    # --- emission -------------------------------------------------------

    def emit(self, name, cat="campaign", ph="i", **coords) -> None:
        """One structured event.  ``coords`` are the event's semantic
        identity — campaign coordinates only (the determinism contract);
        ambient scope coordinates merge underneath them."""
        args = dict(self._scope)
        if coords:
            args.update(coords)
        ev = {"seq": self.seq, "name": str(name), "cat": str(cat),
              "ph": str(ph), "args": args,
              "ts": clock.monotonic() if self._timestamps else None}
        self.seq += 1
        self.emitted += 1
        self.by_name[ev["name"]] = self.by_name.get(ev["name"], 0) + 1
        if len(self._ring) == self._ring.maxlen:
            self.dropped += 1
        self._ring.append(ev)

    def counter(self, name, value, cat="campaign", **coords) -> None:
        self.emit(name, cat=cat, ph="C", value=value, **coords)

    def span(self, name, cat="campaign", **coords):
        return _Span(self, name, cat, coords)

    def scope(self, **coords):
        return _Scope(self, coords)

    # --- inspection -----------------------------------------------------

    def snapshot(self) -> list[dict]:
        """The retained event window, oldest first (copies the ring, not
        the event dicts — callers must not mutate events)."""
        return list(self._ring)

    # --- the flight recorder --------------------------------------------

    def set_flight_path(self, path: str | None) -> None:
        """Pre-register where an abnormal-exit dump lands (the chaos
        hard-kill seam knows no outdir at fire time)."""
        self.flight_path = path

    def flight_dump(self, path: str, reason: str, **extra) -> None:
        """Dump the ring atomically to ``path`` with the abnormal-exit
        reason.  Atomic (tmp + fsync + rename + dir-fsync) because the
        dump races the very failure it documents."""
        from shrewd_tpu import resilience as resil

        doc = {"reason": str(reason), "coords": dict(extra),
               "emitted": self.emitted, "dropped": self.dropped,
               "events": self.snapshot()}
        resil.write_json_atomic(path, doc)
        self.flight_dumps += 1

    def maybe_flight_dump(self, reason: str, **extra) -> None:
        """Dump to the pre-registered flight path, if any (best-effort:
        an observability write must never turn one failure into two)."""
        if not self.flight_path:
            return
        try:
            self.flight_dump(self.flight_path, reason, **extra)
        except OSError:
            pass


NULL_TRACER = _NullTracer()

_TRACER = NULL_TRACER


def tracer():
    """The process-wide tracer (the ``NULL_TRACER`` constant while
    tracing is disabled — the zero-overhead default)."""
    return _TRACER


def enable(ring: int = DEFAULT_RING, timestamps: bool = True) -> Tracer:
    """Install a FRESH live tracer (event ordinals restart at 0, so a
    traced run's stream is self-contained) and return it."""
    global _TRACER
    _TRACER = Tracer(ring=ring, timestamps=timestamps)
    return _TRACER


def disable():
    """Back to the no-op constant; returns the tracer that was live (so
    callers can still export/inspect its window)."""
    global _TRACER
    prev = _TRACER
    _TRACER = NULL_TRACER
    return prev


def flight_dump(outdir: str | None, reason: str, **extra) -> str | None:
    """Dump the live tracer's ring to ``<outdir>/flightrec.json``;
    no-op (None) when tracing is disabled or there is no outdir.
    Best-effort like ``maybe_flight_dump``: every caller sits on a
    failure path (quarantine, abort) or in the scheduler loop, and an
    observability write must never turn one failure into two — a full
    disk loses the dump, not the fleet."""
    t = _TRACER
    if not t.enabled or not outdir:
        return None
    import os

    path = os.path.join(outdir, FLIGHT_NAME)
    try:
        os.makedirs(outdir, exist_ok=True)
        t.flight_dump(path, reason, **extra)
    except OSError:
        return None
    return path
