"""Deterministic observability: flight-recorder tracing + live fleet metrics.

The campaign stack is built on one invariant — every result is a pure
function of frozen campaign coordinates — and the reference answers
"what happened in this run" with its stats dump and O3PipeView/debug-flag
traces (PAPER §stats; ``trace/pipeview.py`` mirrors the instruction-level
side).  This package is the *campaign-level* analog, built so that
observing a run can never perturb it:

- ``obs.clock`` — the ONE sanctioned wall-clock seam.  Instrumented
  modules read time only through it (graftlint GL106), so timestamps
  attach to events without wall clock leaking into any trigger or
  scheduling decision (the GL102 contract).
- ``obs.trace`` — a process-wide ``Tracer`` emitting structured events
  at every load-bearing seam (dispatch/materialize, exec-cache
  hit/miss/compile, integrity verdicts, quarantine→recovery, chaos
  injections, watchdog arms/fires, lease claims, scheduler decisions,
  journal appends).  Event identity derives from semantic coordinates
  (batch_id, super-interval ordinal, tenant name, journal seq) — never
  wall clock or object identity — so two identical runs produce
  byte-identical streams after timestamp normalization.  The disabled
  tracer is a no-op constant (≈zero overhead, pinned in bench).
- ``obs.export`` — Chrome/Perfetto ``trace_event`` JSON (the async
  pipeline timeline: dispatch vs. materialize overlap, per-tenant
  lanes), stream normalization, and text summaries (``tools/obs.py``).
- ``obs.metrics`` — atomic per-tick fleet metrics snapshots
  (``metrics.json`` + Prometheus text exposition) published by the
  resident scheduler.

The **flight recorder** is the tracer's bounded ring dumped atomically to
``outdir/flightrec.json`` on every abnormal exit — integrity abort
(rc 3), escalation abort, tenant quarantine, fleet hard-kill — so "why
did this tenant quarantine" is answerable post-hoc from one artifact.

Import discipline: jax-free (pure host-side bookkeeping; instrumented
modules include the jax-free-at-import campaign layers).
"""

from shrewd_tpu.obs import clock  # noqa: F401
from shrewd_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER, Tracer, disable, enable, flight_dump, tracer)
