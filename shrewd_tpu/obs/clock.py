"""The ONE sanctioned clock seam for instrumented modules.

Every deterministic layer of this codebase promises that wall clock never
enters a trigger, schedule or replay decision (graftlint GL102), yet
observability *needs* timestamps — perf ledgers, event times, queue
latencies.  The resolution is a single seam: instrumented modules read
time only through this module (enforced by graftlint GL106), so

- every clock read in an instrumented region is auditable at one import
  site rather than scattered ``time.*`` calls;
- tests can install a fake clock (``install``) and get fully
  deterministic timestamps — the trace-determinism tests normalize
  timestamps away, and the fake clock proves nothing else leaks;
- the no-wall-clock-in-decisions contract stays checkable: GL102 keeps
  banning ``time.time`` in deterministic modules, and this module is the
  one place that carries the waiver.

Import discipline: stdlib-only (the seam must be importable everywhere,
including the jax-free supervisor processes).
"""

from __future__ import annotations

import time

# test-seam overrides (None = the real clocks).  ``install`` swaps both
# at once so a fake clock cannot mix real and fake time bases.
_mono_override = None
_wall_override = None


def monotonic() -> float:
    """Monotonic seconds — interval/perf timing (never schedule-bearing)."""
    if _mono_override is not None:
        return _mono_override()
    return time.monotonic()


def now() -> float:
    """Wall-clock epoch seconds — event timestamps and cross-process
    latency observability ONLY (the GL102 contract: no trigger, schedule
    or replay decision may consume this)."""
    if _wall_override is not None:
        return _wall_override()
    # graftlint: allow-wall-clock -- this IS the sanctioned wall-clock
    # seam: the one audited read every instrumented module routes
    # through (GL106), used only for timestamps/latency observability
    return time.time()


def install(mono=None, wall=None) -> None:
    """Install fake clocks (tests): ``mono``/``wall`` are zero-arg
    callables returning seconds.  ``None`` leaves that clock real."""
    global _mono_override, _wall_override
    _mono_override = mono
    _wall_override = wall


def reset() -> None:
    """Restore the real clocks (test teardown)."""
    install(None, None)
