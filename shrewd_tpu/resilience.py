"""Backend resilience: watchdog, retry/backoff, degradation ladder, budget.

The round-5 session produced zero chip numbers because the TPU tunnel was
wedged and nothing in the framework could (a) notice a wedged dispatch in
bounded time, (b) retry it when the backend healed, or (c) finish the
campaign on a lower tier while accounting for the substitution.  This module
is that missing layer.  It deliberately mirrors the reference's stance: the
CheckerCPU (``src/cpu/checker/cpu.hh``) is an *always-available oracle*, but
the reference never silently swaps it in for the timing CPU — every tier
substitution here is counted, reported, and budgeted.

Four pieces, composable and individually testable:

- ``DeviceWatchdog`` — bounded-time dispatch.  A jitted device call runs on
  a dedicated dispatch thread; if it does not complete within the timeout
  the watchdog abandons that thread (a C-level wedge cannot be interrupted,
  only orphaned — the same reasoning as bench.py's self-exiting probe) and
  raises ``DispatchTimeout``.
- ``BackoffPolicy`` — exponential backoff with jitter for re-dispatch.
  Host-side only: backoff timing never influences sampled faults.
- ``ReprobeQueue`` — a session-long background re-probe loop.  Deferred
  work (e.g. the TPU bench attempt) is enqueued and fires at the *first
  healthy window* instead of a fixed retry schedule.
- ``EscalationBudget`` + ``ResilientDispatcher`` — the degradation ladder
  device → CPU-JAX → host oracle.  Every batch re-dispatched down the
  ladder reuses the *same frozen PRNG keys*, so tallies are bit-identical
  regardless of where they ran (every tier consumes ``keys`` and nothing
  else); the budget makes the device/host mix a first-class campaign stat
  with a configurable threshold.

Import discipline: this module must stay importable WITHOUT jax (bench.py's
supervisor uses the watchdog/backoff/re-probe pieces and must never touch a
backend); jax is imported lazily inside the fallback-tier builders only.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
import warnings
from typing import Callable, NamedTuple

import numpy as np

from shrewd_tpu.obs import clock as obs_clock
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.utils import debug
from shrewd_tpu.utils.config import ConfigObject, Param

debug.register_flag("Resilience", "watchdog / retry / degradation ladder")

# The degradation ladder, healthiest first.  Index into per-tier tallies —
# NEVER reorder (checkpoints and stats record tier indices).
TIERS = ("device", "cpu", "oracle")
TIER_DEVICE, TIER_CPU, TIER_ORACLE = range(3)


class BackendError(RuntimeError):
    """A dispatch failed in a way worth retrying or degrading over."""


class DispatchTimeout(BackendError):
    """The watchdog declared an in-flight dispatch wedged."""


class LadderExhausted(BackendError):
    """Every tier of the degradation ladder failed for one batch."""


class ResilienceConfig(ConfigObject):
    """Knobs for the resilience layer (a ``CampaignPlan`` child, so every
    campaign's failure posture is reproducible from its config dump)."""

    dispatch_timeout = Param(float, 0.0,
                             "seconds per device dispatch before the "
                             "watchdog declares it wedged (0 = no watchdog; "
                             "first-compile on a real chip needs minutes)")
    max_retries = Param(int, 2,
                        "re-dispatch attempts per tier before degrading",
                        check=lambda v: v >= 0)
    backoff_base = Param(float, 0.05, "first-retry backoff seconds",
                         check=lambda v: v >= 0)
    backoff_max = Param(float, 5.0, "backoff ceiling seconds")
    backoff_jitter = Param(float, 0.25,
                           "uniform jitter fraction on each backoff delay",
                           check=lambda v: 0 <= v <= 1)
    escalation_threshold = Param(float, 0.05,
                                 "max fraction of trials allowed off the "
                                 "device tier before the run is flagged",
                                 check=lambda v: 0 <= v <= 1)
    escalation_action = Param(str, "warn",
                              "off | warn | abort when the escalation rate "
                              "exceeds the threshold",
                              check=lambda v: v in ("off", "warn", "abort"))
    probe_interval = Param(float, 30.0,
                           "background re-probe cadence seconds",
                           check=lambda v: v > 0)
    allow_cpu = Param(bool, True, "permit the CPU-JAX fallback tier")
    allow_oracle = Param(bool, True,
                         "permit the host-oracle fallback tier")


class BackoffPolicy:
    """Exponential backoff with uniform jitter (the classic retry shape;
    the reference has no analog because a wedged EventQueue just deadlocks).

    ``delay(attempt)`` is pure given the instance's RNG stream; ``sleep``
    goes through an injectable sleeper so tests never wall-wait."""

    def __init__(self, base: float = 0.05, cap: float = 5.0,
                 jitter: float = 0.25, seed: int | None = None,
                 sleeper: Callable[[float], None] = time.sleep):
        self.base = float(base)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._sleep = sleeper

    @classmethod
    def from_config(cls, cfg: ResilienceConfig,
                    sleeper: Callable[[float], None] = time.sleep
                    ) -> "BackoffPolicy":
        return cls(cfg.backoff_base, cfg.backoff_max, cfg.backoff_jitter,
                   sleeper=sleeper)

    def delay(self, attempt: int) -> float:
        d = min(self.base * (2 ** max(attempt, 0)), self.cap)
        if self.jitter:
            d *= 1 + self._rng.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        if d:
            self._sleep(d)
        return d


class DeviceWatchdog:
    """Run dispatches with a hard completion deadline.

    A wedged jitted call blocks inside C code where no Python exception can
    reach it, so the watchdog's only safe move on timeout is to *abandon*
    the dispatch thread (daemon; it dies with the process) and surface
    ``DispatchTimeout`` to the caller — exactly the posture of bench.py's
    self-exiting tunnel probe, inverted to stay in-process.  ``timeout=0``
    disables the thread hop entirely (zero overhead on the hot path)."""

    #: abandoned-thread count past which the watchdog warns: each wedged
    #: dispatch orphans one daemon thread (plus whatever C-level state it
    #: pins), so unbounded accumulation is a slow leak worth surfacing
    leak_warn_cap = 8

    def __init__(self, timeout: float = 0.0, name: str = "device"):
        self.timeout = float(timeout)
        self.name = name
        self.healthy = True
        self.dispatches = 0
        self.timeouts = 0
        self.chaos = None            # optional chaos.ChaosEngine (wedge hook)
        self._abandoned: list[threading.Thread] = []
        self._leak_warned = False

    @property
    def leaked_threads(self) -> int:
        """Abandoned dispatch threads still alive (a wedged thread that
        eventually finishes drops off; one that never does is a leak)."""
        self._abandoned = [t for t in self._abandoned if t.is_alive()]
        return len(self._abandoned)

    def call(self, fn: Callable, *args, timeout: float | None = None):
        """``fn(*args)`` bounded by ``timeout`` (default: the instance's).

        Raises ``DispatchTimeout`` on deadline; any exception from ``fn``
        propagates unchanged (the retry loop decides what is retryable)."""
        tmo = self.timeout if timeout is None else float(timeout)
        self.dispatches += 1
        if self.chaos is not None and tmo > 0:
            # chaos wedge hook (only on deadline-bearing dispatches — the
            # ladder also routes fallback tiers through here with tmo=0,
            # which must neither consume nor misreport the wedge):
            # substitute a dispatch that sleeps past the deadline, so the
            # injected fault exercises the REAL timeout machinery (thread
            # hop, abandonment, DispatchTimeout) rather than a synthetic
            # raise.  The injected call runs under the spec's own short
            # deadline so the campaign's real deadline can stay generous
            # enough for first-compile dispatches.
            wedged = self.chaos.take_wedge(tmo)
            if wedged is not None:
                fn, args, tmo = wedged["fn"], (), wedged["deadline"]
        if tmo <= 0:
            return fn(*args)
        # a plain daemon thread, NOT ThreadPoolExecutor: pool workers are
        # non-daemon and concurrent.futures' atexit hook joins them, so a
        # wedged dispatch would block interpreter exit forever
        box: dict = {}
        done = threading.Event()

        def _runner():
            try:
                box["out"] = fn(*args)
            except BaseException as e:  # noqa: BLE001 — re-raised in caller
                box["err"] = e
            finally:
                done.set()

        th = threading.Thread(
            target=_runner, daemon=True,
            name=f"watchdog-{self.name}-{self.dispatches}")
        th.start()
        if not done.wait(tmo):
            self.timeouts += 1
            self.healthy = False
            obs_trace.tracer().emit(
                "watchdog_fire", cat="resilience", watchdog=self.name,
                timeout_s=round(tmo, 3), dispatch=self.dispatches)
            # the dispatch thread is stuck in C; abandon it (daemon — it
            # dies with the process) and let the caller's ladder decide.
            # Track the orphan: repeated wedges accumulate threads (and
            # whatever backend state they pin), which is a leak worth a
            # stat and, past the cap, a warning.
            self._abandoned.append(th)
            leaked = self.leaked_threads
            if leaked > self.leak_warn_cap and not self._leak_warned:
                self._leak_warned = True
                warnings.warn(
                    f"DeviceWatchdog {self.name}: {leaked} abandoned "
                    f"dispatch threads still alive (cap "
                    f"{self.leak_warn_cap}) — the backend is wedging "
                    "repeatedly; each orphan pins backend state until it "
                    "finishes or the process exits", RuntimeWarning,
                    stacklevel=2)
            debug.dprintf("Resilience",
                          "watchdog %s: dispatch wedged after %.1fs "
                          "(%d threads leaked)", self.name, tmo, leaked)
            raise DispatchTimeout(
                f"{self.name}: dispatch exceeded {tmo:.1f}s") from None
        if "err" in box:
            raise box["err"]
        self.healthy = True
        return box["out"]

    # --- future-based mode (pipelined async dispatch) -------------------
    #
    # The serial loop wraps `block_until_ready(step(...))` in `call`, so
    # the deadline covers dispatch AND completion of one batch.  The
    # pipelined engine dispatches WITHOUT blocking (jax dispatch is async)
    # and only blocks later, when the host is ready to consume the result
    # — so the deadline must be armed at dispatch time and enforced at
    # materialization, or a wedged backend would hide inside the
    # never-awaited in-flight window.

    def arm(self) -> float:
        """Future mode, dispatch side: stamp the moment a dispatch was
        enqueued.  Pass the token to ``call_armed`` at materialization."""
        obs_trace.tracer().emit("watchdog_arm", cat="resilience",
                                watchdog=self.name,
                                dispatch=self.dispatches)
        return obs_clock.monotonic()

    #: minimum materialization grace even when the armed deadline has
    #: fully elapsed while the host did other work: an already-complete
    #: result returns instantly, and a genuinely wedged one still
    #: surfaces as DispatchTimeout in bounded (small) time
    armed_floor = 0.05

    def call_armed(self, fn: Callable, armed_at: float,
                   timeout: float | None = None):
        """Future mode, materialization side: run ``fn()`` (the blocking
        device_get / block_until_ready) under the REMAINING deadline,
        measured from ``armed_at`` — the wedge-detection guarantee of the
        serial loop, preserved without per-batch blocking."""
        tmo = self.timeout if timeout is None else float(timeout)
        if tmo <= 0:
            return self.call(fn, timeout=0.0)
        remaining = tmo - (obs_clock.monotonic() - armed_at)
        return self.call(fn, timeout=max(remaining, self.armed_floor))

    def probe(self, fn: Callable, timeout: float | None = None) -> bool:
        """Health probe: True iff ``fn()`` completes in time without
        raising.  Updates ``healthy``."""
        try:
            self.call(fn, timeout=timeout)
            return True
        except Exception:  # noqa: BLE001 — any failure means unhealthy
            self.healthy = False
            return False


class ReprobeQueue:
    """Session-long background re-probe with deferred work.

    Callers enqueue callbacks with ``defer``; a daemon thread probes the
    backend on a backoff schedule and fires every queued callback at the
    FIRST healthy window (replacing bench.py's fixed probe-retry loop,
    which could only retry at bench start and surrendered to the CPU
    fallback even when the tunnel healed minutes later — VERDICT r4 weak
    #3).  Deferred callbacks run on the probe thread; keep them short or
    have them hand off."""

    def __init__(self, probe_fn: Callable[[], bool],
                 interval: float = 30.0,
                 backoff: BackoffPolicy | None = None):
        self._probe = probe_fn
        self._interval = float(interval)
        self._backoff = backoff
        self._deferred: list[Callable[[], None]] = []
        self._lock = threading.Lock()
        self._healthy = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.probes = 0

    @property
    def healthy(self) -> bool:
        return self._healthy.is_set()

    def start(self) -> "ReprobeQueue":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="reprobe-queue", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            self.probes += 1
            ok = False
            try:
                ok = bool(self._probe())
            except Exception:  # noqa: BLE001 — probe failure = unhealthy
                ok = False
            if ok:
                self._healthy.set()
                self._fire()
                return
            wait = (self._backoff.delay(attempt) if self._backoff
                    else self._interval)
            attempt += 1
            self._stop.wait(wait)

    def _fire(self) -> None:
        with self._lock:
            work, self._deferred = self._deferred, []
        for fn in work:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — one callback must not
                # starve the rest of the queue
                debug.dprintf("Resilience", "deferred callback failed: %s",
                              e)

    def defer(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` at the first healthy window (immediately if already
        healthy)."""
        if self._healthy.is_set():
            fn()
            return
        with self._lock:
            self._deferred.append(fn)
        # a late defer after the probe thread exited healthy still fires
        if self._healthy.is_set():
            self._fire()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until healthy (or timeout); True iff healthy."""
        return self._healthy.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)


class EscalationBudget:
    """Per-tier trial accounting — the 'is this number really a device
    number' ledger.  The r5 SimPoint differential silently escalated 50%
    of trials to the host emulator; with this ledger that run would have
    been flagged at the threshold, not discovered in review."""

    def __init__(self, counts=None):
        self.counts = (np.zeros(len(TIERS), dtype=np.int64)
                       if counts is None
                       else np.asarray(counts, dtype=np.int64).copy())
        if self.counts.shape != (len(TIERS),):
            raise ValueError(f"need {len(TIERS)} tier counters, "
                             f"got shape {self.counts.shape}")

    def record(self, tier: int, n_trials: int) -> None:
        self.counts[tier] += int(n_trials)

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    @property
    def escalated(self) -> int:
        """Trials that did NOT run on the device tier."""
        return int(self.counts[1:].sum())

    def rate(self) -> float:
        return self.escalated / max(self.total, 1)

    def over(self, threshold: float) -> bool:
        return self.total > 0 and self.rate() > threshold

    def to_dict(self) -> dict:
        return {"tier_trials": {t: int(c) for t, c in zip(TIERS, self.counts)},
                "escalation_rate": self.rate()}

    @classmethod
    def from_states(cls, tier_arrays) -> "EscalationBudget":
        b = cls()
        for a in tier_arrays:
            b.counts += np.asarray(a, dtype=np.int64)
        return b


class DispatchResult(NamedTuple):
    tally: np.ndarray                 # (N_OUTCOMES,) int64
    strata: np.ndarray | None         # (N_STRATA, N_OUTCOMES) or None
    tier: int                         # TIERS index that produced the tally
    attempts: int                     # total dispatch attempts consumed


class ResilientDispatcher:
    """Retry + degradation ladder around one campaign's batch dispatch.

    ``tiers`` is an ordered list of ``(tier_index, fn)`` where
    ``fn(keys, stratified) -> (tally, strata|None)``; every fn consumes the
    same frozen PRNG keys, which is the whole bit-identity argument — a
    batch's outcomes are a pure function of its keys on every tier (the
    parity contract tests/test_native_diff.py and tests/test_chunked.py
    pin).  Tier order is descent order; a tier whose retries exhaust marks
    the watchdog unhealthy and falls through to the next."""

    def __init__(self, tiers, config: ResilienceConfig | None = None,
                 watchdog: DeviceWatchdog | None = None,
                 backoff: BackoffPolicy | None = None,
                 device_deadline: bool = True, chaos=None):
        """``device_deadline=False`` when the campaign enforces its own
        per-step deadline (ShardedCampaign built with a watchdog): the
        dispatcher then calls the device tier directly instead of adding a
        second thread hop + timer around the same work.

        ``chaos`` (chaos.ChaosEngine, optional): the deterministic
        fault-injection harness — armed per-tier ``BackendError`` faults
        fire here, exercising the retry/degradation machinery exactly as a
        real backend failure would."""
        if not tiers:
            raise ValueError("need at least one tier")
        self.tiers = list(tiers)
        self.cfg = config if config is not None else ResilienceConfig()
        self.watchdog = (watchdog if watchdog is not None
                         else DeviceWatchdog(self.cfg.dispatch_timeout))
        self.backoff = (backoff if backoff is not None
                        else BackoffPolicy.from_config(self.cfg))
        self.device_deadline = device_deadline
        self.chaos = chaos
        self.retries = 0          # re-dispatches beyond each first attempt
        self.degradations = 0     # tier descents taken

    def sub_ladder(self, below: int) -> "ResilientDispatcher | None":
        """The ladder restricted to tiers strictly below ``below`` (in
        descent order), or None when nothing is left.  Used by the
        integrity layer to re-dispatch a quarantined batch on a tier other
        than the one that produced the corrupt result; watchdog/config/
        backoff are shared so health accounting stays campaign-wide."""
        pos = next((i for i, (t, _) in enumerate(self.tiers) if t == below),
                   None)
        if pos is None or pos + 1 >= len(self.tiers):
            return None
        return ResilientDispatcher(
            self.tiers[pos + 1:], self.cfg, watchdog=self.watchdog,
            backoff=self.backoff, device_deadline=self.device_deadline,
            chaos=self.chaos)

    def tally_batch(self, keys, stratified: bool = False) -> DispatchResult:
        attempts = 0
        errors: list[str] = []
        for pos, (tier, fn) in enumerate(self.tiers):
            # only the device tier goes through the watchdog deadline (and
            # only when the campaign isn't already enforcing its own): the
            # fallbacks are host-owned work that must be allowed to finish
            tmo = (self.cfg.dispatch_timeout
                   if tier == TIER_DEVICE and self.device_deadline else 0.0)
            for attempt in range(self.cfg.max_retries + 1):
                attempts += 1
                if attempt:
                    self.retries += 1
                    self.backoff.sleep(attempt - 1)
                try:
                    if self.chaos is not None:
                        # chaos ladder hook: an armed per-tier fault raises
                        # here, consuming one attempt like a real failure
                        self.chaos.maybe_backend_error(tier)
                    tally, strata = self.watchdog.call(
                        fn, keys, stratified, timeout=tmo)
                    return DispatchResult(
                        np.asarray(tally, dtype=np.int64),
                        None if strata is None
                        else np.asarray(strata, dtype=np.int64),
                        tier, attempts)
                except BackendError as e:
                    errors.append(f"{TIERS[tier]}: {e}")
                    debug.dprintf(
                        "Resilience", "%s dispatch failed "
                        "(attempt %d/%d): %s", TIERS[tier], attempt + 1,
                        self.cfg.max_retries + 1, e)
            if pos + 1 < len(self.tiers):
                self.degradations += 1
                debug.dprintf("Resilience", "degrading %s -> %s",
                              TIERS[tier], TIERS[self.tiers[pos + 1][0]])
        raise LadderExhausted("; ".join(errors)[-500:])


# --------------------------------------------------------------------------
# ladder construction for a ShardedCampaign (jax imported lazily)
# --------------------------------------------------------------------------

def _device_tier(campaign):
    def fn(keys, stratified):
        try:
            if stratified:
                strata = np.asarray(campaign.tally_batch_stratified(keys))
                return strata.sum(axis=0), strata
            return np.asarray(campaign.tally_batch(keys)), None
        except BackendError:
            raise
        except Exception as e:  # noqa: BLE001 — a crashing backend (device
            # lost, RESOURCE_EXHAUSTED, runtime aborted) is the other common
            # failure mode besides the wedge; without this wrap the ladder
            # would only ever engage on watchdog timeouts
            raise BackendError(f"device tier failed: {e}") from e
    return fn


def _cpu_tier(campaign):
    """Lazy CPU-JAX re-dispatch: the same kernel compiled over a
    single-device CPU mesh.  Same keys → same sampled faults → same
    outcomes; only the executing backend changes."""
    state: dict = {}

    def fn(keys, stratified):
        try:
            if "camp" not in state:
                import jax

                from shrewd_tpu.parallel.campaign import ShardedCampaign
                from shrewd_tpu.parallel.mesh import make_mesh
                cpu_mesh = make_mesh(jax.devices("cpu")[:1])
                state["camp"] = ShardedCampaign(
                    campaign.kernel, cpu_mesh, campaign.structure,
                    resolution=campaign.resolution,
                    stratify=campaign.stratify)
            camp = state["camp"]
            if stratified:
                strata = np.asarray(camp.tally_batch_stratified(keys))
                return strata.sum(axis=0), strata
            return np.asarray(camp.tally_batch(keys)), None
        except BackendError:
            raise
        except Exception as e:  # noqa: BLE001 — a broken fallback build is
            # itself a backend failure: descend instead of crashing the run
            raise BackendError(f"cpu tier failed: {e}") from e
    return fn


def _oracle_tier(campaign):
    """Host-oracle re-dispatch: the serial C++ golden kernel (the
    CheckerCPU analog, csrc/) classifies the SAME sampled faults.  Valid
    for TrialKernel campaigns without a VA-space memmap (the native kernel
    has no memmap model); ``oracle_available`` gates construction, and
    tests/test_native_diff.py pins outcome parity per structure."""
    def fn(keys, stratified):
        try:
            import jax

            from shrewd_tpu import native
            from shrewd_tpu.ops import classify as C
            kernel = campaign.kernel
            with jax.default_device(jax.devices("cpu")[0]):
                faults = kernel.sampler(campaign.structure).sample_batch(
                    keys)
                f = [np.asarray(x) for x in faults]
                out = native.golden_trials(
                    kernel.trace, *f, np.asarray(kernel.shadow_cov),
                    compare_regs=kernel.cfg.compare_regs)
                tally = np.bincount(out, minlength=C.N_OUTCOMES
                                    ).astype(np.int64)
                if not stratified:
                    return tally, None
                from shrewd_tpu.ops.trial import N_STRATA
                strata_id = np.asarray(kernel.strata_of(
                    faults, campaign.structure))
                strata = np.zeros((N_STRATA, C.N_OUTCOMES), np.int64)
                np.add.at(strata, (strata_id, out), 1)
                return tally, strata
        except BackendError:
            raise
        except Exception as e:  # noqa: BLE001
            raise BackendError(f"oracle tier failed: {e}") from e
    return fn


def oracle_available(campaign) -> bool:
    """The native golden kernel covers TrialKernel structures only, and
    not the VA-space memmap path (lifted traces trap differently there)."""
    kernel = campaign.kernel
    return (hasattr(kernel, "trace") and hasattr(kernel, "shadow_cov")
            and hasattr(kernel, "sampler")
            and getattr(kernel, "memmap", None) is None)


def dispatcher_for_campaign(campaign, cfg: ResilienceConfig | None = None,
                            watchdog: DeviceWatchdog | None = None,
                            chaos=None) -> ResilientDispatcher:
    """Build the ladder for one ShardedCampaign: device, then CPU-JAX
    (skipped when the mesh already IS the cpu backend — re-dispatching to
    the same platform cannot help), then the host oracle where valid."""
    cfg = cfg if cfg is not None else ResilienceConfig()
    tiers = [(TIER_DEVICE, _device_tier(campaign))]
    dev0 = np.asarray(campaign.mesh.devices).flat[0]
    if cfg.allow_cpu and getattr(dev0, "platform", "cpu") != "cpu":
        tiers.append((TIER_CPU, _cpu_tier(campaign)))
    if cfg.allow_oracle and oracle_available(campaign):
        tiers.append((TIER_ORACLE, _oracle_tier(campaign)))
    # a campaign with its own watchdog enforces the per-step deadline
    # inside tally_batch (around only the pure jitted step, so a late
    # orphaned dispatch has no host side effects to corrupt) — don't
    # stack a second deadline around the same call
    return ResilientDispatcher(
        tiers, cfg, watchdog=watchdog,
        device_deadline=getattr(campaign, "watchdog", None) is None,
        chaos=chaos)


# --------------------------------------------------------------------------
# crash-safe document IO (checkpoint v4 helpers)
# --------------------------------------------------------------------------

def doc_checksum(doc: dict) -> str:
    """Content checksum over everything EXCEPT the checksum field itself,
    canonical-JSON-serialized (sort_keys) so dict order never matters."""
    body = {k: v for k, v in doc.items() if k != "checksum"}
    blob = json.dumps(body, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: ``os.replace`` makes a rename visible, but the
    new directory entry itself lives in the directory's data blocks — on a
    power loss before the directory syncs, the rename can vanish and the
    file with it.  POSIX durability for a rename is file-fsync + rename +
    directory-fsync; this is the third step."""
    fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_json_atomic(path: str, doc: dict) -> None:
    """tmp + fsync + rename + dir-fsync: a crash mid-write can truncate
    only the tmp file, never the live document, and a power loss after the
    rename cannot drop the renamed entry."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))
    notify_durability("rename", path)


# --------------------------------------------------------------------------
# durability-boundary observation (the crashcheck seam)
# --------------------------------------------------------------------------
#
# The crash-point model checker (analysis/crashcheck.py) needs to see
# every instant at which durable state changes — each WAL append, each
# journal compaction, each atomic-rename commit — so it can re-execute
# recovery from the filesystem state at EVERY boundary.  One process-
# wide hook, notified by the durable writers right after their fsync
# lands; None (the default) costs one ``is not None`` check.

_durability_hook = None


def set_durability_hook(fn):
    """Install (``fn``) or clear (``None``) the process-wide durability
    observer; returns the previous hook so shims can nest."""
    global _durability_hook
    prev = _durability_hook
    _durability_hook = fn
    return prev


def notify_durability(event: str, path: str, **meta) -> None:
    """Report one durability boundary (``event`` in append/compact/
    rename) to the installed observer, if any.  Called by the durable
    writers AFTER the bytes are on disk — the boundary is the moment a
    crash could no longer un-happen the write."""
    if _durability_hook is not None:
        _durability_hook(event, path, **meta)


def load_json_verified(path: str) -> dict:
    """Load + checksum-verify a document written by ``write_json_atomic``.
    Raises ``ValueError`` on truncation/corruption/checksum mismatch;
    documents from before checksums (no ``checksum`` field) load as-is."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: truncated or corrupt JSON "
                             f"({e})") from e
    want = doc.get("checksum")
    if want is not None and doc_checksum(doc) != want:
        raise ValueError(f"{path}: checksum mismatch "
                         "(partial write or tampering)")
    return doc
