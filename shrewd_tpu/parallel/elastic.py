"""Elastic multi-host campaigns: heartbeats, batch leases, re-mesh on loss.

The reference's distributed mode has no failure story: one dead gem5 node
wedges the hand-rolled TCP barrier forever (``dev/net/dist_iface.hh:102``,
``util/dist/gem5-dist.sh``), and the naive TPU-native analog inherits the
same fate — with ``jax.distributed`` + a global mesh, a lost or preempted
process stalls every surviving worker inside the next psum collective.

This module is the elastic alternative.  The key move is to stop sharing a
*collective* and share only *work*:

- every worker owns a mesh over **its own local devices** (its psum is
  process-local, so no peer can wedge it — the "re-mesh onto surviving
  devices" is structural: the surviving workers' meshes ARE the surviving
  devices);
- batches are **leased per batch_id** from a shared coordination directory
  (claims are atomic ``os.link`` creations; results are atomic JSON
  documents), so any worker can compute any batch;
- workers announce liveness with **heartbeat files**; a worker that stops
  beating past the timeout is declared lost, its leases are revoked, and
  survivors re-dispatch the orphaned batch_ids — on the same frozen PRNG
  keys, so the recovered tally is bit-identical to an undisturbed run
  (the same discipline as the resilience ladder and the integrity
  quarantine: a batch's outcomes are a pure function of its coordinates,
  never of where or when it ran);
- a **bounded speculation window** (``lookahead``) lets workers run ahead
  of the batch currently blocking accumulation, so the campaign
  parallelizes across workers without any ordering collective.

Every worker accumulates every batch's published tally in batch-id order,
so all survivors converge to the same cumulative state and apply the
stopping rule identically — agreement without a barrier.

Import discipline: importable WITHOUT jax (pure host-side file
coordination; the compute callables passed in own all backend work).
"""

from __future__ import annotations

import os
import re
import time
from typing import Callable, NamedTuple

from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.resilience import load_json_verified, write_json_atomic
from shrewd_tpu.utils import debug
from shrewd_tpu.utils.config import ConfigObject, Param

debug.register_flag("Elastic", "membership / leases / re-mesh")


class ElasticError(RuntimeError):
    """The elastic layer could not make progress (e.g. a lease held past
    ``claim_wait`` by a worker that still appears alive)."""


class DrainRequested(Exception):
    """Raised out of a blocked ``obtain`` when the caller's drain
    predicate turns true: a SIGTERM must not wait out a peer's lease —
    the scheduler's kill grace is usually far shorter than
    ``claim_wait``."""


class ElasticConfig(ConfigObject):
    """Knobs for the elastic layer (a ``CampaignPlan`` child, so a
    campaign's survivability posture is reproducible from its config
    dump).  The coordination directory and worker name are *runtime*
    identity, not plan state — they come from the CLI/launcher."""

    heartbeat_interval = Param(float, 0.5,
                               "seconds between liveness beats",
                               check=lambda v: v > 0)
    heartbeat_timeout = Param(float, 5.0,
                              "seconds without a beat before a worker is "
                              "declared lost and its leases are revoked",
                              check=lambda v: v > 0)
    lookahead = Param(int, 2,
                      "batches a worker may speculatively run ahead of the "
                      "one blocking accumulation (bounds wasted work past "
                      "convergence)", check=lambda v: v >= 0)
    poll_interval = Param(float, 0.05,
                          "seconds between lease-board polls while blocked",
                          check=lambda v: v > 0)
    claim_wait = Param(float, 120.0,
                       "max seconds blocked on a live peer's lease before "
                       "the worker gives up (guards against undetectable "
                       "wedges; lost workers are revoked, not waited out)",
                       check=lambda v: v > 0)


def _sanitize(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.+-]", "+", name)


class HeartbeatWriter:
    """Periodic atomic liveness beats: ``hb_<worker>.json`` in the
    coordination directory.  ``stop()`` removes the file — a graceful
    leave is visible immediately, only a *dead* worker goes stale."""

    def __init__(self, coord_dir: str, worker: str, interval: float = 0.5):
        self.path = os.path.join(coord_dir, f"hb_{_sanitize(worker)}.json")
        self.worker = worker
        self.interval = float(interval)
        self.beats = 0
        self._thread = None
        self._stop = None

    def beat(self) -> None:
        """Atomic but deliberately UNSYNCED (plain tmp-write + rename, no
        fsyncs): a beat is a liveness signal whose loss on crash IS the
        signal — paying two synchronous flushes per beat per worker
        against the shared directory would buy nothing."""
        import json

        self.beats += 1
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            # graftlint: allow-raw-write -- liveness beat: atomic rename,
            # deliberately unsynced; its loss on crash IS the signal
            json.dump({"worker": self.worker, "beats": self.beats}, f)
        os.replace(tmp, self.path)

    def start(self) -> "HeartbeatWriter":
        import threading

        if self._thread is not None:
            return self
        self.beat()                      # liveness visible before any claim
        self._stop = threading.Event()

        def _loop():
            while not self._stop.wait(self.interval):
                self.beat()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name=f"heartbeat-{self.worker}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            os.unlink(self.path)         # graceful leave
        except OSError:
            pass


class Membership:
    """Liveness view over the heartbeat files."""

    def __init__(self, coord_dir: str, timeout: float = 5.0):
        self.coord_dir = coord_dir
        self.timeout = float(timeout)

    def _hb_path(self, worker: str) -> str:
        return os.path.join(self.coord_dir, f"hb_{_sanitize(worker)}.json")

    def alive(self, worker: str) -> bool:
        try:
            # graftlint: allow-wall-clock -- heartbeat staleness is
            # wall-clock liveness, not a trigger decision: tallies stay
            # bit-identical under any membership (frozen-key re-dispatch)
            # graftlint: allow-clock -- lease revocation compares against
            # real filesystem mtimes, so this read must NOT route through
            # the fake-able obs.clock seam (a test-installed clock would
            # mass-revoke live workers or never revoke dead ones)
            age = time.time() - os.stat(self._hb_path(worker)).st_mtime
        except OSError:
            return False                 # left gracefully or never joined
        return age < self.timeout

    def workers(self) -> list[str]:
        out = []
        for name in sorted(os.listdir(self.coord_dir)):
            if name.startswith("hb_") and name.endswith(".json"):
                try:
                    out.append(load_json_verified(
                        os.path.join(self.coord_dir, name))["worker"])
                except (OSError, ValueError, KeyError):
                    continue             # torn beat mid-read: skip
        return out


class LeaseBoard:
    """Per-batch leases + published results in a shared directory.

    ``claim`` is an atomic ``os.link`` of a fully-written temp file onto
    the lease path — two workers racing a batch cannot both win, and a
    reader never sees a half-written lease.  ``publish`` writes the done
    document atomically; after a revocation two workers may both compute
    (and publish) the same batch, which is harmless by construction: the
    tally is a pure function of the frozen keys, so both documents are
    bit-identical."""

    def __init__(self, coord_dir: str, worker: str):
        self.dir = os.path.join(coord_dir, "board")
        os.makedirs(self.dir, exist_ok=True)
        self.worker = worker

    def _lease(self, key: str) -> str:
        return os.path.join(self.dir, f"lease_{_sanitize(key)}.json")

    def _done(self, key: str) -> str:
        return os.path.join(self.dir, f"done_{_sanitize(key)}.json")

    def claim(self, key: str) -> bool:
        path = self._lease(key)
        tmp = f"{path}.{os.getpid()}.claim"
        with open(tmp, "w") as f:
            import json
            # graftlint: allow-raw-write -- lease claim: fsync'd tmp +
            # atomic os.link is the commit point; no torn lease is
            # observable and the board path must stay write_json-free
            json.dump({"worker": self.worker, "key": key}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def owner(self, key: str) -> str | None:
        try:
            return load_json_verified(self._lease(key)).get("worker")
        except (OSError, ValueError):
            return None

    def revoke(self, key: str, expected_owner: str | None = None) -> bool:
        """Remove the lease (the owner was declared lost).  True when this
        call actually removed a lease held by ``expected_owner``.

        The observe-owner → check-alive → revoke sequence is not atomic,
        so a naive unlink could delete a lease a LIVE worker re-claimed
        after an earlier revocation (the ABA race).  Instead the lease is
        atomically renamed into a per-revoker graveyard name, its content
        is read, and a mismatched owner is restored via ``os.link`` —
        one winner among racing revokers, and a re-claimed lease is never
        silently destroyed.  ``expected_owner=None`` skips the check
        (unconditional revoke, single-revoker callers/tests)."""
        path = self._lease(key)
        grave = f"{path}.{os.getpid()}.revoked"
        try:
            os.rename(path, grave)
        except OSError:
            return False                 # lost the race: someone else won
        try:
            if expected_owner is not None:
                try:
                    owner = load_json_verified(grave).get("worker")
                except (OSError, ValueError):
                    owner = None
                if owner != expected_owner:
                    # ABA: a live worker re-claimed between our
                    # observation and the rename — give the lease back
                    # (if a third claim landed meanwhile, the link fails
                    # and the re-claimer's publish still stands)
                    try:
                        os.link(grave, path)
                    except OSError:
                        pass
                    return False
            return True
        finally:
            try:
                os.unlink(grave)
            except OSError:
                pass

    def publish(self, key: str, doc: dict) -> None:
        """Done documents carry a content checksum (resilience.doc_checksum)
        so a result torn/corrupted on the shared filesystem reads as
        ABSENT (``done`` returns None → someone recomputes) rather than
        being adopted into a survivor's cumulative tally."""
        from shrewd_tpu.resilience import doc_checksum

        doc = dict(doc)
        doc["checksum"] = doc_checksum(doc)
        write_json_atomic(self._done(key), doc)

    def done(self, key: str) -> dict | None:
        try:
            return load_json_verified(self._done(key))
        except (OSError, ValueError):
            return None

    def retract(self, key: str) -> None:
        """Remove a published result AND its lease (an adopted document
        that failed validation): the batch reads as never-run, so the
        caller can claim and recompute it from its frozen coordinates."""
        for path in (self._done(key), self._lease(key)):
            try:
                os.unlink(path)
            except OSError:
                pass


class WorkerLostInfo(NamedTuple):
    """Payload of ``ExitEvent.WORKER_LOST``: who died, which batch lease
    was revoked, and who survives (the re-meshed membership)."""
    worker: str
    batch_key: str
    survivors: tuple


class ElasticContext:
    """One worker's view of an elastic campaign: heartbeat + membership +
    lease board + the accounting the ``campaign.elastic.*`` stats group
    reports."""

    def __init__(self, coord_dir: str, worker: str,
                 cfg: ElasticConfig | None = None):
        self.cfg = cfg if cfg is not None else ElasticConfig()
        self.coord_dir = coord_dir
        os.makedirs(coord_dir, exist_ok=True)
        self.worker = worker
        self.heartbeat = HeartbeatWriter(coord_dir, worker,
                                         self.cfg.heartbeat_interval)
        self.membership = Membership(coord_dir, self.cfg.heartbeat_timeout)
        self.board = LeaseBoard(coord_dir, worker)
        # the campaign.elastic.* ledgers
        self.claimed = 0          # leases this worker won
        self.adopted = 0          # batches accumulated from a peer's result
        self.revoked = 0          # leases revoked after owner loss
        self.reclaimed = 0        # revoked batches this worker re-computed
        self.lost_workers: set[str] = set()
        self._pending_lost: list[WorkerLostInfo] = []
        self._reclaim_pending = False

    def start(self) -> "ElasticContext":
        self.heartbeat.start()
        return self

    def stop(self) -> None:
        self.heartbeat.stop()

    def key(self, simpoint: str, structure: str, batch_id: int) -> str:
        return f"{simpoint}.{structure}.{int(batch_id)}"

    def take_lost(self) -> list[WorkerLostInfo]:
        ev, self._pending_lost = self._pending_lost, []
        return ev

    def counters(self) -> dict:
        return {"workers_lost": len(self.lost_workers),
                "leases_claimed": self.claimed,
                "leases_adopted": self.adopted,
                "leases_revoked": self.revoked,
                "batches_reclaimed": self.reclaimed}

    # --- the ensure loop -------------------------------------------------

    def obtain(self, target_key: str,
               compute: Callable[[], dict],
               speculate: Callable[[], bool] | None = None,
               should_abort: Callable[[], bool] | None = None
               ) -> tuple[dict, bool]:
        """Ensure ``target_key``'s done document exists and return
        ``(doc, adopted)``.

        Order of preference each round: adopt a published result; claim
        and compute it ourselves; revoke a lost owner's lease; speculate
        one batch ahead (``speculate()`` returns True when it did work);
        otherwise poll.  Blocked-on-a-live-peer time is bounded by
        ``claim_wait`` (progress resets the clock).  ``should_abort``
        (e.g. the orchestrator's drain flag) is re-checked while blocked
        and raises ``DrainRequested`` — a graceful preemption must not
        wait out a peer's lease."""
        waited = 0.0
        while True:
            if should_abort is not None and should_abort():
                raise DrainRequested(target_key)
            doc = self.board.done(target_key)
            if doc is not None:
                mine = doc.get("worker") == self.worker
                if not mine:
                    self.adopted += 1
                    obs_trace.tracer().emit(
                        "lease_adopt", cat="elastic", key=target_key,
                        peer=str(doc.get("worker", "")))
                # a revocation we won may have been computed by a third
                # worker first: the reclaim credit belongs to whoever
                # computed it, not to our next unrelated claim
                self._reclaim_pending = False
                return doc, not mine
            if self.board.claim(target_key):
                self.claimed += 1
                obs_trace.tracer().emit(
                    "lease_claim", cat="elastic", key=target_key,
                    worker=self.worker)
                if self._reclaim_pending:
                    self.reclaimed += 1
                    self._reclaim_pending = False
                doc = compute()
                doc["worker"] = self.worker
                self.board.publish(target_key, doc)
                return doc, False
            owner = self.board.owner(target_key)
            if owner is None:
                continue                 # lease vanished between checks
            if not self.membership.alive(owner):
                if self.board.revoke(target_key, expected_owner=owner):
                    self.revoked += 1
                    obs_trace.tracer().emit(
                        "lease_revoke", cat="elastic", key=target_key,
                        lost=owner)
                    self.lost_workers.add(owner)
                    self._reclaim_pending = True
                    self._pending_lost.append(WorkerLostInfo(
                        owner, target_key,
                        tuple(w for w in self.membership.workers()
                              if self.membership.alive(w))))
                    debug.dprintf(
                        "Elastic", "%s: revoked %s held by lost worker %s",
                        self.worker, target_key, owner)
                continue
            if speculate is not None and speculate():
                waited = 0.0             # progress: reset the give-up clock
                continue
            time.sleep(self.cfg.poll_interval)
            waited += self.cfg.poll_interval
            if waited > self.cfg.claim_wait:
                raise ElasticError(
                    f"{self.worker}: blocked {waited:.0f}s on "
                    f"{target_key} held by live worker {owner!r}")
