"""Sharded campaign step: vmap over trials within a chip, shard_map over the
mesh, psum tally reduction.

The TPU-native replacement of the reference's campaign fan-out (SURVEY §2.12
P3: ``multisim`` host multiprocessing / one gem5 process per config): one
jitted SPMD program runs ``batch_size`` trials spread across every device and
returns the (replicated) outcome tally; the host loop accumulates tallies and
applies the CI stopping rule (stopping.py).
"""

from __future__ import annotations

import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from shrewd_tpu.ops import classify as C
from shrewd_tpu.parallel import exec_cache, stopping
from shrewd_tpu.parallel.mesh import (TRIAL_AXIS, shard_batch_stack,
                                      shard_keys, shard_map)
from shrewd_tpu.resilience import DeviceWatchdog, DispatchTimeout, TIERS
from shrewd_tpu.utils import debug, prng

debug.register_flag("CampaignStep", "per-batch sharded campaign steps")


class ShardedCampaign:
    """One (trace, structure) campaign compiled over a mesh.

    Honors the kernel's ``replay_kernel`` config.  "dense" runs the fully
    SPMD dense path with an in-graph psum.  "taint"/"hybrid" default to the
    **device resolution** path (``resolution="device"``): the sharded taint
    fast pass plus in-graph budgeted exact re-runs
    (ops/trial.py run_keys_device) — one SPMD program per batch, each
    process resolving only its own shard, no host round-trip (VERDICT r2
    weak #9 removed the multi-host hazard of every process re-running
    global escape resolution).  ``resolution="host"`` keeps the round-2
    host-driven exact path (unbounded escapes; single-process debugging).
    Kernels without a replay_kernel knob (models.ruby.CacheKernel) use the
    dense protocol: ``outcomes_from_keys(keys, structure)``.
    """

    def __init__(self, kernel, mesh, structure: str,
                 resolution: str = "device", stratify: bool = False,
                 watchdog: DeviceWatchdog | None = None,
                 integrity_check: bool = False, chunked=None):
        """``watchdog`` (resilience.DeviceWatchdog, optional): every jitted
        device step routes through ``watchdog.call`` so a wedged dispatch
        surfaces as ``DispatchTimeout`` in bounded time instead of hanging
        the campaign loop forever.  None = direct dispatch (no overhead).

        ``integrity_check``: the jitted steps additionally return each
        shard's LOCAL tally (pre-psum), and every ``tally_batch`` verifies
        the locals sum to the replicated psum result — the shard-vs-psum
        invariant of the integrity layer (shrewd_tpu/integrity.py).  A
        mismatch raises ``integrity.IntegrityError``; the extra output is
        a few dozen integers per batch, so the hot path is unaffected.

        ``chunked`` (ops.chunked.ChunkedCampaign, optional): route every
        tally through the chunked execution strategy instead of the
        full-window jitted steps — the SimPoint-scale path, where one
        dense whole-window program would not fit compile/memory budgets.
        The chunked driver is host-orchestrated (its wave loop is the
        dispatch unit), so the mesh is not consulted for sharding and the
        multi-batch interval steps don't apply; outcomes are bit-identical
        to the dense protocol on the same keys."""
        if resolution not in ("device", "host"):
            raise ValueError(f"unknown resolution {resolution!r}")
        if stratify and not hasattr(kernel, "run_keys_stratified"):
            raise ValueError(
                f"{type(kernel).__name__} has no stratified tally path")
        if chunked is not None and chunked.kernel is not kernel:
            raise ValueError("chunked campaign wraps a different kernel")
        if stratify and resolution != "device" and chunked is None:
            # the stratified step uses the budgeted device resolution; a
            # host-resolution campaign would make summed strata disagree
            # with tally_batch on over-budget batches
            raise ValueError("stratify=True requires resolution='device'")
        self.kernel = kernel
        self.mesh = mesh
        self.structure = structure
        self.resolution = resolution
        self.stratify = stratify
        self.watchdog = watchdog
        self.integrity_check = integrity_check
        self.chunked = chunked
        self.shard_checks = 0        # shard-vs-psum verifications run
        self.shard_mismatches = 0    # ... that failed (each also raises)
        # collective-timeout detection (elastic layer): in a multi-host
        # mesh a deadline on the psum step is the first observable symptom
        # of a lost peer — the count feeds worker-loss diagnosis upstream
        self.collective_timeouts = 0
        self.mode = getattr(getattr(kernel, "cfg", None),
                            "replay_kernel", "dense")
        may_latch = structure == "latch"
        if chunked is not None:
            # host-orchestrated: no jitted campaign steps to build here
            # (the chunked driver owns its per-chunk executables, shared
            # through the same exec_cache)
            self._step = None
            self._taint_step = None
            self._device_step = None
            self._strat_step = None
            return

        def build_step():
            def local_step(keys):
                # the traceable campaign protocol (ops.trial.TrialKernel,
                # models.ruby.CacheKernel): keys → per-trial outcome classes
                outs = kernel.outcomes_from_keys(keys, structure)
                t = C.tally(outs)
                if integrity_check:
                    return jax.lax.psum(t, TRIAL_AXIS), t[None, :]
                return jax.lax.psum(t, TRIAL_AXIS)

            return jax.jit(shard_map(
                local_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
                out_specs=((P(), P(TRIAL_AXIS)) if integrity_check
                           else P())))

        # every jitted step goes through the process-wide executable cache
        # (parallel/exec_cache.py): two campaigns computing the same pure
        # function — the CPU fallback tier, a re-built orchestrator, the
        # canary battery's tier fns — share ONE compiled callable instead
        # of re-tracing per ShardedCampaign instance
        self._step = self._cached("step", build_step)

        self._taint_step = None
        self._device_step = None
        self._strat_step = None
        if stratify:
            def build_strat():
                def strat_step(keys):
                    tally_h, n_unres = kernel.run_keys_stratified(keys,
                                                                  structure)
                    out = (jax.lax.psum(tally_h, TRIAL_AXIS),
                           jax.lax.psum(n_unres, TRIAL_AXIS))
                    if integrity_check:
                        return out + (tally_h[None],)
                    return out

                return jax.jit(shard_map(
                    strat_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
                    out_specs=((P(), P(), P(TRIAL_AXIS)) if integrity_check
                               else (P(), P()))))

            self._strat_step = self._cached("strat", build_strat)
        if self.mode != "dense":
            _ = kernel.golden_rec     # materialize before tracing
            if resolution == "device":
                def build_device():
                    def device_step(keys):
                        tally, n_unres = kernel.run_keys_device(keys,
                                                                structure)
                        out = (jax.lax.psum(tally, TRIAL_AXIS),
                               jax.lax.psum(n_unres, TRIAL_AXIS))
                        if integrity_check:
                            return out + (tally[None],)
                        return out

                    return jax.jit(shard_map(
                        device_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
                        out_specs=((P(), P(), P(TRIAL_AXIS))
                                   if integrity_check else (P(), P()))))

                self._device_step = self._cached("device", build_device)
            else:
                def build_taint():
                    def taint_step(keys):
                        faults = kernel.sampler(structure).sample_batch(keys)
                        res = kernel.taint_fast(faults, may_latch=may_latch)
                        return res.outcome, res.escaped, res.overflow

                    return jax.jit(shard_map(
                        taint_step, mesh=mesh,
                        in_specs=P(TRIAL_AXIS),
                        out_specs=(P(TRIAL_AXIS),) * 3))

                self._taint_step = self._cached("taint", build_taint)

    def _cached(self, kind: str, build, **extra):
        """One campaign-step executable via the shared cache, keyed by the
        kernel's content fingerprint + mesh + structure + step kind."""
        return exec_cache.cache().get(
            exec_cache.step_key(self.kernel, self.mesh, self.structure,
                                kind=kind, mode=self.mode,
                                resolution=self.resolution,
                                integrity=self.integrity_check, **extra),
            owner=self.kernel, build=build)

    def _dispatch(self, step, *args):
        """One jitted device step, through the watchdog when configured.
        ``block_until_ready`` inside the guarded call: jax dispatch is
        async, so without it a wedged backend would 'return' instantly
        and hang later at the np.asarray materialization — outside the
        deadline."""
        if self.watchdog is None:
            return step(*args)
        try:
            return self.watchdog.call(
                lambda: jax.block_until_ready(step(*args)))
        except DispatchTimeout:
            # in a multi-process mesh this step IS a collective: a
            # deadline here may mean a lost peer, not a wedged backend —
            # count it so the elastic layer can fold it into membership
            self.collective_timeouts += 1
            raise

    def _guarded_dispatch(self, work, n_batches: int):
        """Deadline-guarded dispatch-side call shared by the interval
        and until-CI paths: a backend that wedges at enqueue/compile
        time (buffer allocation, device_put, the first AOT compile)
        surfaces as ``DispatchTimeout`` in bounded time, the per-batch
        deadline scaled by the dispatch's batch count."""
        if self.watchdog is not None and self.watchdog.timeout > 0:
            try:
                return self.watchdog.call(
                    work, timeout=self.watchdog.timeout * n_batches)
            except DispatchTimeout:
                self.collective_timeouts += 1
                raise
        return work()

    def _guarded_fetch(self, fetch, handle: "InflightInterval",
                       timeout: float | None):
        """Deadline-enforcing materialization shared by the interval and
        until-CI paths: the deadline armed at dispatch is enforced here,
        default-scaled by the in-flight batch count."""
        if self.watchdog is None:
            return fetch()
        if timeout is None and self.watchdog.timeout > 0:
            timeout = self.watchdog.timeout * handle.n_batches
        try:
            return self.watchdog.call_armed(fetch, handle.armed_at,
                                            timeout=timeout)
        except DispatchTimeout:
            self.collective_timeouts += 1
            raise

    def _verify_shards(self, local, total) -> None:
        """The shard-vs-psum invariant (integrity layer): the locals each
        shard computed must sum to the replicated reduction everyone
        received — a corrupted collective or stale donated buffer cannot
        pass."""
        from shrewd_tpu import integrity as integ

        self.shard_checks += 1
        viol = integ.shard_sum_violations(np.asarray(local),
                                          np.asarray(total))
        if viol:
            self.shard_mismatches += 1
            raise integ.IntegrityError(f"{self.structure}: {viol[0]}")

    def tally_batch_stratified(self, keys: jax.Array) -> jax.Array:
        """Sharded keys (B,) → replicated (N_STRATA, N_OUTCOMES) tally for
        the post-stratified estimator; summing over strata reproduces
        ``tally_batch`` exactly (same outcomes, same resolution)."""
        if self.chunked is not None:
            if not self.stratify:
                raise ValueError("campaign built without stratify=True")
            return self._tally_chunked(keys, stratified=True)
        if self._strat_step is None:
            raise ValueError("campaign built without stratify=True")
        out = self._dispatch(self._strat_step, shard_keys(self.mesh, keys))
        # ONE host transfer of the whole result tuple (the per-output
        # np.asarray pulls each paid their own sync + copy)
        host = jax.device_get(out)
        tally_h, n_unres = host[0], host[1]
        if self.integrity_check:
            self._verify_shards(host[2], tally_h)
        if self.mode != "dense":    # dense replay has no escape machinery
            self.kernel.escapes += int(n_unres)
            self.kernel.taint_trials += int(keys.shape[0])
        return tally_h

    def _tally_chunked(self, keys: jax.Array, stratified: bool):
        """Chunked-strategy tally: outcomes from the chunked wave driver
        (host-orchestrated; per-chunk executables dispatch on device),
        binned host-side.  Same keys → same outcomes as the dense
        protocol, so summing the stratified tally over strata reproduces
        ``tally_batch`` exactly, as on the jitted paths."""
        from shrewd_tpu.ops.trial import N_STRATA

        kernel = self.kernel
        faults = kernel.sampler(self.structure).sample_batch(keys)
        out = self.chunked.outcomes_of_faults(faults)
        if not stratified:
            return jnp.asarray(np.bincount(
                out, minlength=C.N_OUTCOMES).astype(np.int32))
        strata = np.asarray(kernel.strata_of(faults, self.structure))
        tally = np.zeros((N_STRATA, C.N_OUTCOMES), np.int32)
        np.add.at(tally, (strata, out), 1)
        return jnp.asarray(tally)

    def tally_batch(self, keys: jax.Array) -> jax.Array:
        """Sharded keys (B,) → replicated tally (N_OUTCOMES,)."""
        if self.chunked is not None:
            return self._tally_chunked(keys, stratified=False)
        if self._device_step is not None:
            out = self._dispatch(self._device_step,
                                 shard_keys(self.mesh, keys))
            host = jax.device_get(out)      # one transfer for the tuple
            tally, n_unres = host[0], host[1]
            if self.integrity_check:
                self._verify_shards(host[2], tally)
            self.kernel.escapes += int(n_unres)
            self.kernel.taint_trials += int(keys.shape[0])
            return tally
        if self._taint_step is None:
            out = self._dispatch(self._step, shard_keys(self.mesh, keys))
            if self.integrity_check:
                tally, local = jax.device_get(out)
                self._verify_shards(local, tally)
                return tally
            return out
        keys_sh = shard_keys(self.mesh, keys)
        res = self._dispatch(self._taint_step, keys_sh)
        out, esc, ovf = jax.device_get(res)  # one transfer for all three
        out = np.array(out)    # device_get may return a read-only view;
        # the escape-resolution passes below write into ``out``
        if self.mode == "taint":    # conservative, no host re-runs
            out[esc | ovf] = C.OUTCOME_SDC
            self.kernel.escapes += int((esc | ovf).sum())
            self.kernel.taint_trials += out.size
        elif (esc | ovf).any():
            faults = self.kernel.sample_batch(keys_sh, self.structure)
            out = self.kernel.resolve_escapes(faults, out, esc, ovf)
        else:
            # zero-escape batches still count toward the escape-rate stats
            # (resolve_escapes, which increments both, was not needed)
            self.kernel.taint_trials += out.size
        return jnp.asarray(
            np.bincount(out, minlength=C.N_OUTCOMES).astype(np.int32))

    # --- sync-interval machinery (pipelined engine, parallel/pipeline.py)

    @property
    def supports_intervals(self) -> bool:
        """Whether the multi-batch jitted interval step applies: the
        host-resolution taint path does per-batch host re-runs (nothing to
        accumulate on device), the chunked strategy is host-orchestrated,
        and a multi-process mesh would need the distributed key-data
        transport ``shard_batch_stack`` doesn't do."""
        return (self.chunked is None and self._taint_step is None
                and jax.process_count() == 1)

    def _build_interval_step(self, S: int):
        """Jitted S-batch step: raw key data (S, B, ...) sharded on B →
        cumulative interval tallies, accumulated ON DEVICE with one psum
        at the end.  Integer per-batch tallies commute, so the result is
        bit-identical to S serial ``tally_batch`` calls summed on the
        host.  Keys travel as raw data and re-wrap per batch inside the
        scan — extended-dtype arrays through scan/stack are version-
        fragile, uint32 data is not."""
        kernel, structure = self.kernel, self.structure
        integrity = self.integrity_check

        if self.stratify:
            from shrewd_tpu.ops.trial import N_STRATA

            def local(kd):
                def body(acc, kd_b):
                    keys = jax.random.wrap_key_data(kd_b)
                    th, nu = kernel.run_keys_stratified(keys, structure)
                    return (acc[0] + th, acc[1] + nu), None
                acc0 = (jnp.zeros((N_STRATA, C.N_OUTCOMES), jnp.int32),
                        jnp.int32(0))
                (th, nu), _ = jax.lax.scan(body, acc0, kd)
                out = (jax.lax.psum(th, TRIAL_AXIS),
                       jax.lax.psum(nu, TRIAL_AXIS))
                if integrity:
                    out = out + (th[None],)
                return out

            out_specs = ((P(), P(), P(TRIAL_AXIS)) if integrity
                         else (P(), P()))
        elif self._device_step is not None:
            def local(kd):
                def body(acc, kd_b):
                    keys = jax.random.wrap_key_data(kd_b)
                    tally, nu = kernel.run_keys_device(keys, structure)
                    return (acc[0] + tally, acc[1] + nu), None
                acc0 = (jnp.zeros(C.N_OUTCOMES, jnp.int32), jnp.int32(0))
                (t, nu), _ = jax.lax.scan(body, acc0, kd)
                out = (jax.lax.psum(t, TRIAL_AXIS),
                       jax.lax.psum(nu, TRIAL_AXIS))
                if integrity:
                    out = out + (t[None],)
                return out

            out_specs = ((P(), P(), P(TRIAL_AXIS)) if integrity
                         else (P(), P()))
        else:
            def local(kd):
                def body(acc, kd_b):
                    keys = jax.random.wrap_key_data(kd_b)
                    outs = kernel.outcomes_from_keys(keys, structure)
                    return acc + C.tally(outs), None
                t, _ = jax.lax.scan(
                    body, jnp.zeros(C.N_OUTCOMES, jnp.int32), kd)
                out = jax.lax.psum(t, TRIAL_AXIS)
                if integrity:
                    out = (out, t[None])
                return out

            out_specs = ((P(), P(TRIAL_AXIS)) if integrity else P())
        return jax.jit(shard_map(
            local, mesh=self.mesh, in_specs=P(None, TRIAL_AXIS),
            out_specs=out_specs))

    # --- device-resident run-until-CI (the fused stopping rule) ---------

    def _build_until_ci_step(self, S: int, strat_rule: bool):
        """Jitted device-resident run-until-CI step: a ``lax.while_loop``
        around the per-batch tally step that keeps consuming frozen
        per-batch keys from a pre-staged (S, B, ...) key stack,
        accumulates tallies/strata/n_unres ON DEVICE, evaluates the
        Wilson (pooled) or post-stratified half-width each batch, and
        exits at the first batch boundary where the stopping rule fires —
        or when the S-batch super-interval budget is exhausted.  ONE
        result transfer per super-interval replaces one per batch.

        Decision cadence is per batch — exactly the serial host loop's —
        so for matching decisions (see ``stopping.wilson_halfwidth_device``
        on float32 parity) the consumed batch count and therefore the
        final tallies are bit-identical to the serial loop's.  Integer
        gates (min_trials, the ceiling-clamped budget) are exact.

        ``strat_rule``: evaluate the post-stratified rule (only offered
        when the strata history covers every counted trial — the same
        gate the host loop applies); the pooled Wilson rule otherwise.
        Inputs beyond the key stack: initial cumulative tallies (+strata
        when stratified), integer params (initial trials, min_trials) and
        float params (target half-width, z) — all replicated, so one
        executable serves any precision target at the same (S, B)."""
        kernel, structure = self.kernel, self.structure
        integrity = self.integrity_check
        stratify = self.stratify
        mesh_size = self.mesh.size
        if strat_rule and not stratify:
            raise ValueError("stratified stopping rule needs a stratified "
                             "campaign")
        if stratify:
            from shrewd_tpu.ops.trial import N_STRATA

        def batch_tally(keys):
            """per-batch LOCAL tallies: (pooled tally, strata|None,
            n_unres) — the same per-batch step the interval scan runs."""
            if stratify:
                th, nu = kernel.run_keys_stratified(keys, structure)
                return th.sum(axis=0), th, nu
            if self._device_step is not None:
                t, nu = kernel.run_keys_device(keys, structure)
                return t, None, nu
            outs = kernel.outcomes_from_keys(keys, structure)
            return C.tally(outs), None, jnp.int32(0)

        def local(kd, tal0, strat0, iparams, fparams):
            B_global = kd.shape[1] * mesh_size
            trials0, min_trials = iparams[0], iparams[1]
            target, z = fparams[0], fparams[1]

            def cond(carry):
                i, _dt, _loc, _ds, _nu, _hw, done = carry
                return jnp.logical_and(i < S, jnp.logical_not(done))

            def body(carry):
                i, dt, loc, ds, nu, hw_buf, _done = carry
                keys = jax.random.wrap_key_data(kd[i])
                t, th, nu_b = batch_tally(keys)
                dt = dt + jax.lax.psum(t, TRIAL_AXIS)
                # the shard-local accumulator mirrors the RETURNED
                # accumulator (strata when stratified, pooled otherwise)
                # so the shard-vs-psum invariant checks what ships
                loc = loc + (th if stratify else t)
                if stratify:
                    ds = ds + jax.lax.psum(th, TRIAL_AXIS)
                nu = nu + jax.lax.psum(nu_b, TRIAL_AXIS)
                trials = trials0 + (i + 1) * B_global
                cum = tal0 + dt
                if strat_rule:
                    hw = stopping.post_stratified_halfwidth_device(
                        strat0 + ds, z)
                else:
                    vul = cum[C.OUTCOME_SDC] + cum[C.OUTCOME_DUE]
                    hw = stopping.wilson_halfwidth_device(vul, trials, z)
                hw_buf = hw_buf.at[i].set(hw)
                done = stopping.should_stop_device(hw, trials, target,
                                                  min_trials)
                return (i + 1, dt, loc, ds, nu, hw_buf, done)

            zt = jnp.zeros(C.N_OUTCOMES, jnp.int32)
            zs = (jnp.zeros((N_STRATA, C.N_OUTCOMES), jnp.int32)
                  if stratify else jnp.int32(0))
            carry0 = (jnp.int32(0), zt, (zs if stratify else zt), zs,
                      jnp.int32(0),
                      jnp.full((S,), jnp.nan, jnp.float32),
                      jnp.bool_(False))
            i, dt, loc, ds, nu, hw_buf, _done = jax.lax.while_loop(
                cond, body, carry0)
            out = ((ds if stratify else dt), nu, i, hw_buf)
            if integrity:
                # the per-shard local accumulator rides along for the
                # shard-vs-psum invariant, exactly like the interval step
                out = out + (loc[None],)
            return out

        out_specs = (P(), P(), P(), P())
        if integrity:
            out_specs = out_specs + (P(TRIAL_AXIS),)
        return jax.jit(shard_map(
            local, mesh=self.mesh,
            in_specs=(P(None, TRIAL_AXIS), P(), P(), P(), P()),
            out_specs=out_specs))

    def dispatch_until_ci(self, keys_list, initial_tallies,
                          initial_strata, trials0: int, min_trials: int,
                          target_halfwidth: float, confidence: float,
                          strat_rule: bool) -> "InflightInterval":
        """Async-dispatch one device-resident until-CI super-interval
        (budget = len(keys_list) batches) and return without blocking —
        the device consumes batches and checks the stopping rule in-graph
        until it fires or the budget runs out.  Same watchdog posture as
        ``dispatch_interval`` (armed now, enforced at materialization);
        same AOT executable-cache routing (shape-specialized per (S, B),
        NOT per precision target — target/z/min_trials travel as
        replicated scalars)."""
        if not self.supports_intervals:
            raise ValueError(f"{self.structure}: campaign does not support "
                             "device-resident until-CI accumulation")
        S = len(keys_list)
        B = int(keys_list[0].shape[0])
        armed_at = (self.watchdog.arm() if self.watchdog is not None
                    else time.monotonic())

        def dispatch_work():
            from shrewd_tpu.parallel.mesh import replicated

            kd = jnp.stack([jax.random.key_data(k) for k in keys_list])
            kd_sh = shard_batch_stack(self.mesh, kd)
            tal0 = replicated(self.mesh, jnp.asarray(
                np.asarray(initial_tallies), jnp.int32))
            if self.stratify:
                from shrewd_tpu.ops.trial import N_STRATA
                s0 = (np.zeros((N_STRATA, C.N_OUTCOMES), np.int64)
                      if initial_strata is None
                      else np.asarray(initial_strata))
                strat0 = replicated(self.mesh, jnp.asarray(s0, jnp.int32))
            else:
                strat0 = replicated(self.mesh, jnp.int32(0))
            iparams = replicated(self.mesh, jnp.asarray(
                [int(trials0), int(min_trials)], jnp.int32))
            fparams = replicated(self.mesh, jnp.asarray(
                [float(target_halfwidth),
                 stopping.z_value(float(confidence))], jnp.float32))
            args = (kd_sh, tal0, strat0, iparams, fparams)
            step = exec_cache.cache().get_aot(
                exec_cache.step_key(self.kernel, self.mesh,
                                    self.structure, kind="until_ci",
                                    S=S, B=B, mode=self.mode,
                                    resolution=self.resolution,
                                    stratify=self.stratify,
                                    rule=("strat" if strat_rule
                                          else "pooled"),
                                    integrity=self.integrity_check),
                owner=self.kernel,
                build=lambda: self._build_until_ci_step(S, strat_rule),
                example_args=args)
            return step(*args)

        out = self._guarded_dispatch(dispatch_work, S)
        return InflightInterval(out, armed_at, S, S * B)

    def materialize_until_ci(self, handle: "InflightInterval",
                             timeout: float | None = None):
        """Block for / transfer one until-CI super-interval — ONE host
        transfer covering however many batches the device consumed.
        → (tally_delta int64 (N_OUTCOMES,), strata_delta int64 | None,
        batches_consumed int, hw_trace float32 (consumed,)).  Escape
        counters update from the CONSUMED batch count (device-decided),
        and the shard-vs-psum invariant is verified on the super-interval
        accumulators exactly like the interval path."""
        host = self._guarded_fetch(lambda: jax.device_get(handle.out),
                                   handle, timeout)
        acc, n_unres, consumed, hw_buf = host[0], host[1], host[2], host[3]
        consumed = int(consumed)
        strata = None
        if self.stratify:
            strata = np.asarray(acc, dtype=np.int64)
            tally = strata.sum(axis=0)
        else:
            tally = np.asarray(acc, dtype=np.int64)
        if self.integrity_check:
            self._verify_shards(host[4], acc)
        if self.mode != "dense":
            B = handle.n_trials // max(handle.n_batches, 1)
            self.kernel.escapes += int(n_unres)
            self.kernel.taint_trials += consumed * B
        return tally, strata, consumed, np.asarray(hw_buf)[:consumed]

    def dispatch_interval(self, keys_list) -> "InflightInterval":
        """Async-dispatch one sync interval (len(keys_list) batches) and
        return WITHOUT blocking — jax dispatch is asynchronous, so the
        host is free to consume the previous interval while the device
        computes this one.  The watchdog deadline is armed NOW and
        enforced at ``materialize_interval``.  The interval step is
        AOT-compiled through the shared executable cache (keyed by kernel
        content, mesh, structure, S)."""
        if not self.supports_intervals:
            raise ValueError(f"{self.structure}: campaign does not support "
                             "sync-interval accumulation")
        S = len(keys_list)
        B = int(keys_list[0].shape[0])
        armed_at = (self.watchdog.arm() if self.watchdog is not None
                    else time.monotonic())

        def dispatch_work():
            kd = jnp.stack([jax.random.key_data(k) for k in keys_list])
            kd_sh = shard_batch_stack(self.mesh, kd)
            # B is part of the key: the AOT path caches a SHAPE-
            # SPECIALIZED executable, so a second campaign over the same
            # trace at a different batch size must compile its own
            step = exec_cache.cache().get_aot(
                exec_cache.step_key(self.kernel, self.mesh,
                                    self.structure, kind="interval",
                                    S=S, B=B, mode=self.mode,
                                    resolution=self.resolution,
                                    stratify=self.stratify,
                                    integrity=self.integrity_check),
                owner=self.kernel,
                build=lambda: self._build_interval_step(S),
                example_args=(kd_sh,))
            return step(kd_sh)

        # the dispatch side is deadline-guarded too (arm() above starts
        # the clock, so materialization only gets what the dispatch
        # didn't spend)
        out = self._guarded_dispatch(dispatch_work, S)
        return InflightInterval(out, armed_at, S, S * B)

    def materialize_interval(self, handle: "InflightInterval",
                             timeout: float | None = None):
        """Block for / transfer an in-flight interval — ONE host transfer
        per sync interval.  Enforces the deadline armed at dispatch,
        verifies the shard-vs-psum invariant on the interval accumulators,
        and updates the kernel's escape counters exactly as the serial
        per-batch loop would.  → (tally int64 (N_OUTCOMES,),
        strata int64 | None).

        ``timeout``: total deadline measured from the arm time.  Default
        scales the watchdog's PER-BATCH deadline by the interval's batch
        count; the pipelined engine passes a depth-scaled value on top,
        since a prefetched interval legitimately queues behind the
        intervals dispatched ahead of it."""
        host = self._guarded_fetch(lambda: jax.device_get(handle.out),
                                   handle, timeout)
        strata = None
        n_unres = None
        if self.stratify:
            strata = np.asarray(host[0], dtype=np.int64)
            n_unres = int(host[1])
            if self.integrity_check:
                self._verify_shards(host[2], host[0])
            tally = strata.sum(axis=0)
        elif self._device_step is not None:
            tally = np.asarray(host[0], dtype=np.int64)
            n_unres = int(host[1])
            if self.integrity_check:
                self._verify_shards(host[2], host[0])
        elif self.integrity_check:
            tally = np.asarray(host[0], dtype=np.int64)
            self._verify_shards(host[1], host[0])
        else:
            tally = np.asarray(host, dtype=np.int64)
        if self.mode != "dense" and n_unres is not None:
            self.kernel.escapes += n_unres
            self.kernel.taint_trials += handle.n_trials
        return tally, strata

    def tally_interval(self, keys_list):
        """Blocking convenience: dispatch + materialize one interval (the
        serial-equivalence surface the bit-identity tests pin)."""
        return self.materialize_interval(self.dispatch_interval(keys_list))


class InflightInterval(NamedTuple):
    """An async-dispatched sync interval: device outputs not yet awaited,
    the watchdog arm time, and the interval's shape."""
    out: object              # device arrays (pytree), still in flight
    armed_at: float          # watchdog deadline epoch (resilience.arm)
    n_batches: int
    n_trials: int


class CampaignResult(NamedTuple):
    structure: str
    tallies: np.ndarray          # (N_OUTCOMES,)
    trials: int
    batches: int
    avf: float
    avf_interval: stopping.Interval
    sdc_interval: stopping.Interval
    wall_seconds: float
    trials_per_second: float
    converged: bool
    strata_tallies: np.ndarray | None = None   # (N_STRATA, N_OUTCOMES)
    tier_trials: np.ndarray | None = None      # (len(TIERS),) per-tier count
    escalation_rate: float = 0.0               # fraction run below device


def run_until_ci(campaign: ShardedCampaign, *, seed: int, simpoint_id: int,
                 structure_id: int, batch_size: int = 4096,
                 target_halfwidth: float = 0.01, confidence: float = 0.95,
                 max_trials: int = 1_000_000, min_trials: int = 1000,
                 start_batch: int = 0,
                 initial_tallies: np.ndarray | None = None,
                 initial_strata: np.ndarray | None = None,
                 dispatcher=None) -> CampaignResult:
    """Accumulate batches until the AVF CI is tight enough (the north-star
    wall-clock loop).  ``start_batch``/``initial_tallies`` (and, for a
    stratified campaign, ``initial_strata``) resume a checkpointed campaign
    without replaying old batches.  A stratified run resumed WITHOUT its
    strata (or capped before its first batch) falls back to the pooled
    Wilson interval over everything it has, so the reported interval always
    covers every counted trial.

    ``dispatcher`` (resilience.ResilientDispatcher, optional): route every
    batch through the retry/degradation ladder; the result then carries
    per-tier trial counts and the escalation rate so a degraded run is
    self-describing."""
    sk = prng.structure_key(
        prng.simpoint_key(prng.campaign_key(seed), simpoint_id), structure_id)
    stratified = campaign.stratify
    tallies = (np.zeros(C.N_OUTCOMES, dtype=np.int64)
               if initial_tallies is None
               else np.asarray(initial_tallies, dtype=np.int64).copy())
    strata = None
    if stratified:
        from shrewd_tpu.ops.trial import N_STRATA
        strata = (np.zeros((N_STRATA, C.N_OUTCOMES), dtype=np.int64)
                  if initial_strata is None
                  else np.asarray(initial_strata, dtype=np.int64).copy())
    trials = int(tallies.sum())
    batch_id = start_batch
    t0 = time.monotonic()
    converged = False

    def _strata_pairs():
        return stopping.pairs_from_strata(strata)

    tier_trials = np.zeros(len(TIERS), dtype=np.int64)
    while trials < max_trials:
        keys = prng.trial_keys(prng.batch_key(sk, batch_id), batch_size)
        if dispatcher is not None:
            res = dispatcher.tally_batch(keys, stratified=stratified)
            tier_trials[res.tier] += batch_size
            if stratified:
                strata += res.strata
            t = res.tally
        elif stratified:
            th = np.asarray(campaign.tally_batch_stratified(keys),
                            dtype=np.int64)
            strata += th
            t = th.sum(axis=0)
        else:
            t = np.asarray(campaign.tally_batch(keys), dtype=np.int64)
        tallies += t
        trials += batch_size
        batch_id += 1
        vulnerable = int(tallies[C.OUTCOME_SDC] + tallies[C.OUTCOME_DUE])
        debug.dprintf("CampaignStep", "%s batch %d: trials=%d avf=%.4f",
                      campaign.structure, batch_id, trials,
                      vulnerable / max(trials, 1))
        # strata cover every counted trial only when the whole history ran
        # stratified (fresh run, or resume that passed initial_strata)
        strata_complete = stratified and stopping.strata_cover_trials(
            strata, trials)
        if strata_complete:
            if stopping.should_stop_stratified(
                    _strata_pairs(), target_halfwidth, confidence,
                    min_trials):
                converged = True
                break
        elif stopping.should_stop(vulnerable, trials, target_halfwidth,
                                  confidence, min_trials):
            converged = True
            break
    wall = time.monotonic() - t0
    vulnerable = int(tallies[C.OUTCOME_SDC] + tallies[C.OUTCOME_DUE])
    return CampaignResult(
        structure=campaign.structure,
        tallies=tallies,
        trials=trials,
        batches=batch_id - start_batch,
        avf=vulnerable / max(trials, 1),
        avf_interval=(stopping.post_stratified(_strata_pairs(), confidence)
                      if stratified and stopping.strata_cover_trials(
                          strata, trials)
                      else stopping.wilson(vulnerable, trials, confidence)),
        sdc_interval=stopping.wilson(
            int(tallies[C.OUTCOME_SDC]), trials, confidence),
        wall_seconds=wall,
        trials_per_second=(trials - int(0 if initial_tallies is None
                                        else initial_tallies.sum())) / wall
        if wall > 0 else float("inf"),
        converged=converged,
        strata_tallies=strata,
        tier_trials=tier_trials if dispatcher is not None else None,
        escalation_rate=(
            float(tier_trials[1:].sum() / max(tier_trials.sum(), 1))
            if dispatcher is not None else 0.0),
    )
