"""Sharded campaign step: vmap over trials within a chip, shard_map over the
mesh, psum tally reduction.

The TPU-native replacement of the reference's campaign fan-out (SURVEY §2.12
P3: ``multisim`` host multiprocessing / one gem5 process per config): one
jitted SPMD program runs ``batch_size`` trials spread across every device and
returns the (replicated) outcome tally; the host loop accumulates tallies and
applies the CI stopping rule (stopping.py).
"""

from __future__ import annotations

import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from shrewd_tpu.ops import classify as C
from shrewd_tpu.parallel import stopping
from shrewd_tpu.parallel.mesh import TRIAL_AXIS, shard_keys, shard_map
from shrewd_tpu.resilience import DeviceWatchdog, DispatchTimeout, TIERS
from shrewd_tpu.utils import debug, prng

debug.register_flag("CampaignStep", "per-batch sharded campaign steps")


class ShardedCampaign:
    """One (trace, structure) campaign compiled over a mesh.

    Honors the kernel's ``replay_kernel`` config.  "dense" runs the fully
    SPMD dense path with an in-graph psum.  "taint"/"hybrid" default to the
    **device resolution** path (``resolution="device"``): the sharded taint
    fast pass plus in-graph budgeted exact re-runs
    (ops/trial.py run_keys_device) — one SPMD program per batch, each
    process resolving only its own shard, no host round-trip (VERDICT r2
    weak #9 removed the multi-host hazard of every process re-running
    global escape resolution).  ``resolution="host"`` keeps the round-2
    host-driven exact path (unbounded escapes; single-process debugging).
    Kernels without a replay_kernel knob (models.ruby.CacheKernel) use the
    dense protocol: ``outcomes_from_keys(keys, structure)``.
    """

    def __init__(self, kernel, mesh, structure: str,
                 resolution: str = "device", stratify: bool = False,
                 watchdog: DeviceWatchdog | None = None,
                 integrity_check: bool = False):
        """``watchdog`` (resilience.DeviceWatchdog, optional): every jitted
        device step routes through ``watchdog.call`` so a wedged dispatch
        surfaces as ``DispatchTimeout`` in bounded time instead of hanging
        the campaign loop forever.  None = direct dispatch (no overhead).

        ``integrity_check``: the jitted steps additionally return each
        shard's LOCAL tally (pre-psum), and every ``tally_batch`` verifies
        the locals sum to the replicated psum result — the shard-vs-psum
        invariant of the integrity layer (shrewd_tpu/integrity.py).  A
        mismatch raises ``integrity.IntegrityError``; the extra output is
        a few dozen integers per batch, so the hot path is unaffected."""
        if resolution not in ("device", "host"):
            raise ValueError(f"unknown resolution {resolution!r}")
        if stratify and not hasattr(kernel, "run_keys_stratified"):
            raise ValueError(
                f"{type(kernel).__name__} has no stratified tally path")
        if stratify and resolution != "device":
            # the stratified step uses the budgeted device resolution; a
            # host-resolution campaign would make summed strata disagree
            # with tally_batch on over-budget batches
            raise ValueError("stratify=True requires resolution='device'")
        self.kernel = kernel
        self.mesh = mesh
        self.structure = structure
        self.resolution = resolution
        self.stratify = stratify
        self.watchdog = watchdog
        self.integrity_check = integrity_check
        self.shard_checks = 0        # shard-vs-psum verifications run
        self.shard_mismatches = 0    # ... that failed (each also raises)
        # collective-timeout detection (elastic layer): in a multi-host
        # mesh a deadline on the psum step is the first observable symptom
        # of a lost peer — the count feeds worker-loss diagnosis upstream
        self.collective_timeouts = 0
        self.mode = getattr(getattr(kernel, "cfg", None),
                            "replay_kernel", "dense")
        may_latch = structure == "latch"

        def local_step(keys):
            # the traceable campaign protocol (ops.trial.TrialKernel,
            # models.ruby.CacheKernel): keys → per-trial outcome classes
            outs = kernel.outcomes_from_keys(keys, structure)
            t = C.tally(outs)
            if integrity_check:
                return jax.lax.psum(t, TRIAL_AXIS), t[None, :]
            return jax.lax.psum(t, TRIAL_AXIS)

        self._step = jax.jit(shard_map(
            local_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
            out_specs=((P(), P(TRIAL_AXIS)) if integrity_check else P())))

        self._taint_step = None
        self._device_step = None
        self._strat_step = None
        if stratify:
            def strat_step(keys):
                tally_h, n_unres = kernel.run_keys_stratified(keys,
                                                              structure)
                out = (jax.lax.psum(tally_h, TRIAL_AXIS),
                       jax.lax.psum(n_unres, TRIAL_AXIS))
                if integrity_check:
                    return out + (tally_h[None],)
                return out

            self._strat_step = jax.jit(shard_map(
                strat_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
                out_specs=((P(), P(), P(TRIAL_AXIS)) if integrity_check
                           else (P(), P()))))
        if self.mode != "dense":
            _ = kernel.golden_rec     # materialize before tracing
            if resolution == "device":
                def device_step(keys):
                    tally, n_unres = kernel.run_keys_device(keys, structure)
                    out = (jax.lax.psum(tally, TRIAL_AXIS),
                           jax.lax.psum(n_unres, TRIAL_AXIS))
                    if integrity_check:
                        return out + (tally[None],)
                    return out

                self._device_step = jax.jit(shard_map(
                    device_step, mesh=mesh, in_specs=P(TRIAL_AXIS),
                    out_specs=((P(), P(), P(TRIAL_AXIS)) if integrity_check
                               else (P(), P()))))
            else:
                def taint_step(keys):
                    faults = kernel.sampler(structure).sample_batch(keys)
                    res = kernel.taint_fast(faults, may_latch=may_latch)
                    return res.outcome, res.escaped, res.overflow

                self._taint_step = jax.jit(shard_map(
                    taint_step, mesh=mesh,
                    in_specs=P(TRIAL_AXIS),
                    out_specs=(P(TRIAL_AXIS),) * 3))

    def _dispatch(self, step, *args):
        """One jitted device step, through the watchdog when configured.
        ``block_until_ready`` inside the guarded call: jax dispatch is
        async, so without it a wedged backend would 'return' instantly
        and hang later at the np.asarray materialization — outside the
        deadline."""
        if self.watchdog is None:
            return step(*args)
        try:
            return self.watchdog.call(
                lambda: jax.block_until_ready(step(*args)))
        except DispatchTimeout:
            # in a multi-process mesh this step IS a collective: a
            # deadline here may mean a lost peer, not a wedged backend —
            # count it so the elastic layer can fold it into membership
            self.collective_timeouts += 1
            raise

    def _verify_shards(self, local, total) -> None:
        """The shard-vs-psum invariant (integrity layer): the locals each
        shard computed must sum to the replicated reduction everyone
        received — a corrupted collective or stale donated buffer cannot
        pass."""
        from shrewd_tpu import integrity as integ

        self.shard_checks += 1
        viol = integ.shard_sum_violations(np.asarray(local),
                                          np.asarray(total))
        if viol:
            self.shard_mismatches += 1
            raise integ.IntegrityError(f"{self.structure}: {viol[0]}")

    def tally_batch_stratified(self, keys: jax.Array) -> jax.Array:
        """Sharded keys (B,) → replicated (N_STRATA, N_OUTCOMES) tally for
        the post-stratified estimator; summing over strata reproduces
        ``tally_batch`` exactly (same outcomes, same resolution)."""
        if self._strat_step is None:
            raise ValueError("campaign built without stratify=True")
        out = self._dispatch(self._strat_step, shard_keys(self.mesh, keys))
        tally_h, n_unres = out[0], out[1]
        if self.integrity_check:
            self._verify_shards(out[2], tally_h)
        if self.mode != "dense":    # dense replay has no escape machinery
            self.kernel.escapes += int(n_unres)
            self.kernel.taint_trials += int(keys.shape[0])
        return tally_h

    def tally_batch(self, keys: jax.Array) -> jax.Array:
        """Sharded keys (B,) → replicated tally (N_OUTCOMES,)."""
        if self._device_step is not None:
            out = self._dispatch(self._device_step,
                                 shard_keys(self.mesh, keys))
            tally, n_unres = out[0], out[1]
            if self.integrity_check:
                self._verify_shards(out[2], tally)
            self.kernel.escapes += int(n_unres)
            self.kernel.taint_trials += int(keys.shape[0])
            return tally
        if self._taint_step is None:
            out = self._dispatch(self._step, shard_keys(self.mesh, keys))
            if self.integrity_check:
                tally, local = out
                self._verify_shards(local, tally)
                return tally
            return out
        keys_sh = shard_keys(self.mesh, keys)
        out, esc, ovf = self._dispatch(self._taint_step, keys_sh)
        out = np.asarray(out).copy()
        esc = np.asarray(esc)
        ovf = np.asarray(ovf)
        if self.mode == "taint":    # conservative, no host re-runs
            out[esc | ovf] = C.OUTCOME_SDC
            self.kernel.escapes += int((esc | ovf).sum())
            self.kernel.taint_trials += out.size
        elif (esc | ovf).any():
            faults = self.kernel.sample_batch(keys_sh, self.structure)
            out = self.kernel.resolve_escapes(faults, out, esc, ovf)
        else:
            # zero-escape batches still count toward the escape-rate stats
            # (resolve_escapes, which increments both, was not needed)
            self.kernel.taint_trials += out.size
        return jnp.asarray(
            np.bincount(out, minlength=C.N_OUTCOMES).astype(np.int32))


class CampaignResult(NamedTuple):
    structure: str
    tallies: np.ndarray          # (N_OUTCOMES,)
    trials: int
    batches: int
    avf: float
    avf_interval: stopping.Interval
    sdc_interval: stopping.Interval
    wall_seconds: float
    trials_per_second: float
    converged: bool
    strata_tallies: np.ndarray | None = None   # (N_STRATA, N_OUTCOMES)
    tier_trials: np.ndarray | None = None      # (len(TIERS),) per-tier count
    escalation_rate: float = 0.0               # fraction run below device


def run_until_ci(campaign: ShardedCampaign, *, seed: int, simpoint_id: int,
                 structure_id: int, batch_size: int = 4096,
                 target_halfwidth: float = 0.01, confidence: float = 0.95,
                 max_trials: int = 1_000_000, min_trials: int = 1000,
                 start_batch: int = 0,
                 initial_tallies: np.ndarray | None = None,
                 initial_strata: np.ndarray | None = None,
                 dispatcher=None) -> CampaignResult:
    """Accumulate batches until the AVF CI is tight enough (the north-star
    wall-clock loop).  ``start_batch``/``initial_tallies`` (and, for a
    stratified campaign, ``initial_strata``) resume a checkpointed campaign
    without replaying old batches.  A stratified run resumed WITHOUT its
    strata (or capped before its first batch) falls back to the pooled
    Wilson interval over everything it has, so the reported interval always
    covers every counted trial.

    ``dispatcher`` (resilience.ResilientDispatcher, optional): route every
    batch through the retry/degradation ladder; the result then carries
    per-tier trial counts and the escalation rate so a degraded run is
    self-describing."""
    sk = prng.structure_key(
        prng.simpoint_key(prng.campaign_key(seed), simpoint_id), structure_id)
    stratified = campaign.stratify
    tallies = (np.zeros(C.N_OUTCOMES, dtype=np.int64)
               if initial_tallies is None
               else np.asarray(initial_tallies, dtype=np.int64).copy())
    strata = None
    if stratified:
        from shrewd_tpu.ops.trial import N_STRATA
        strata = (np.zeros((N_STRATA, C.N_OUTCOMES), dtype=np.int64)
                  if initial_strata is None
                  else np.asarray(initial_strata, dtype=np.int64).copy())
    trials = int(tallies.sum())
    batch_id = start_batch
    t0 = time.monotonic()
    converged = False

    def _strata_pairs():
        return stopping.pairs_from_strata(strata)

    tier_trials = np.zeros(len(TIERS), dtype=np.int64)
    while trials < max_trials:
        keys = prng.trial_keys(prng.batch_key(sk, batch_id), batch_size)
        if dispatcher is not None:
            res = dispatcher.tally_batch(keys, stratified=stratified)
            tier_trials[res.tier] += batch_size
            if stratified:
                strata += res.strata
            t = res.tally
        elif stratified:
            th = np.asarray(campaign.tally_batch_stratified(keys),
                            dtype=np.int64)
            strata += th
            t = th.sum(axis=0)
        else:
            t = np.asarray(campaign.tally_batch(keys), dtype=np.int64)
        tallies += t
        trials += batch_size
        batch_id += 1
        vulnerable = int(tallies[C.OUTCOME_SDC] + tallies[C.OUTCOME_DUE])
        debug.dprintf("CampaignStep", "%s batch %d: trials=%d avf=%.4f",
                      campaign.structure, batch_id, trials,
                      vulnerable / max(trials, 1))
        # strata cover every counted trial only when the whole history ran
        # stratified (fresh run, or resume that passed initial_strata)
        strata_complete = stratified and stopping.strata_cover_trials(
            strata, trials)
        if strata_complete:
            if stopping.should_stop_stratified(
                    _strata_pairs(), target_halfwidth, confidence,
                    min_trials):
                converged = True
                break
        elif stopping.should_stop(vulnerable, trials, target_halfwidth,
                                  confidence, min_trials):
            converged = True
            break
    wall = time.monotonic() - t0
    vulnerable = int(tallies[C.OUTCOME_SDC] + tallies[C.OUTCOME_DUE])
    return CampaignResult(
        structure=campaign.structure,
        tallies=tallies,
        trials=trials,
        batches=batch_id - start_batch,
        avf=vulnerable / max(trials, 1),
        avf_interval=(stopping.post_stratified(_strata_pairs(), confidence)
                      if stratified and stopping.strata_cover_trials(
                          strata, trials)
                      else stopping.wilson(vulnerable, trials, confidence)),
        sdc_interval=stopping.wilson(
            int(tallies[C.OUTCOME_SDC]), trials, confidence),
        wall_seconds=wall,
        trials_per_second=(trials - int(0 if initial_tallies is None
                                        else initial_tallies.sum())) / wall
        if wall > 0 else float("inf"),
        converged=converged,
        strata_tallies=strata,
        tier_trials=tier_trials if dispatcher is not None else None,
        escalation_rate=(
            float(tier_trials[1:].sum() / max(tier_trials.sum(), 1))
            if dispatcher is not None else 0.0),
    )
