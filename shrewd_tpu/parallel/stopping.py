"""Confidence-interval estimation and early stopping.

The statistical machinery of the north star ("wall-clock to AVF ±1% CI"):
AVF is a binomial proportion over trials; the campaign stops when the
interval half-width reaches the target.  Wilson intervals avoid the Wald
interval's collapse at p→0/1 (SDC rates near 1e-5 in the replication DSE),
and stopping on a *fixed precision* rather than sequential significance keeps
the early-stop bias negligible (SURVEY §7 hard part #5).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import numpy as np

from shrewd_tpu.ops import classify as C

# two-sided z for common confidence levels; non-tabulated confidences are
# bisected once and memoized here — the 80-iteration erf bisection used to
# rerun on EVERY should_stop call (once per batch per campaign for e.g.
# confidence=0.975)
_Z = {0.90: 1.6448536269514722, 0.95: 1.959963984540054,
      0.99: 2.5758293035489004}


def z_value(confidence: float) -> float:
    z = _Z.get(confidence)
    if z is not None:
        return z
    # Acklam-style rational approximation is overkill here; bisect the
    # complementary error function instead (exact enough for stopping).
    lo, hi = 0.0, 10.0
    target = (1.0 + confidence) / 2.0
    for _ in range(80):
        mid = (lo + hi) / 2
        if 0.5 * (1.0 + math.erf(mid / math.sqrt(2.0))) < target:
            lo = mid
        else:
            hi = mid
    z = (lo + hi) / 2
    _Z[confidence] = z
    return z


class Interval(NamedTuple):
    estimate: float    # point estimate (Wilson center is used for bounds)
    lo: float
    hi: float

    @property
    def halfwidth(self) -> float:
        return (self.hi - self.lo) / 2.0


def wilson(successes: float, trials: float, confidence: float = 0.95) -> Interval:
    """Wilson score interval for a binomial proportion."""
    if trials <= 0:
        return Interval(float("nan"), 0.0, 1.0)
    z = z_value(confidence)
    p = successes / trials
    denom = 1.0 + z * z / trials
    center = (p + z * z / (2 * trials)) / denom
    margin = (z / denom) * math.sqrt(
        p * (1 - p) / trials + z * z / (4 * trials * trials))
    return Interval(p, max(0.0, center - margin), min(1.0, center + margin))


def should_stop(successes: float, trials: float, target_halfwidth: float,
                confidence: float = 0.95, min_trials: int = 1000) -> bool:
    """The campaign stopping rule: enough trials AND CI tight enough."""
    if trials < min_trials:
        return False
    return wilson(successes, trials, confidence).halfwidth <= target_halfwidth


def trials_needed(p_guess: float, target_halfwidth: float,
                  confidence: float = 0.95) -> int:
    """Planning estimate: trials for a Wald-width target at proportion p."""
    z = z_value(confidence)
    p = min(max(p_guess, 1e-12), 1 - 1e-12)
    return int(math.ceil(z * z * p * (1 - p) / (target_halfwidth ** 2)))


def post_stratified(tallies_h, confidence: float = 0.95) -> Interval:
    """Post-stratified proportion estimate from per-stratum (vulnerable,
    trials) counts: ``tallies_h`` is a sequence of (successes_h, n_h).

    Stratum weights are the OBSERVED allocation shares W_h = n_h / n (the
    sampler draws strata at their natural rates, so the observed shares are
    unbiased weights); the estimator is p̂ = Σ W_h p̂_h with variance
    Σ W_h² p̃_h(1-p̃_h)/n_h — ≤ the pooled binomial variance when
    per-stratum rates differ (classic post-stratification; normal-approx
    interval, adequate at campaign trial counts).  The variance uses the
    Agresti-Coull-adjusted p̃_h = (s_h+2)/(n_h+4), never the raw p̂_h: a
    tiny stratum with all-vulnerable or all-masked trials would otherwise
    contribute ZERO variance and stop the campaign before the claimed
    coverage holds.  Empty strata contribute nothing."""
    n = sum(nh for _s, nh in tallies_h)
    if n <= 0:
        return Interval(float("nan"), 0.0, 1.0)
    z = z_value(confidence)
    p = 0.0
    var = 0.0
    for s_h, n_h in tallies_h:
        if n_h <= 0:
            continue
        w = n_h / n
        p += w * (s_h / n_h)
        pt = (s_h + 2.0) / (n_h + 4.0)
        var += w * w * pt * (1.0 - pt) / n_h
    margin = z * math.sqrt(var)
    return Interval(p, max(0.0, p - margin), min(1.0, p + margin))


def should_stop_stratified(tallies_h, target_halfwidth: float,
                           confidence: float = 0.95,
                           min_trials: int = 1000) -> bool:
    """Stratified stopping rule (post_stratified interval vs target)."""
    n = sum(nh for _s, nh in tallies_h)
    if n < min_trials:
        return False
    return post_stratified(tallies_h,
                           confidence).halfwidth <= target_halfwidth


def pairs_from_strata(strata) -> list:
    """(N_STRATA, N_OUTCOMES) tally → [(vulnerable_h, n_h), ...] for
    post_stratified/should_stop_stratified.  The single definition of
    "vulnerable" for stratified stopping — the orchestrator and
    run_until_ci must not diverge on it."""
    s = np.asarray(strata)
    vul_h = s[:, C.OUTCOME_SDC] + s[:, C.OUTCOME_DUE]
    return list(zip(vul_h.tolist(), s.sum(axis=1).tolist()))


def strata_cover_trials(strata, trials: int) -> bool:
    """True iff the strata history accounts for every counted trial (the
    gate for using the stratified rule over pooled Wilson)."""
    return strata is not None and int(np.asarray(strata).sum()) == trials


def eta_trials(vulnerable: int, trials: int, strata, stratify: bool,
               confidence: float, target_halfwidth: float,
               min_trials: int) -> float:
    """Trials the stopping rule still plausibly needs — the half-width-
    trajectory estimate (Wilson hw ~∝ 1/√n at a stable p̂, so distance-
    to-target is ~ n·((hw/target)² − 1)), floored by ``min_trials``.
    THE single convergence-distance estimator: the orchestrator's
    adaptive sync interval and until-CI planner consume it, and
    ``obs/metrics`` publishes it per tenant so the federation gateway
    routes and estimates deadlines on the same number the stopping rule
    would act on.  0.0 means the rule could stop now."""
    need = float(min_trials - trials)
    if trials > 0:
        hw = live_halfwidth(vulnerable, trials, strata, stratify,
                            confidence)
        target = float(target_halfwidth)
        if hw > target > 0:
            need = max(need, trials * ((hw / target) ** 2 - 1.0))
    return max(0.0, need)


def live_halfwidth(vulnerable: int, trials: int, strata,
                   stratify: bool, confidence: float) -> float:
    """The half-width the live stopping rule actually tracks: the
    post-stratified (Agresti-Coull) estimator when the campaign
    stratifies and the strata history covers every counted trial, pooled
    Wilson otherwise — the same selection the orchestrator's convergence
    check applies, so any published convergence distance (metrics
    snapshots, the trials-needed planner) agrees with the rule that
    decides stopping."""
    if stratify and strata_cover_trials(strata, trials):
        return post_stratified(pairs_from_strata(strata),
                               confidence).halfwidth
    return wilson(vulnerable, trials, confidence).halfwidth


def merged_fold(lanes_by_shard, stratify: bool, confidence: float,
                target_halfwidth: float, min_trials: int) -> dict:
    """Order-fixed fold of per-shard cumulative lane reports into the
    merged campaign trajectory (the federation gateway's single-campaign
    sharding merge, ``federation/gateway.py``).

    ``lanes_by_shard`` maps shard index → {lane: {"tallies", "trials",
    "strata"}} where each report is that shard's CUMULATIVE count over
    its round-robin stripe of the parent's frozen batch-id space.  The
    fold sums in ascending shard index — int64 tally addition is exact,
    so the fixed order is what makes the recorded merge trajectory
    deterministic under WAL replay (the psum-vs-shard invariant
    ``integrity.py`` checks per batch, lifted to the fleet level).
    Because shard i of N serves global ids {i, i+N, ...}, a balanced
    fold (every shard r batches deep) covers exactly the solo prefix
    {0..rN−1}: the merged tallies are bit-identical to the solo
    accumulation at the same trial count.

    Returns {lane: {"tallies": [...], "trials", "strata", "halfwidth",
    "converged"}} — JSON-ready, evaluated with the SAME rule selection
    as ``live_halfwidth`` so the merged stopping decision is the one the
    solo campaign's convergence check would have made."""
    merged: dict = {}
    has_strata: dict = {}
    for idx in sorted(lanes_by_shard):
        for lane, rep in lanes_by_shard[idx].items():
            m = merged.setdefault(lane, {"tallies": None, "trials": 0,
                                         "strata": None})
            t = np.asarray(rep["tallies"], dtype=np.int64)
            m["tallies"] = t if m["tallies"] is None else m["tallies"] + t
            m["trials"] += int(rep["trials"])
            s = rep.get("strata")
            if s is None:
                has_strata[lane] = False
            elif has_strata.setdefault(lane, True):
                sa = np.asarray(s, dtype=np.int64)
                m["strata"] = (sa if m["strata"] is None
                               else m["strata"] + sa)
    for lane, m in merged.items():
        strata = (m["strata"].tolist()
                  if has_strata.get(lane) and m["strata"] is not None
                  else None)
        vul = int(m["tallies"][C.OUTCOME_SDC]
                  + m["tallies"][C.OUTCOME_DUE])
        hw = live_halfwidth(vul, m["trials"], strata, stratify, confidence)
        m["tallies"] = m["tallies"].tolist()
        m["strata"] = strata
        m["avf"] = vul / max(m["trials"], 1)
        m["halfwidth"] = hw
        m["converged"] = bool(m["trials"] > 0
                              and m["trials"] >= min_trials
                              and hw <= float(target_halfwidth))
    return merged


# --------------------------------------------------------------------------
# device mirrors (the device-resident run-until-CI step)
# --------------------------------------------------------------------------
#
# jnp mirrors of the two stopping half-widths, traced into the
# ``lax.while_loop`` until-CI step (parallel/campaign.py
# ``_build_until_ci_step``) so the convergence decision runs where the
# cumulative tallies live instead of costing a device→host transfer per
# check.  Each mirror follows the HOST formula's operation order so the
# only divergence is float32-vs-float64 rounding; the host↔device
# decision-parity pin (tests/test_until_ci.py) sweeps campaign-realistic
# tallies and requires the stop/continue DECISION to match exactly.  A
# tally within float32 epsilon of the target boundary could in principle
# flip either way: an EARLY device stop is caught by the host rule's
# re-evaluation of the believed cumulative tallies (cost: one extra
# super-interval, never a wrong interval), while a LATE device stop
# keeps the extra consumed batches — still valid frozen-key trials with
# an honest host-computed CI over everything counted, but a consumed
# count above the serial loop's.  The parity pin is what makes both
# directions empirically vacuous at campaign-realistic tallies; it is a
# pin, not a proof.  Import note: this module already imports jax
# transitively (ops.classify, hoisted for pairs_from_strata); the
# mirrors defer jax.numpy to call time only because they run during a
# trace, not to keep the module jax-free.


def wilson_halfwidth_device(successes, trials, z):
    """``wilson(successes, trials).halfwidth`` as traceable float32 math
    (``successes``/``trials`` int32 scalars, ``z`` a float32 scalar)."""
    import jax.numpy as jnp

    n = jnp.maximum(trials, 1).astype(jnp.float32)
    s = successes.astype(jnp.float32)
    p = s / n
    zz = z * z
    denom = 1.0 + zz / n
    center = (p + zz / (2.0 * n)) / denom
    margin = (z / denom) * jnp.sqrt(
        p * (1.0 - p) / n + zz / (4.0 * n * n))
    lo = jnp.maximum(0.0, center - margin)
    hi = jnp.minimum(1.0, center + margin)
    return (hi - lo) / 2.0


def post_stratified_halfwidth_device(strata, z):
    """``post_stratified(pairs_from_strata(strata)).halfwidth`` as
    traceable float32 math over the (N_STRATA, N_OUTCOMES) cumulative
    tally: observed-share weights, Agresti-Coull-adjusted per-stratum
    variance, empty strata contributing nothing (the host's zero-variance
    guard, mirrored with a where-mask instead of a continue)."""
    import jax.numpy as jnp

    n_h = strata.sum(axis=1).astype(jnp.float32)
    s_h = (strata[:, C.OUTCOME_SDC]
           + strata[:, C.OUTCOME_DUE]).astype(jnp.float32)
    n = jnp.maximum(n_h.sum(), 1.0)
    nz = n_h > 0
    safe_n_h = jnp.maximum(n_h, 1.0)
    w = jnp.where(nz, n_h / n, 0.0)
    p = jnp.sum(jnp.where(nz, w * (s_h / safe_n_h), 0.0))
    pt = (s_h + 2.0) / (n_h + 4.0)
    var = jnp.sum(jnp.where(nz, w * w * pt * (1.0 - pt) / safe_n_h, 0.0))
    margin = z * jnp.sqrt(var)
    lo = jnp.maximum(0.0, p - margin)
    hi = jnp.minimum(1.0, p + margin)
    return (hi - lo) / 2.0


def should_stop_device(halfwidth, trials, target_halfwidth, min_trials):
    """The stopping decision on device: enough trials AND CI tight
    enough — the integer gates are exact mirrors of ``should_stop``; only
    the half-width comparison carries float32 rounding."""
    return (trials >= min_trials) & (halfwidth <= target_halfwidth)
