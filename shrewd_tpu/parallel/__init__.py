from shrewd_tpu.parallel import campaign, mesh, stopping
from shrewd_tpu.parallel.campaign import (CampaignResult, ShardedCampaign,
                                          run_until_ci)
from shrewd_tpu.parallel.mesh import (TRIAL_AXIS, init_distributed, make_mesh,
                                      shard_keys)

__all__ = ["CampaignResult", "ShardedCampaign", "TRIAL_AXIS", "campaign",
           "init_distributed", "make_mesh", "mesh", "run_until_ci",
           "shard_keys", "stopping"]
