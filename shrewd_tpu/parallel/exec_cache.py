"""Process-wide executable cache: compile each campaign step once.

Every ``ShardedCampaign`` used to build its *own* ``jax.jit(shard_map(...))``
closures, so jax's function-identity jit cache never matched across
instances: the CPU fallback tier, a resumed orchestrator in the same
process, the canary battery's tier functions, and bench's warm-up/timed
pairs each re-traced and re-compiled an identical program over the same
trace.  This module is the shared registry those builders route through:
executables are keyed by *content* — a digest of the trace arrays plus the
kernel config, the structure, the mesh fingerprint, and the step kind — so
any two campaigns computing the same pure function share one compiled
callable, whichever kernel instance built it first.

Two cache surfaces:

- ``get(key, owner, build)`` — memoize a jitted callable.  ``owner`` is the
  object whose lifetime the entry's correctness depends on (the kernel): a
  weak reference guards against ``id()`` reuse after garbage collection.
- ``get_aot(key, owner, build, example_args)`` — the AOT variant for the
  pipelined interval steps: ``build()``'s jitted callable is
  ``lower(...).compile()``d eagerly at build time, so the whole compile cost
  lands before the campaign loop starts (and is skipped entirely on re-runs
  when the persistent compilation cache below is enabled).  Falls back to
  the plain jitted callable when AOT lowering is unavailable.

``enable_persistent_cache(dir)`` opts into jax's on-disk compilation cache
(``jax_compilation_cache_dir``) so *re-runs and resumes in new processes*
skip retrace/recompile too.

Import discipline: jax-free at module import (the cache is pure host-side
bookkeeping; jax enters only inside ``enable_persistent_cache`` and the
callers' build functions).
"""

from __future__ import annotations

import hashlib
import json
import weakref
from collections import OrderedDict
from typing import Callable

import numpy as np

from shrewd_tpu.utils import debug

debug.register_flag("ExecCache", "shared executable cache hits/misses")

#: entries kept before least-recently-used eviction — each entry pins its
#: builder kernel (trace constants) through the jit closure, so an
#: unbounded cache would leak every trace a long session ever touched
MAX_ENTRIES = 64


class ExecutableCache:
    """LRU registry of compiled campaign steps (see module docstring)."""

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self.max_entries = int(max_entries)
        # key -> (owner weakref | None, callable)
        self._entries: OrderedDict = OrderedDict()
        self.compiled = 0       # cache misses that built a new executable
        self.reused = 0         # cache hits
        self.aot = 0            # ... of the compiled ones, AOT-lowered
        self.evicted = 0

    def _hit(self, key, owner):
        ent = self._entries.get(key)
        if ent is None:
            return None
        ref, fn = ent
        if ref is not None and ref() is None:
            # the owner died and its id() may since have been reused by a
            # different object — the digest alone can no longer prove the
            # entry matches, so treat as a miss and rebuild
            del self._entries[key]
            return None
        self._entries.move_to_end(key)
        self.reused += 1
        debug.dprintf("ExecCache", "reuse %s", key[0] if key else key)
        return fn

    def _store(self, key, owner, fn):
        ref = None
        if owner is not None:
            try:
                ref = weakref.ref(owner)
            except TypeError:       # unweakrefable owner: entry unguarded
                ref = None
        self._entries[key] = (ref, fn)
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            self.evicted += 1
        return fn

    def get(self, key, owner, build: Callable[[], Callable]):
        """The memoized callable for ``key`` (built via ``build()`` on
        miss).  ``owner``: the object whose ``id()`` participates in the
        key's digest chain (weakly held; a dead owner invalidates)."""
        fn = self._hit(key, owner)
        if fn is not None:
            return fn
        self.compiled += 1
        debug.dprintf("ExecCache", "compile %s", key[0] if key else key)
        return self._store(key, owner, build())

    def get_aot(self, key, owner, build: Callable[[], Callable],
                example_args: tuple):
        """Like ``get`` but the built callable is AOT lower/compile'd
        against ``example_args`` so the compile happens NOW (before the
        campaign loop), not inside the first timed dispatch.  Lowering
        failures degrade to the plain jitted callable — AOT is a latency
        optimization, never a correctness dependency."""
        fn = self._hit(key, owner)
        if fn is not None:
            return fn
        self.compiled += 1
        jit_fn = build()
        try:
            compiled = jit_fn.lower(*example_args).compile()
            self.aot += 1
            debug.dprintf("ExecCache", "AOT compile %s",
                          key[0] if key else key)
        except Exception as e:  # noqa: BLE001 — no AOT on this path/version
            debug.dprintf("ExecCache", "AOT lowering unavailable (%s) — "
                          "falling back to jit for %s", e, key)
            return self._store(key, owner, jit_fn)
        return self._store(key, owner, compiled)

    def stats(self) -> dict:
        return {"compiled": self.compiled, "reused": self.reused,
                "aot": self.aot, "evicted": self.evicted,
                "entries": len(self._entries)}

    def clear(self) -> None:
        self._entries.clear()


_GLOBAL: ExecutableCache | None = None


def cache() -> ExecutableCache:
    """The per-process shared cache (campaigns, tiers, bench all route
    through the same one — that is the whole point)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = ExecutableCache()
    return _GLOBAL


# --------------------------------------------------------------------------
# key fingerprints
# --------------------------------------------------------------------------

_TRACE_FIELDS = ("opcode", "dst", "src1", "src2", "imm", "taken",
                 "init_reg", "init_mem")


def trace_digest(trace) -> str:
    """Content digest of a trace's arrays — the part of an executable's
    identity that ``id()`` cannot provide (two ``build_trace()`` calls on
    the same spec yield distinct objects with identical content, and their
    compiled steps are interchangeable).  Cached on the trace object."""
    got = getattr(trace, "_exec_cache_digest", None)
    if got is not None:
        return got
    h = hashlib.sha1()
    for name in _TRACE_FIELDS:
        arr = getattr(trace, name, None)
        if arr is None:
            continue
        a = np.asarray(arr)
        h.update(name.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    for name in ("n", "nphys", "mem_words"):
        h.update(f"{name}={getattr(trace, name, None)}".encode())
    digest = h.hexdigest()
    try:
        trace._exec_cache_digest = digest
    except Exception:  # noqa: BLE001 — unsettable attr: recompute next time
        pass
    return digest


def kernel_fingerprint(kernel) -> tuple:
    """Stable identity of the pure computation a kernel performs: trace
    content + full config.  Kernels with equal fingerprints compute
    identical outcome functions, so their compiled steps interchange."""
    cfgs = []
    for attr in ("cfg", "minor_cfg"):
        c = getattr(kernel, attr, None)
        if c is None:
            cfgs.append(None)
        else:
            try:
                cfgs.append(json.dumps(c.to_dict(), sort_keys=True,
                                       default=str))
            except Exception:  # noqa: BLE001 — config without to_dict:
                cfgs.append(repr(c))
    trace = getattr(kernel, "trace", None)
    tdig = trace_digest(trace) if trace is not None else f"id{id(kernel)}"
    # a memmap'd kernel classifies mem faults differently (VA-trap model);
    # no digest covers the memmap, so fall back to instance identity there
    if getattr(kernel, "memmap", None) is not None:
        tdig += f"+memmap{id(kernel.memmap)}"
    return (type(kernel).__name__, tdig, tuple(cfgs))


def mesh_fingerprint(mesh) -> tuple | None:
    if mesh is None:               # mesh-free executables (sampler jits)
        return None
    devs = np.asarray(mesh.devices).reshape(-1)
    return (np.asarray(mesh.devices).shape,
            tuple(getattr(d, "id", i) for i, d in enumerate(devs)),
            tuple(mesh.axis_names))


def step_key(kernel, mesh, structure: str, kind: str, **flags) -> tuple:
    """The full cache key for one campaign step executable."""
    return (kind, kernel_fingerprint(kernel), mesh_fingerprint(mesh),
            str(structure), tuple(sorted(flags.items())))


# --------------------------------------------------------------------------
# persistent (on-disk) compilation cache
# --------------------------------------------------------------------------

def enable_persistent_cache(path: str) -> bool:
    """Opt into jax's on-disk compilation cache at ``path`` so re-runs and
    resumes in NEW processes skip retrace/recompile of unchanged steps.
    Returns True when the backend accepted the setting; best-effort —
    an old jax without the knobs degrades to in-process caching only."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
    except Exception as e:  # noqa: BLE001 — no persistent cache support
        debug.dprintf("ExecCache",
                      "persistent compilation cache unavailable: %s", e)
        return False
    # default thresholds skip sub-second compiles — campaign steps on CPU
    # test shapes are exactly those, so lower both floors where supported
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 — older jax: keep its defaults
            pass
    return True
