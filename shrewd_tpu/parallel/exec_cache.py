"""Process-wide executable cache: compile each campaign step once.

Every ``ShardedCampaign`` used to build its *own* ``jax.jit(shard_map(...))``
closures, so jax's function-identity jit cache never matched across
instances: the CPU fallback tier, a resumed orchestrator in the same
process, the canary battery's tier functions, and bench's warm-up/timed
pairs each re-traced and re-compiled an identical program over the same
trace.  This module is the shared registry those builders route through:
executables are keyed by *content* — a digest of the trace arrays plus the
kernel config, the structure, the mesh fingerprint, and the step kind — so
any two campaigns computing the same pure function share one compiled
callable, whichever kernel instance built it first.

Two cache surfaces:

- ``get(key, owner, build)`` — memoize a jitted callable.  ``owner`` is the
  object whose lifetime the entry's correctness depends on (the kernel): a
  weak reference guards against ``id()`` reuse after garbage collection.
- ``get_aot(key, owner, build, example_args)`` — the AOT variant for the
  pipelined interval steps: ``build()``'s jitted callable is
  ``lower(...).compile()``d eagerly at build time, so the whole compile cost
  lands before the campaign loop starts (and is skipped entirely on re-runs
  when the persistent compilation cache below is enabled).  Falls back to
  the plain jitted callable when AOT lowering is unavailable.

``enable_persistent_cache(dir)`` opts into jax's on-disk compilation cache
(``jax_compilation_cache_dir``) so *re-runs and resumes in new processes*
skip retrace/recompile too.

Import discipline: jax-free at module import (the cache is pure host-side
bookkeeping; jax enters only inside ``enable_persistent_cache`` and the
callers' build functions).
"""

from __future__ import annotations

import hashlib
import json
import weakref
from collections import OrderedDict
from typing import Callable

import numpy as np

from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.utils import debug

debug.register_flag("ExecCache", "shared executable cache hits/misses")

#: entries kept before least-recently-used eviction — each entry pins its
#: builder kernel (trace constants) through the jit closure, so an
#: unbounded cache would leak every trace a long session ever touched
MAX_ENTRIES = 64


class AdmissionError(RuntimeError):
    """A strict-mode replay-safety audit refused this executable (see
    ``.certificate`` for the evidence)."""

    def __init__(self, msg: str, certificate: dict | None = None):
        super().__init__(msg)
        self.certificate = certificate or {}


# the installed auditor (analysis/jaxpr_audit.StepAuditor or compatible):
# ``auditor(fn, example_args, key) -> certificate dict`` — raises to
# refuse admission.  None (the default) = zero-overhead pass-through.
_AUDITOR = None


def install_auditor(auditor) -> None:
    """Certify every executable admitted from now on: the AOT path audits
    at admission (example args in hand), the plain-jit path on its first
    eager call.  Certificates are cached content-keyed alongside the
    entries (``cache().certificates``)."""
    global _AUDITOR
    _AUDITOR = auditor


def clear_auditor() -> None:
    global _AUDITOR
    _AUDITOR = None


def current_auditor():
    return _AUDITOR


class _LowerMemo:
    """A jitted callable with its ``lower(*args)`` memoized — the AOT
    admission path audits (jaxpr + HLO) and then compiles, and both want
    the same lowering."""

    def __init__(self, fn):
        self._fn = fn
        self._lowered = None

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    def lower(self, *args):
        if self._lowered is None:
            self._lowered = self._fn.lower(*args)
        return self._lowered


class ExecutableCache:
    """LRU registry of compiled campaign steps (see module docstring)."""

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self.max_entries = int(max_entries)
        # key -> (owner weakref | None, callable)
        self._entries: OrderedDict = OrderedDict()
        self.compiled = 0       # cache misses that built a new executable
        self.reused = 0         # cache hits
        self.aot = 0            # ... of the compiled ones, AOT-lowered
        self.evicted = 0
        # content key digest -> replay-safety certificate (when an
        # auditor is installed) — the ahead-of-time evidence that the
        # executable honors the frozen-key/one-transfer contracts
        self.certificates: dict[str, dict] = {}
        self.refused = 0        # strict-mode admission refusals
        # content key digest -> {"kind", "hits", "misses", "evictions"}:
        # the per-key half of the hit ledger (campaign.perf.exec_cache
        # stats).  Cross-tenant compile dedupe must be OBSERVABLE — a
        # second tenant admitted over a shared window should show pure
        # hits on the window's step keys, and the fleet test asserts it.
        # Survives eviction deliberately: an evicted-then-recompiled key
        # is a churn signal, not a fresh key.
        self.key_stats: dict[str, dict] = {}

    def _key_stat(self, key) -> dict:
        return self.key_stats.setdefault(
            key_digest(key),
            {"kind": str(key[0]) if key else "step",
             "hits": 0, "misses": 0, "evictions": 0})

    def _hit(self, key, owner):
        ent = self._entries.get(key)
        if ent is None:
            return None
        ref, fn = ent
        if ref is not None and ref() is None:
            # the owner died and its id() may since have been reused by a
            # different object — the digest alone can no longer prove the
            # entry matches, so treat as a miss and rebuild (and drop the
            # certificate with the entry: evidence about a dead
            # executable must not count toward the rebuilt one)
            del self._entries[key]
            self.certificates.pop(key_digest(key), None)
            return None
        self._entries.move_to_end(key)
        self.reused += 1
        self._key_stat(key)["hits"] += 1
        obs_trace.tracer().emit(
            "exec_cache_hit", cat="exec_cache",
            kind=str(key[0]) if key else "step", digest=key_digest(key))
        debug.dprintf("ExecCache", "reuse %s", key[0] if key else key)
        return fn

    def _store(self, key, owner, fn):
        ref = None
        if owner is not None:
            try:
                ref = weakref.ref(owner)
            except TypeError:       # unweakrefable owner: entry unguarded
                ref = None
        self._entries[key] = (ref, fn)
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            old_key, _ = self._entries.popitem(last=False)
            # the certificate is evidence ABOUT a cached executable: it
            # leaves with its entry (the count must track live entries)
            self.certificates.pop(key_digest(old_key), None)
            self.evicted += 1
            self._key_stat(old_key)["evictions"] += 1
        return fn

    def _audit(self, key, fn, example_args) -> None:
        """Run the installed auditor and cache its certificate.  Only a
        deliberate REFUSAL (the auditor's own error type, carrying its
        certificate) becomes ``AdmissionError``; an auditor that merely
        crashed proves nothing, so infrastructure failures are recorded
        and the executable admits — a warn-mode run must never abort
        because the auditor couldn't analyze something."""
        auditor = _AUDITOR
        if auditor is None:
            return
        try:
            cert = auditor(fn, example_args, key)
        except AdmissionError:
            self.refused += 1
            raise
        except Exception as e:  # noqa: BLE001
            if hasattr(e, "certificate"):
                # the auditor's own refusal type (CertificationError):
                # normalize so callers see one refusal type
                self.refused += 1
                raise AdmissionError(
                    f"executable refused by replay-safety audit: {e}",
                    e.certificate) from e
            debug.dprintf("ExecCache", "audit of %s errored (%s) — "
                          "admitting unaudited", key[0] if key else key, e)
            self.certificates[key_digest(key)] = {
                "kind": str(key[0]) if key else "step", "ok": False,
                "audit_error": str(e), "violations": []}
            return
        self.certificates[key_digest(key)] = cert

    def _audited_on_first_call(self, key, fn):
        """Wrap a plain jitted callable so its FIRST eager call (no
        ambient trace — auditing mid-trace would trace a trace) runs the
        replay-safety audit on the real arguments.  Zero wrapping when no
        auditor is installed."""
        if _AUDITOR is None:
            return fn
        state = {"done": False, "refusal": None}

        def audited(*args, **kwargs):
            if state["refusal"] is not None:
                # a refused executable STAYS refused: holders that cached
                # this wrapper (kernel._shared_jits, chunk fns) must not
                # execute it just because the first caller caught the
                # error — e.g. a resilience ladder retrying the "failed"
                # dispatch
                raise state["refusal"]
            if not state["done"]:
                import jax

                if jax.core.trace_state_clean():
                    state["done"] = True
                    if kwargs:
                        # make_jaxpr takes positional args only — don't
                        # silently skip: an unauditable call shape is
                        # recorded as evidence, never as certified
                        self.certificates[key_digest(key)] = {
                            "kind": str(key[0]) if key else "step",
                            "ok": False, "violations": [],
                            "audit_error": "called with keyword "
                            "arguments — unauditable"}
                        return fn(*args, **kwargs)
                    try:
                        self._audit(key, fn, args)
                    except AdmissionError as e:
                        # refusal evicts the entry: the executable is not
                        # admitted, and a later get() re-refuses afresh
                        state["refusal"] = e
                        self._entries.pop(key, None)
                        raise
            return fn(*args, **kwargs)

        return audited

    def get(self, key, owner, build: Callable[[], Callable]):
        """The memoized callable for ``key`` (built via ``build()`` on
        miss).  ``owner``: the object whose ``id()`` participates in the
        key's digest chain (weakly held; a dead owner invalidates)."""
        fn = self._hit(key, owner)
        if fn is not None:
            return fn
        self.compiled += 1
        self._key_stat(key)["misses"] += 1
        obs_trace.tracer().emit(
            "exec_cache_compile", cat="exec_cache",
            kind=str(key[0]) if key else "step", digest=key_digest(key),
            aot=False)
        debug.dprintf("ExecCache", "compile %s", key[0] if key else key)
        return self._store(key, owner,
                           self._audited_on_first_call(key, build()))

    def get_aot(self, key, owner, build: Callable[[], Callable],
                example_args: tuple):
        """Like ``get`` but the built callable is AOT lower/compile'd
        against ``example_args`` so the compile happens NOW (before the
        campaign loop), not inside the first timed dispatch.  Lowering
        failures degrade to the plain jitted callable — AOT is a latency
        optimization, never a correctness dependency."""
        fn = self._hit(key, owner)
        if fn is not None:
            return fn
        self.compiled += 1
        self._key_stat(key)["misses"] += 1
        obs_trace.tracer().emit(
            "exec_cache_compile", cat="exec_cache",
            kind=str(key[0]) if key else "step", digest=key_digest(key),
            aot=True)
        jit_fn = build()
        # the AOT path has example args in hand: certify at ADMISSION —
        # a strict-mode violation refuses the executable before the
        # compile is even attempted (and before any trial runs).  The
        # lowering is memoized so the auditor's HLO check and the AOT
        # compile below share ONE lower() instead of paying the biggest
        # executables' trace cost twice
        lowerable = (_LowerMemo(jit_fn) if hasattr(jit_fn, "lower")
                     else jit_fn)
        self._audit(key, lowerable, example_args)
        try:
            compiled = lowerable.lower(*example_args).compile()
            self.aot += 1
            debug.dprintf("ExecCache", "AOT compile %s",
                          key[0] if key else key)
        except Exception as e:  # noqa: BLE001 — no AOT on this path/version
            debug.dprintf("ExecCache", "AOT lowering unavailable (%s) — "
                          "falling back to jit for %s", e, key)
            return self._store(key, owner, jit_fn)
        return self._store(key, owner, compiled)

    def stats(self) -> dict:
        return {"compiled": self.compiled, "reused": self.reused,
                "aot": self.aot, "evicted": self.evicted,
                "entries": len(self._entries),
                "certified": len(self.certificates),
                "refused": self.refused}

    def per_key_stats(self) -> dict:
        """Per-content-key hit/miss/evict counters keyed by the short key
        digest (``campaign.perf.exec_cache_keys``): the observable form
        of cross-tenant compile dedupe — a tenant co-scheduled over a
        window another tenant already compiled shows hits and ZERO new
        misses on that window's step keys."""
        return {d: dict(s) for d, s in self.key_stats.items()}

    def clear(self) -> None:
        self._entries.clear()
        self.certificates.clear()
        self.key_stats.clear()


_GLOBAL: ExecutableCache | None = None


def cache() -> ExecutableCache:
    """The per-process shared cache (campaigns, tiers, bench all route
    through the same one — that is the whole point)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = ExecutableCache()
    return _GLOBAL


# --------------------------------------------------------------------------
# shared kernel registry (heavyweight host objects, not executables)
# --------------------------------------------------------------------------

#: kernels kept before LRU eviction — each pins its trace arrays and its
#: materialized goldens, so the bound is deliberately small
KERNEL_CACHE_MAX = 8

_KERNELS: OrderedDict = OrderedDict()


def shared_kernel(trace, cfg_fp: str, build: Callable[[], object]):
    """Content-keyed registry of *kernel objects* (TrialKernel & co) —
    the object-level complement of the executable cache.  Two campaigns
    over the same window content and machine config (co-scheduled
    tenants of the multi-tenant fleet, a re-built orchestrator, bench's
    paired arms) share ONE kernel instance: construction cost (golden
    materialization, scoreboard timing) is paid once, and the shared
    instance keeps the executable cache's owner-weakrefs alive across
    tenants.  Safe because a kernel's mutable state is only the running
    escape counters, which every consumer reads as per-dispatch DELTAS
    (orchestrator `_compute_batch`/`_compute_interval`), and dispatch is
    single-threaded per process."""
    key = (trace_digest(trace), cfg_fp)
    kern = _KERNELS.get(key)
    if kern is not None:
        _KERNELS.move_to_end(key)
        debug.dprintf("ExecCache", "shared kernel reuse %s", key[0][:12])
        return kern
    kern = build()
    _KERNELS[key] = kern
    while len(_KERNELS) > KERNEL_CACHE_MAX:
        _KERNELS.popitem(last=False)
    return kern


def clear_kernels() -> None:
    _KERNELS.clear()


# --------------------------------------------------------------------------
# key fingerprints
# --------------------------------------------------------------------------

_TRACE_FIELDS = ("opcode", "dst", "src1", "src2", "imm", "taken",
                 "init_reg", "init_mem")


def trace_digest(trace) -> str:
    """Content digest of a trace's arrays — the part of an executable's
    identity that ``id()`` cannot provide (two ``build_trace()`` calls on
    the same spec yield distinct objects with identical content, and their
    compiled steps are interchangeable).  Cached on the trace object."""
    got = getattr(trace, "_exec_cache_digest", None)
    if got is not None:
        return got
    h = hashlib.sha1()
    for name in _TRACE_FIELDS:
        arr = getattr(trace, name, None)
        if arr is None:
            continue
        a = np.asarray(arr)
        h.update(name.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    for name in ("n", "nphys", "mem_words"):
        h.update(f"{name}={getattr(trace, name, None)}".encode())
    digest = h.hexdigest()
    try:
        trace._exec_cache_digest = digest
    except Exception:  # noqa: BLE001 — unsettable attr: recompute next time
        pass
    return digest


def kernel_fingerprint(kernel) -> tuple:
    """Stable identity of the pure computation a kernel performs: trace
    content + full config.  Kernels with equal fingerprints compute
    identical outcome functions, so their compiled steps interchange."""
    cfgs = []
    for attr in ("cfg", "minor_cfg"):
        c = getattr(kernel, attr, None)
        if c is None:
            cfgs.append(None)
        else:
            try:
                cfgs.append(json.dumps(c.to_dict(), sort_keys=True,
                                       default=str))
            except Exception:  # noqa: BLE001 — config without to_dict:
                cfgs.append(repr(c))
    trace = getattr(kernel, "trace", None)
    tdig = trace_digest(trace) if trace is not None else f"id{id(kernel)}"
    # a memmap'd kernel classifies mem faults differently (VA-trap model);
    # no digest covers the memmap, so fall back to instance identity there
    if getattr(kernel, "memmap", None) is not None:
        tdig += f"+memmap{id(kernel.memmap)}"
    return (type(kernel).__name__, tdig, tuple(cfgs))


def mesh_fingerprint(mesh) -> tuple | None:
    if mesh is None:               # mesh-free executables (sampler jits)
        return None
    devs = np.asarray(mesh.devices).reshape(-1)
    return (np.asarray(mesh.devices).shape,
            tuple(getattr(d, "id", i) for i, d in enumerate(devs)),
            tuple(mesh.axis_names))


def step_key(kernel, mesh, structure: str, kind: str, **flags) -> tuple:
    """The full cache key for one campaign step executable."""
    return (kind, kernel_fingerprint(kernel), mesh_fingerprint(mesh),
            str(structure), tuple(sorted(flags.items())))


def key_digest(key) -> str:
    """Stable short digest of a cache key — how certificates are content-
    keyed alongside their executables (the key already IS the content
    identity; the digest just makes it a JSON-able handle)."""
    return hashlib.sha1(repr(key).encode()).hexdigest()[:16]


# --------------------------------------------------------------------------
# persistent (on-disk) compilation cache
# --------------------------------------------------------------------------

def enable_persistent_cache(path: str) -> bool:
    """Opt into jax's on-disk compilation cache at ``path`` so re-runs and
    resumes in NEW processes skip retrace/recompile of unchanged steps.
    Returns True when the backend accepted the setting; best-effort —
    an old jax without the knobs degrades to in-process caching only."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
    except Exception as e:  # noqa: BLE001 — no persistent cache support
        debug.dprintf("ExecCache",
                      "persistent compilation cache unavailable: %s", e)
        return False
    # default thresholds skip sub-second compiles — campaign steps on CPU
    # test shapes are exactly those, so lower both floors where supported
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 — older jax: keep its defaults
            pass
    return True
