"""Device-mesh helpers.

The framework's scaling fabric: where the reference scales with parallel
event queues (SURVEY §2.12 P1), dist-gem5 TCP barriers (P2), and multisim
process fan-out (P3), the TPU design uses one ``jax.sharding.Mesh`` with a
``trials`` data-parallel axis; collectives (psum of tallies) ride ICI/DCN and
the explicit barrier machinery disappears (SURVEY §5.8).

Multi-host: call ``init_distributed()`` once per process before mesh
creation — the ``jax.distributed`` analog of dist-gem5's launcher handshake
(``util/dist/gem5-dist.sh``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                            # public name, jax ≥ 0.6
    from jax import shard_map
except ImportError:             # 0.4.x home
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map_expt

    # 0.4.x's replication checker false-positives on scan carries inside
    # psum-reducing bodies (the taint/device kernels) — the error text
    # itself prescribes check_rep=False; out_specs still enforce the
    # sharding contract
    shard_map = functools.partial(_shard_map_expt, check_rep=False)

TRIAL_AXIS = "trials"


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialize multi-host JAX (no-op when single-process).

    Replaces the reference's hand-rolled TCP barrier layer
    (``dev/net/dist_iface.hh:102``, ``tcp_iface.hh:62``): after this, XLA
    collectives provide synchronization implicitly.
    """
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(devices=None) -> Mesh:
    """A 1-D mesh over all (or the given) devices on the trial axis.

    Trials are embarrassingly parallel, so one DP axis is the natural
    topology; tallies reduce with a single psum. A 2-D (dp × structure)
    mesh is a later refinement once per-structure campaigns co-schedule.
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (TRIAL_AXIS,))


def round_up_to_mesh(n: int, mesh_size: int) -> int:
    """Smallest multiple of ``mesh_size`` that is >= ``n``.

    The plan-level fix for the ``shard_keys`` divisibility requirement:
    the orchestrator rounds its plan's batch_size up through this (with a
    warning) instead of crashing mid-campaign — required once elastic
    re-meshing can shrink the device count under a running plan.  The
    hard raise in ``shard_keys`` stays: an explicit low-level call with a
    non-divisible batch is a caller bug, not a plan to repair."""
    if mesh_size <= 0:
        raise ValueError(f"mesh size must be positive, got {mesh_size}")
    return -(-int(n) // int(mesh_size)) * int(mesh_size)


def shard_keys(mesh: Mesh, keys: jax.Array) -> jax.Array:
    """Place a per-trial key batch sharded across the trial axis.

    Multi-host: every process computes the same (deterministic) global key
    batch, and each contributes its addressable shards — the data-placement
    half of what dist-gem5 does with explicit TCP packet forwarding
    (``dev/net/dist_iface.hh:102``); typed PRNG keys go through
    key_data/wrap_key_data since process-local assembly needs a raw view."""
    n = keys.shape[0]
    if n % mesh.size:
        raise ValueError(f"batch size {n} not divisible by mesh size {mesh.size}")
    if jax.process_count() > 1:
        data = np.asarray(jax.random.key_data(keys))
        spec = P(TRIAL_AXIS, *([None] * (data.ndim - 1)))
        arr = jax.make_array_from_callback(
            data.shape, NamedSharding(mesh, spec), lambda idx: data[idx])
        return jax.random.wrap_key_data(arr)
    return jax.device_put(keys, NamedSharding(mesh, P(TRIAL_AXIS)))


def shard_batch_stack(mesh: Mesh, arr) -> jax.Array:
    """Place a stacked per-batch array (S, B, ...) sharded on the B axis —
    the sync-interval analog of ``shard_keys`` (raw arrays only: the
    pipelined engine ships PRNG key *data* and re-wraps on device, which
    sidesteps extended-dtype transport entirely).  Single-process only;
    the pipelined engine gates on ``jax.process_count() == 1``."""
    n = arr.shape[1]
    if n % mesh.size:
        raise ValueError(
            f"batch size {n} not divisible by mesh size {mesh.size}")
    return jax.device_put(arr, NamedSharding(mesh, P(None, TRIAL_AXIS)))


def replicated(mesh: Mesh, x) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P()))
