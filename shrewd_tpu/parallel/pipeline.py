"""Pipelined campaign engine: overlap device compute with host-side work.

The serial loop (``campaign/orchestrator.py`` → ``parallel/campaign.py``)
dispatches one batch, blocks until it completes, materializes the tally,
then runs every host-side consumer — canary salting, tally invariants,
audit sampling, stats, checkpoint decisions — before the next dispatch.
The device idles during all host work and the host idles during all device
work.  JAX's dispatch is asynchronous by design; this engine exploits it:

- **async double-buffered dispatch** — while the host consumes interval N,
  intervals N+1..N+depth-1 are already dispatched (``depth`` bounds the
  in-flight window).  The ``DeviceWatchdog`` deadline is *armed at
  dispatch* and *enforced at materialization* (``resilience.call_armed``),
  so the wedge-detection guarantee survives without per-batch blocking.
- **sync-interval accumulation** — one jitted multi-batch step
  (``ShardedCampaign.dispatch_interval``) accumulates ``sync_every``
  batches' tallies (and strata) on device and transfers to host ONCE per
  interval.  Stopping-rule checks, integrity invariants and canary
  verification run at interval boundaries on the cumulative deltas.
  Per-batch tallies are pure functions of their frozen PRNG keys and
  integer sums commute, so the accumulated interval tally is
  **bit-identical** to the serial loop's — ``sync_every=1`` reproduces
  today's semantics exactly and stays the default for chaos/elastic modes.
- **serial recovery** — any failure at materialization (wedge, backend
  error, shard mismatch) or any interval-boundary integrity problem
  (invariant/canary/corruption) drops the whole in-flight window and
  re-dispatches the interval's batches one-by-one through the existing
  integrity-checked resilience ladder on the same frozen keys: recovery is
  bit-identical because the serial path is.

Import discipline: jax-free at module import (``PipelineConfig`` rides the
``CampaignPlan``, which bench's jax-free supervisor deserializes); jax
enters only through the campaign/dispatcher objects the engine drives.
"""

from __future__ import annotations

from collections import deque
from typing import NamedTuple

import numpy as np

from shrewd_tpu import resilience as resil
from shrewd_tpu.obs import clock as obs_clock
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.utils import debug
from shrewd_tpu.utils.config import ConfigObject, Param

debug.register_flag("Pipeline", "pipelined campaign engine")


class PipelineConfig(ConfigObject):
    """Knobs for the pipelined engine (a ``CampaignPlan`` child, so a
    campaign's pipelining posture is reproducible from its config dump)."""

    sync_every = Param(int, 1,
                       "batches accumulated on device per host transfer "
                       "(1 = serial semantics, exactly today's loop; keep "
                       "1 for chaos/elastic runs unless testing them "
                       "pipelined)", check=lambda v: v >= 1)
    depth = Param(int, 2,
                  "max sync intervals in flight (2 = double buffering)",
                  check=lambda v: v >= 1)
    compilation_cache_dir = Param(str, "",
                                  "opt-in persistent jax compilation "
                                  "cache directory: re-runs and resumes "
                                  "in new processes skip retrace/"
                                  "recompile (empty = in-process "
                                  "executable cache only)")
    until_ci = Param(bool, False,
                     "device-resident run-until-CI: fuse the Wilson/"
                     "post-stratified stopping rule into a jitted "
                     "lax.while_loop around the batch step — ONE host "
                     "transfer per super-interval instead of one per "
                     "batch, with per-batch decision cadence (results "
                     "bit-identical to the serial loop, INCLUDING the "
                     "consumed trial count).  Supersedes sync_every "
                     "where it applies; off for chaos/elastic runs "
                     "unless testing them fused")
    max_super_interval = Param(int, 64,
                               "max batches per device-resident "
                               "super-interval: bounds the while-loop "
                               "budget (integrity checks still gate "
                               "every cumulative delta) and the "
                               "shape-specialized executable variety",
                               check=lambda v: v >= 1)


class PerfStats:
    """Host-side perf ledger for the ``campaign.perf.*`` stats group —
    the speedup must be observable, not asserted.  Jax-free."""

    def __init__(self):
        self.device_step_seconds = 0.0   # dispatch → materialized, summed
        # per interval (includes device queue time at depth > 1)
        self.device_wait_seconds = 0.0   # host BLOCKED in materialization
        # (the non-overlapped remainder of device_step_seconds)
        self.host_seconds = 0.0          # host-side work while intervals
        # were in flight (checks, stats, checkpoints, audit)
        self.dispatches = 0              # intervals dispatched
        self.intervals = 0               # intervals believed pipelined
        self.serial_fallbacks = 0        # intervals recovered serially
        self.depth_hwm = 0               # in-flight high-water mark
        # device-resident run-until-CI (UntilCIEngine): the stopping rule
        # runs on device, so the host sees one transfer per super-interval
        self.super_intervals = 0         # super-intervals believed fused
        self.host_roundtrips_saved = 0   # batches consumed minus transfers
        self.hw_trajectory_final = float("nan")  # last observed half-width
        self.auto_sync_every = 0         # last planned super-interval len

    def overlap_fraction(self) -> float:
        """Fraction of device latency hidden behind host work: 1.0 means
        the host never blocked (compute fully overlapped), 0.0 means the
        serial posture (every device second was a host wait second)."""
        if self.device_step_seconds <= 0:
            return 0.0
        return max(0.0, 1.0 - self.device_wait_seconds
                   / self.device_step_seconds)

    def to_dict(self) -> dict:
        return {
            "device_step_seconds": round(self.device_step_seconds, 4),
            "device_wait_seconds": round(self.device_wait_seconds, 4),
            "host_seconds": round(self.host_seconds, 4),
            "overlap_fraction": round(self.overlap_fraction(), 4),
            "dispatches": self.dispatches,
            "intervals": self.intervals,
            "serial_fallbacks": self.serial_fallbacks,
            "depth_hwm": self.depth_hwm,
            "super_intervals": self.super_intervals,
            "host_roundtrips_saved": self.host_roundtrips_saved,
            "hw_trajectory_final": round(self.hw_trajectory_final, 6)
            if self.hw_trajectory_final == self.hw_trajectory_final
            else None,
            "auto_sync_every": self.auto_sync_every,
        }


def _frozen_keys(sk, batch_size: int, batch_id: int):
    """The frozen per-batch trial keys — ONE derivation shared by both
    engines and their serial recovery paths, so the
    pure-function-of-coordinates contract cannot drift between them."""
    from shrewd_tpu.utils import prng

    return prng.trial_keys(prng.batch_key(sk, batch_id), batch_size)


def _believe_device_result(engine, tally, strata, n_batches: int, b0: int,
                           audit_keys, esc0, tt0, recover):
    """The shared believe/quarantine path both engines run on a
    materialized device result covering ``n_batches`` batches: armed
    corruption hook, invariants + canaries on the cumulative delta,
    shard-counter sync, then the per-batch deterministic audit — any
    problem rolls the kernel's escape counters back to (esc0, tt0),
    records the quarantine and recovers through ``recover()`` (the
    serial ladder).  → (believed doc, recovered flag); ONE copy so the
    two engines' mismatch ledgers cannot drift."""
    kernel = engine.campaign.kernel
    res = resil.DispatchResult(np.asarray(tally, dtype=np.int64),
                               None if strata is None
                               else np.asarray(strata, dtype=np.int64),
                               resil.TIER_DEVICE, 1)
    res = engine.monitor.apply_corruption(res)
    problems = engine.checked.check_result(
        res, n_batches * engine.batch_size, batch_id=b0)
    engine.checked.sync_shard_counters(b0)
    if problems:
        if esc0 is not None:
            kernel.escapes = esc0
        if tt0 is not None:
            kernel.taint_trials = tt0
        engine.monitor.record_quarantine({
            "kind": problems[0]["kind"], "simpoint": engine.sp_name,
            "structure": engine.structure, "batch_id": int(b0),
            "interval": int(n_batches),
            "tier": resil.TIERS[resil.TIER_DEVICE],
            "problems": problems, "fatal": False})
        engine.monitor.requeues += 1
        doc = recover()
        engine.monitor.recovered += 1
        return doc, True
    for i, keys in enumerate(audit_keys):
        # same deterministic per-batch audit sample as the serial loop:
        # the mismatch ledger is identical whichever loop ran
        engine.checked.audit_batch(keys, b0 + i)
    return {
        "batch_id": int(b0),
        "n_batches": int(n_batches),
        "batch_size": int(engine.batch_size),
        "tally": res.tally.tolist(),
        "strata": (None if res.strata is None else res.strata.tolist()),
        "tier": int(res.tier),
        "tiers": [int(res.tier)] * int(n_batches),
        "attempts": 1,
    }, False


class _Pending(NamedTuple):
    b0: int                 # first batch id of the interval
    k: int                  # batches in the interval
    keys: list              # per-batch key arrays (audit / serial recovery)
    handle: object          # ShardedCampaign in-flight interval handle


class PipelinedEngine:
    """Per-(simpoint, structure) pipelined dispatch over one campaign.

    ``obtain(b0, k, stratified)`` returns the interval's believed result
    document (the ``_compute_batch`` doc shape plus ``n_batches`` /
    ``tiers``): materialize the head interval, keep ``depth`` intervals in
    flight behind it, run the interval-boundary integrity checks, and fall
    back to the serial per-batch checked ladder on any failure."""

    def __init__(self, campaign, checked, structure_key, batch_size: int,
                 ceiling_batches: int, sync_every: int, depth: int,
                 monitor, chaos=None, perf: PerfStats | None = None,
                 sp_name: str = "", structure: str = ""):
        self.campaign = campaign
        self.checked = checked            # integrity.CheckedDispatcher
        self.sk = structure_key
        self.batch_size = int(batch_size)
        self.ceiling = int(ceiling_batches)
        self.sync_every = max(int(sync_every), 1)
        self.depth = max(int(depth), 1)
        self.monitor = monitor
        self.chaos = chaos
        self.perf = perf if perf is not None else PerfStats()
        self.sp_name = sp_name
        self.structure = structure
        self._q: deque[_Pending] = deque()
        self._last_return: float | None = None

    # --- keys -----------------------------------------------------------

    def _keys(self, batch_id: int):
        return _frozen_keys(self.sk, self.batch_size, batch_id)

    # --- dispatch-ahead -------------------------------------------------

    def _drop_inflight(self) -> None:
        """Discard the in-flight queue, CLOSING each interval's async
        span (same name/coords as its "B", so exporters pair them):
        routinely-dropped speculation must not read as wedged dispatches
        in the flight recorder — unclosed spans are the wedge signal."""
        for p in self._q:
            obs_trace.tracer().emit(
                "interval_inflight", cat="dispatch", ph="E",
                sp=self.sp_name, structure=self.structure,
                b0=int(p.b0), k=int(p.k), dropped=True)
        self._q.clear()

    def _fill(self, b0: int, k: int) -> None:
        q = self._q
        if q and (q[0].b0 != b0 or q[0].k != k):
            # realignment (resume, recovery, interval-length change):
            # in-flight results are pure device work with no host side
            # effects — dropping them costs compute, never correctness
            debug.dprintf("Pipeline", "%s/%s: dropping %d stale in-flight "
                          "intervals (head %d!=%d)", self.sp_name,
                          self.structure, len(q), q[0].b0, b0)
            self._drop_inflight()
        while len(q) < self.depth:
            nb = (q[-1].b0 + q[-1].k) if q else b0
            if nb >= self.ceiling:
                break
            # prefetch length follows the caller's CURRENT ask, not the
            # plan ceiling: when the orchestrator's half-width-adaptive
            # interval shrinks toward convergence (k → 1), speculative
            # dispatch-ahead shrinks with it — batches past the stopping
            # point are wasted device work, and near convergence is
            # exactly where the next ask will be short
            kk = min(k, self.sync_every, self.ceiling - nb)
            if not q:
                kk = k            # the head must match the caller's ask
            keys = [self._keys(b) for b in range(nb, nb + kk)]
            # async-span begin: the interval is now in flight — the
            # matching "E" lands at materialization, so the exported
            # timeline shows dispatch-ahead overlap and queue depth
            obs_trace.tracer().emit(
                "interval_inflight", cat="dispatch", ph="B",
                sp=self.sp_name, structure=self.structure,
                b0=int(nb), k=int(kk))
            handle = self.campaign.dispatch_interval(keys)
            q.append(_Pending(nb, kk, keys, handle))
            self.perf.dispatches += 1
            self.perf.depth_hwm = max(self.perf.depth_hwm, len(q))
            obs_trace.tracer().counter(
                "dispatch_depth", len(q), cat="dispatch",
                sp=self.sp_name, structure=self.structure)
        if not q or q[0].b0 != b0:
            raise RuntimeError(
                f"{self.sp_name}/{self.structure}: interval at batch {b0} "
                f"is beyond the campaign ceiling ({self.ceiling} batches)")

    # --- the believed-interval protocol ---------------------------------

    def obtain(self, b0: int, k: int, stratified: bool = False) -> dict:
        now = obs_clock.monotonic()
        if self._last_return is not None:
            # host-side time since the last interval was handed over:
            # stats/stopping/checkpoint work that ran while the next
            # intervals computed — the overlapped half of the ledger
            self.perf.host_seconds += now - self._last_return
        try:
            return self._obtain(b0, k, stratified)
        finally:
            self._last_return = obs_clock.monotonic()

    def _obtain(self, b0: int, k: int, stratified: bool) -> dict:
        try:
            # dispatch failures (an interval-step compile the backend
            # rejects, an enqueue-time crash) must degrade like any other
            # device failure — the serial ladder is the recovery path for
            # the whole interval, exactly as for a materialization wedge
            self._fill(b0, k)
            head = self._q.popleft()
            if self.chaos is not None:
                # armed device-tier chaos faults fire at consume time, the
                # pipelined analog of the ladder's per-dispatch hook
                self.chaos.maybe_backend_error(resil.TIER_DEVICE)
            # the per-batch watchdog deadline scales by interval length x
            # in-flight depth: a prefetched interval legitimately queues
            # behind everything dispatched ahead of it
            wd = self.campaign.watchdog
            tmo = (wd.timeout * k * self.depth
                   if wd is not None and wd.timeout > 0 else None)
            # snapshot the kernel's escape counters: materialization bumps
            # them, but a quarantined interval's bump must be rolled back
            # before serial recovery re-adds the believed values (the
            # _CounterGuard discipline of the serial checked dispatch)
            kernel = self.campaign.kernel
            esc0 = getattr(kernel, "escapes", None)
            tt0 = getattr(kernel, "taint_trials", None)
            t0 = obs_clock.monotonic()
            tally, strata = self.campaign.materialize_interval(
                head.handle, timeout=tmo)
            t1 = obs_clock.monotonic()
            self.perf.device_wait_seconds += t1 - t0
            self.perf.device_step_seconds += t1 - head.handle.armed_at
            obs_trace.tracer().emit(
                "interval_inflight", cat="dispatch", ph="E",
                sp=self.sp_name, structure=self.structure,
                b0=int(b0), k=int(k))
        except Exception as e:  # noqa: BLE001 — wedge, backend crash,
            # shard-sum mismatch: every dispatch/materialization failure
            # recovers through the serial per-batch ladder on frozen keys
            debug.dprintf("Pipeline", "%s/%s interval [%d,%d): "
                          "pipelined dispatch failed (%s) — serial "
                          "recovery", self.sp_name, self.structure,
                          b0, b0 + k, e)
            return self._recover(b0, k, stratified)
        doc, recovered = _believe_device_result(
            self, tally, strata, k, b0, head.keys, esc0, tt0,
            lambda: self._recover(b0, k, stratified))
        if not recovered:
            self.perf.intervals += 1
        return doc

    def _recover(self, b0: int, k: int, stratified: bool) -> dict:
        """Serial per-batch recovery on the frozen keys: the in-flight
        window is untrusted (a wedged backend may poison everything
        dispatched to it), so drop it and route each batch through the
        integrity-checked resilience ladder — the exact serial path, so
        recovery is bit-identical by the ladder's own contract."""
        self._drop_inflight()
        obs_trace.tracer().emit(
            "serial_recovery", cat="dispatch", sp=self.sp_name,
            structure=self.structure, b0=int(b0), k=int(k))
        return _serial_batches(self.checked, self._keys, b0, k, stratified,
                               self.batch_size, self.perf)


def _serial_batches(checked, keys_fn, b0: int, k: int, stratified: bool,
                    batch_size: int, perf: PerfStats,
                    stop_after=None) -> dict:
    """The shared serial per-batch ladder loop behind both engines'
    recovery paths (and the until-CI recovery's host-rule replay):
    ``stop_after(j, res)`` — called after batch ``b0 + j`` is believed —
    may end the loop early (the until-CI path re-derives the device's
    stopping decision with the HOST rule, so a quarantined super-interval
    recovers bit-identically without trusting the device-decided batch
    count)."""
    from shrewd_tpu.ops import classify as C

    perf.serial_fallbacks += 1
    tally = np.zeros(C.N_OUTCOMES, dtype=np.int64)
    strata_sum = None
    tiers: list[int] = []
    attempts = 0
    for j in range(k):
        b = b0 + j
        res = checked.tally_batch(keys_fn(b), stratified=stratified,
                                  batch_id=b)
        tally += np.asarray(res.tally, dtype=np.int64)
        if res.strata is not None:
            s = np.asarray(res.strata, dtype=np.int64)
            strata_sum = s if strata_sum is None else strata_sum + s
        tiers.append(int(res.tier))
        attempts += int(res.attempts)
        if stop_after is not None and stop_after(j, res):
            break
    return {
        "batch_id": int(b0),
        "n_batches": len(tiers),
        "batch_size": int(batch_size),
        "tally": tally.tolist(),
        "strata": (None if strata_sum is None else strata_sum.tolist()),
        "tier": int(max(tiers)),
        "tiers": tiers,
        "attempts": int(attempts),
    }


class UntilCIEngine:
    """Device-resident run-until-CI driver for one campaign (the fused
    stopping rule of ``ShardedCampaign.dispatch_until_ci``).

    ``obtain(b0, S, tallies, strata, strat_rule)`` dispatches ONE
    super-interval — the device consumes up to ``S`` frozen-key batches,
    checking the Wilson/post-stratified half-width against the target
    after each, and the host transfers ONE result when the rule fires or
    the budget runs out.  The believed-result document is the interval
    doc shape with ``n_batches`` = the device-decided consumed count,
    plus the half-width trajectory tail for the orchestrator's
    super-interval planner.

    Integrity stance: the super-interval is bounded (``S``), and the
    interval-boundary invariants, canary battery and sampled audit still
    gate the cumulative delta before a converged result is believed.  A
    quarantined or failed super-interval re-dispatches down the serial
    per-batch ladder on the same frozen keys, re-deriving the stopping
    decision with the HOST rule after every believed batch — so recovery
    never trusts the device-decided count and is bit-identical by the
    decision-parity contract (stopping.wilson_halfwidth_device)."""

    def __init__(self, campaign, checked, structure_key, batch_size: int,
                 monitor, *, min_trials: int, target_halfwidth: float,
                 confidence: float, chaos=None,
                 perf: PerfStats | None = None,
                 sp_name: str = "", structure: str = ""):
        self.campaign = campaign
        self.checked = checked            # integrity.CheckedDispatcher
        self.sk = structure_key
        self.batch_size = int(batch_size)
        self.monitor = monitor
        self.min_trials = int(min_trials)
        self.target_halfwidth = float(target_halfwidth)
        self.confidence = float(confidence)
        self.chaos = chaos
        self.perf = perf if perf is not None else PerfStats()
        self.sp_name = sp_name
        self.structure = structure

    def _keys(self, batch_id: int):
        return _frozen_keys(self.sk, self.batch_size, batch_id)

    def obtain(self, b0: int, S: int, tallies, strata,
               strat_rule: bool) -> dict:
        """One believed super-interval starting at batch ``b0`` with
        budget ``S``, given the campaign's cumulative state (``tallies``
        int64 (N_OUTCOMES,), ``strata`` int64 | None)."""
        self.perf.auto_sync_every = int(S)
        trials0 = int(np.asarray(tallies).sum())
        keys = [self._keys(b) for b in range(b0, b0 + S)]
        kernel = self.campaign.kernel
        esc0 = getattr(kernel, "escapes", None)
        tt0 = getattr(kernel, "taint_trials", None)
        try:
            obs_trace.tracer().emit(
                "super_interval_inflight", cat="dispatch", ph="B",
                sp=self.sp_name, structure=self.structure,
                b0=int(b0), k=int(S))
            handle = self.campaign.dispatch_until_ci(
                keys, tallies, strata, trials0, self.min_trials,
                self.target_halfwidth, self.confidence, strat_rule)
            self.perf.dispatches += 1
            if self.chaos is not None:
                # armed device-tier chaos faults fire at consume time,
                # exactly like the pipelined interval path
                self.chaos.maybe_backend_error(resil.TIER_DEVICE)
            wd = self.campaign.watchdog
            tmo = (wd.timeout * S if wd is not None and wd.timeout > 0
                   else None)
            t0 = obs_clock.monotonic()
            tally, strata_d, consumed, hw_tail = \
                self.campaign.materialize_until_ci(handle, timeout=tmo)
            t1 = obs_clock.monotonic()
            self.perf.device_wait_seconds += t1 - t0
            self.perf.device_step_seconds += t1 - handle.armed_at
            obs_trace.tracer().emit(
                "super_interval_inflight", cat="dispatch", ph="E",
                sp=self.sp_name, structure=self.structure,
                b0=int(b0), k=int(S), consumed=int(consumed))
        except Exception as e:  # noqa: BLE001 — wedge, backend crash,
            # shard-sum mismatch: recover serially on frozen keys with
            # the host stopping rule deciding where to stop
            debug.dprintf("Pipeline", "%s/%s until-CI super-interval "
                          "[%d,%d): device loop failed (%s) — serial "
                          "recovery", self.sp_name, self.structure,
                          b0, b0 + S, e)
            return self._recover(b0, S, tallies, strata, strat_rule)
        doc, recovered = _believe_device_result(
            self, tally, strata_d, consumed, b0, keys[:consumed],
            esc0, tt0,
            lambda: self._recover(b0, S, tallies, strata, strat_rule))
        if recovered:
            return doc
        # super_intervals is the fused loop's own counter; perf.intervals
        # stays pipelined-path-only (its stats description says so)
        self.perf.super_intervals += 1
        # the serial host loop would have paid one transfer per batch;
        # the fused loop paid ONE for the whole super-interval
        self.perf.host_roundtrips_saved += max(consumed - 1, 0)
        if len(hw_tail):
            self.perf.hw_trajectory_final = float(hw_tail[-1])
        doc["hw_tail"] = [float(h) for h in hw_tail]
        return doc

    def _recover(self, b0: int, S: int, tallies, strata,
                 strat_rule: bool) -> dict:
        """Serial per-batch ladder replay of the super-interval on the
        same frozen keys, with the HOST stopping rule re-deriving the
        consumed batch count (never trusting a device-decided count from
        an untrusted result)."""
        from shrewd_tpu.parallel import stopping

        obs_trace.tracer().emit(
            "serial_recovery", cat="dispatch", sp=self.sp_name,
            structure=self.structure, b0=int(b0), k=int(S),
            host_rule=True)

        cum = np.asarray(tallies, dtype=np.int64).copy()
        cum_strata = (None if strata is None
                      else np.asarray(strata, dtype=np.int64).copy())

        def stop_after(_j, res) -> bool:
            nonlocal cum, cum_strata
            cum = cum + np.asarray(res.tally, dtype=np.int64)
            if res.strata is not None:
                s = np.asarray(res.strata, dtype=np.int64)
                cum_strata = (s.copy() if cum_strata is None
                              else cum_strata + s)
            trials = int(cum.sum())
            if strat_rule:
                return stopping.should_stop_stratified(
                    stopping.pairs_from_strata(cum_strata),
                    self.target_halfwidth, self.confidence,
                    self.min_trials)
            from shrewd_tpu.ops import classify as C

            vul = int(cum[C.OUTCOME_SDC] + cum[C.OUTCOME_DUE])
            return stopping.should_stop(vul, trials,
                                        self.target_halfwidth,
                                        self.confidence, self.min_trials)

        return _serial_batches(self.checked, self._keys, b0, S,
                               self.campaign.stratify, self.batch_size,
                               self.perf, stop_after=stop_after)
