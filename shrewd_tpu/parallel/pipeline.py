"""Pipelined campaign engine: overlap device compute with host-side work.

The serial loop (``campaign/orchestrator.py`` → ``parallel/campaign.py``)
dispatches one batch, blocks until it completes, materializes the tally,
then runs every host-side consumer — canary salting, tally invariants,
audit sampling, stats, checkpoint decisions — before the next dispatch.
The device idles during all host work and the host idles during all device
work.  JAX's dispatch is asynchronous by design; this engine exploits it:

- **async double-buffered dispatch** — while the host consumes interval N,
  intervals N+1..N+depth-1 are already dispatched (``depth`` bounds the
  in-flight window).  The ``DeviceWatchdog`` deadline is *armed at
  dispatch* and *enforced at materialization* (``resilience.call_armed``),
  so the wedge-detection guarantee survives without per-batch blocking.
- **sync-interval accumulation** — one jitted multi-batch step
  (``ShardedCampaign.dispatch_interval``) accumulates ``sync_every``
  batches' tallies (and strata) on device and transfers to host ONCE per
  interval.  Stopping-rule checks, integrity invariants and canary
  verification run at interval boundaries on the cumulative deltas.
  Per-batch tallies are pure functions of their frozen PRNG keys and
  integer sums commute, so the accumulated interval tally is
  **bit-identical** to the serial loop's — ``sync_every=1`` reproduces
  today's semantics exactly and stays the default for chaos/elastic modes.
- **serial recovery** — any failure at materialization (wedge, backend
  error, shard mismatch) or any interval-boundary integrity problem
  (invariant/canary/corruption) drops the whole in-flight window and
  re-dispatches the interval's batches one-by-one through the existing
  integrity-checked resilience ladder on the same frozen keys: recovery is
  bit-identical because the serial path is.

Import discipline: jax-free at module import (``PipelineConfig`` rides the
``CampaignPlan``, which bench's jax-free supervisor deserializes); jax
enters only through the campaign/dispatcher objects the engine drives.
"""

from __future__ import annotations

import time
from collections import deque
from typing import NamedTuple

import numpy as np

from shrewd_tpu import resilience as resil
from shrewd_tpu.utils import debug
from shrewd_tpu.utils.config import ConfigObject, Param

debug.register_flag("Pipeline", "pipelined campaign engine")


class PipelineConfig(ConfigObject):
    """Knobs for the pipelined engine (a ``CampaignPlan`` child, so a
    campaign's pipelining posture is reproducible from its config dump)."""

    sync_every = Param(int, 1,
                       "batches accumulated on device per host transfer "
                       "(1 = serial semantics, exactly today's loop; keep "
                       "1 for chaos/elastic runs unless testing them "
                       "pipelined)", check=lambda v: v >= 1)
    depth = Param(int, 2,
                  "max sync intervals in flight (2 = double buffering)",
                  check=lambda v: v >= 1)
    compilation_cache_dir = Param(str, "",
                                  "opt-in persistent jax compilation "
                                  "cache directory: re-runs and resumes "
                                  "in new processes skip retrace/"
                                  "recompile (empty = in-process "
                                  "executable cache only)")


class PerfStats:
    """Host-side perf ledger for the ``campaign.perf.*`` stats group —
    the speedup must be observable, not asserted.  Jax-free."""

    def __init__(self):
        self.device_step_seconds = 0.0   # dispatch → materialized, summed
        # per interval (includes device queue time at depth > 1)
        self.device_wait_seconds = 0.0   # host BLOCKED in materialization
        # (the non-overlapped remainder of device_step_seconds)
        self.host_seconds = 0.0          # host-side work while intervals
        # were in flight (checks, stats, checkpoints, audit)
        self.dispatches = 0              # intervals dispatched
        self.intervals = 0               # intervals believed pipelined
        self.serial_fallbacks = 0        # intervals recovered serially
        self.depth_hwm = 0               # in-flight high-water mark

    def overlap_fraction(self) -> float:
        """Fraction of device latency hidden behind host work: 1.0 means
        the host never blocked (compute fully overlapped), 0.0 means the
        serial posture (every device second was a host wait second)."""
        if self.device_step_seconds <= 0:
            return 0.0
        return max(0.0, 1.0 - self.device_wait_seconds
                   / self.device_step_seconds)

    def to_dict(self) -> dict:
        return {
            "device_step_seconds": round(self.device_step_seconds, 4),
            "device_wait_seconds": round(self.device_wait_seconds, 4),
            "host_seconds": round(self.host_seconds, 4),
            "overlap_fraction": round(self.overlap_fraction(), 4),
            "dispatches": self.dispatches,
            "intervals": self.intervals,
            "serial_fallbacks": self.serial_fallbacks,
            "depth_hwm": self.depth_hwm,
        }


class _Pending(NamedTuple):
    b0: int                 # first batch id of the interval
    k: int                  # batches in the interval
    keys: list              # per-batch key arrays (audit / serial recovery)
    handle: object          # ShardedCampaign in-flight interval handle


class PipelinedEngine:
    """Per-(simpoint, structure) pipelined dispatch over one campaign.

    ``obtain(b0, k, stratified)`` returns the interval's believed result
    document (the ``_compute_batch`` doc shape plus ``n_batches`` /
    ``tiers``): materialize the head interval, keep ``depth`` intervals in
    flight behind it, run the interval-boundary integrity checks, and fall
    back to the serial per-batch checked ladder on any failure."""

    def __init__(self, campaign, checked, structure_key, batch_size: int,
                 ceiling_batches: int, sync_every: int, depth: int,
                 monitor, chaos=None, perf: PerfStats | None = None,
                 sp_name: str = "", structure: str = ""):
        self.campaign = campaign
        self.checked = checked            # integrity.CheckedDispatcher
        self.sk = structure_key
        self.batch_size = int(batch_size)
        self.ceiling = int(ceiling_batches)
        self.sync_every = max(int(sync_every), 1)
        self.depth = max(int(depth), 1)
        self.monitor = monitor
        self.chaos = chaos
        self.perf = perf if perf is not None else PerfStats()
        self.sp_name = sp_name
        self.structure = structure
        self._q: deque[_Pending] = deque()
        self._last_return: float | None = None

    # --- keys -----------------------------------------------------------

    def _keys(self, batch_id: int):
        from shrewd_tpu.utils import prng

        return prng.trial_keys(prng.batch_key(self.sk, batch_id),
                               self.batch_size)

    # --- dispatch-ahead -------------------------------------------------

    def _fill(self, b0: int, k: int) -> None:
        q = self._q
        if q and (q[0].b0 != b0 or q[0].k != k):
            # realignment (resume, recovery, interval-length change):
            # in-flight results are pure device work with no host side
            # effects — dropping them costs compute, never correctness
            debug.dprintf("Pipeline", "%s/%s: dropping %d stale in-flight "
                          "intervals (head %d!=%d)", self.sp_name,
                          self.structure, len(q), q[0].b0, b0)
            q.clear()
        while len(q) < self.depth:
            nb = (q[-1].b0 + q[-1].k) if q else b0
            if nb >= self.ceiling:
                break
            # prefetch length follows the caller's CURRENT ask, not the
            # plan ceiling: when the orchestrator's half-width-adaptive
            # interval shrinks toward convergence (k → 1), speculative
            # dispatch-ahead shrinks with it — batches past the stopping
            # point are wasted device work, and near convergence is
            # exactly where the next ask will be short
            kk = min(k, self.sync_every, self.ceiling - nb)
            if not q:
                kk = k            # the head must match the caller's ask
            keys = [self._keys(b) for b in range(nb, nb + kk)]
            handle = self.campaign.dispatch_interval(keys)
            q.append(_Pending(nb, kk, keys, handle))
            self.perf.dispatches += 1
            self.perf.depth_hwm = max(self.perf.depth_hwm, len(q))
        if not q or q[0].b0 != b0:
            raise RuntimeError(
                f"{self.sp_name}/{self.structure}: interval at batch {b0} "
                f"is beyond the campaign ceiling ({self.ceiling} batches)")

    # --- the believed-interval protocol ---------------------------------

    def obtain(self, b0: int, k: int, stratified: bool = False) -> dict:
        now = time.monotonic()
        if self._last_return is not None:
            # host-side time since the last interval was handed over:
            # stats/stopping/checkpoint work that ran while the next
            # intervals computed — the overlapped half of the ledger
            self.perf.host_seconds += now - self._last_return
        try:
            return self._obtain(b0, k, stratified)
        finally:
            self._last_return = time.monotonic()

    def _obtain(self, b0: int, k: int, stratified: bool) -> dict:
        try:
            # dispatch failures (an interval-step compile the backend
            # rejects, an enqueue-time crash) must degrade like any other
            # device failure — the serial ladder is the recovery path for
            # the whole interval, exactly as for a materialization wedge
            self._fill(b0, k)
            head = self._q.popleft()
            if self.chaos is not None:
                # armed device-tier chaos faults fire at consume time, the
                # pipelined analog of the ladder's per-dispatch hook
                self.chaos.maybe_backend_error(resil.TIER_DEVICE)
            # the per-batch watchdog deadline scales by interval length x
            # in-flight depth: a prefetched interval legitimately queues
            # behind everything dispatched ahead of it
            wd = self.campaign.watchdog
            tmo = (wd.timeout * k * self.depth
                   if wd is not None and wd.timeout > 0 else None)
            # snapshot the kernel's escape counters: materialization bumps
            # them, but a quarantined interval's bump must be rolled back
            # before serial recovery re-adds the believed values (the
            # _CounterGuard discipline of the serial checked dispatch)
            kernel = self.campaign.kernel
            esc0 = getattr(kernel, "escapes", None)
            tt0 = getattr(kernel, "taint_trials", None)
            t0 = time.monotonic()
            tally, strata = self.campaign.materialize_interval(
                head.handle, timeout=tmo)
            t1 = time.monotonic()
            self.perf.device_wait_seconds += t1 - t0
            self.perf.device_step_seconds += t1 - head.handle.armed_at
        except Exception as e:  # noqa: BLE001 — wedge, backend crash,
            # shard-sum mismatch: every dispatch/materialization failure
            # recovers through the serial per-batch ladder on frozen keys
            debug.dprintf("Pipeline", "%s/%s interval [%d,%d): "
                          "pipelined dispatch failed (%s) — serial "
                          "recovery", self.sp_name, self.structure,
                          b0, b0 + k, e)
            return self._recover(b0, k, stratified)
        res = resil.DispatchResult(np.asarray(tally, dtype=np.int64),
                                   None if strata is None
                                   else np.asarray(strata, dtype=np.int64),
                                   resil.TIER_DEVICE, 1)
        res = self.monitor.apply_corruption(res)
        problems = self.checked.check_result(res, k * self.batch_size)
        self.checked.sync_shard_counters(b0)
        if problems:
            if esc0 is not None:
                kernel.escapes = esc0
            if tt0 is not None:
                kernel.taint_trials = tt0
            self.monitor.record_quarantine({
                "kind": problems[0]["kind"], "simpoint": self.sp_name,
                "structure": self.structure, "batch_id": int(b0),
                "interval": int(k), "tier": resil.TIERS[resil.TIER_DEVICE],
                "problems": problems, "fatal": False})
            self.monitor.requeues += 1
            doc = self._recover(b0, k, stratified)
            self.monitor.recovered += 1
            return doc
        for i, b in enumerate(range(b0, b0 + k)):
            # audit each batch with the SAME deterministic per-batch
            # sample as the serial loop: the mismatch ledger is identical
            # whichever loop ran (and the re-runs overlap the next
            # interval's device compute)
            self.checked.audit_batch(head.keys[i], b)
        self.perf.intervals += 1
        return {
            "batch_id": int(b0),
            "n_batches": int(k),
            "batch_size": int(self.batch_size),
            "tally": res.tally.tolist(),
            "strata": (None if res.strata is None
                       else res.strata.tolist()),
            "tier": int(res.tier),
            "tiers": [int(res.tier)] * int(k),
            "attempts": 1,
        }

    def _recover(self, b0: int, k: int, stratified: bool) -> dict:
        """Serial per-batch recovery on the frozen keys: the in-flight
        window is untrusted (a wedged backend may poison everything
        dispatched to it), so drop it and route each batch through the
        integrity-checked resilience ladder — the exact serial path, so
        recovery is bit-identical by the ladder's own contract."""
        from shrewd_tpu.ops import classify as C

        self._q.clear()
        self.perf.serial_fallbacks += 1
        tally = np.zeros(C.N_OUTCOMES, dtype=np.int64)
        strata_sum = None
        tiers: list[int] = []
        attempts = 0
        for b in range(b0, b0 + k):
            res = self.checked.tally_batch(self._keys(b),
                                           stratified=stratified,
                                           batch_id=b)
            tally += np.asarray(res.tally, dtype=np.int64)
            if res.strata is not None:
                s = np.asarray(res.strata, dtype=np.int64)
                strata_sum = s if strata_sum is None else strata_sum + s
            tiers.append(int(res.tier))
            attempts += int(res.attempts)
        return {
            "batch_id": int(b0),
            "n_batches": int(k),
            "batch_size": int(self.batch_size),
            "tally": tally.tolist(),
            "strata": (None if strata_sum is None else strata_sum.tolist()),
            "tier": int(max(tiers)),
            "tiers": tiers,
            "attempts": int(attempts),
        }
