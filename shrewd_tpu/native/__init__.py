"""ctypes bindings to the native C++ runtime (csrc/).

The Python↔C++ boundary of the framework — the counterpart of the reference's
pybind11 layer (``src/python/pybind11/``, ``SimObject.getCCObject()``), done
with ctypes per the environment (no pybind11).  Builds ``libshrewd.so`` on
demand via the csrc Makefile.
"""

from __future__ import annotations

import ctypes as ct
import subprocess
from pathlib import Path

import numpy as np

from shrewd_tpu.utils import debug

_CSRC = Path(__file__).resolve().parent.parent.parent / "csrc"
_LIB_PATH = _CSRC / "libshrewd.so"
_lib = None


class _TraceView(ct.Structure):
    _fields_ = [
        ("opcode", ct.POINTER(ct.c_int32)),
        ("dst", ct.POINTER(ct.c_int32)),
        ("src1", ct.POINTER(ct.c_int32)),
        ("src2", ct.POINTER(ct.c_int32)),
        ("imm", ct.POINTER(ct.c_uint32)),
        ("taken", ct.POINTER(ct.c_int32)),
        ("n", ct.c_int32),
        ("nphys", ct.c_int32),
        ("mem_words", ct.c_int32),
    ]


class _FaultView(ct.Structure):
    _fields_ = [
        ("kind", ct.POINTER(ct.c_int32)),
        ("cycle", ct.POINTER(ct.c_int32)),
        ("entry", ct.POINTER(ct.c_int32)),
        ("bit", ct.POINTER(ct.c_int32)),
        ("shadow_u", ct.POINTER(ct.c_float)),
        ("n_trials", ct.c_int32),
    ]


class _WorkloadParams(ct.Structure):
    _fields_ = [
        ("seed", ct.c_uint64),
        ("n", ct.c_int32),
        ("nphys", ct.c_int32),
        ("mem_words", ct.c_int32),
        ("working_set_words", ct.c_int32),
        ("frac_alu", ct.c_float),
        ("frac_mul", ct.c_float),
        ("frac_load", ct.c_float),
        ("frac_store", ct.c_float),
        ("frac_branch", ct.c_float),
        ("locality", ct.c_float),
        ("reuse_geo_p", ct.c_float),
    ]


def build(force: bool = False) -> Path:
    """Compile libshrewd.so (make is timestamp-aware, so this is a cheap
    no-op when the binary is fresh — and picks up csrc edits when not)."""
    debug.dprintf("Native", "building %s", _LIB_PATH)
    try:
        subprocess.run(["make", "-C", str(_CSRC)] + (["-B"] if force else []),
                       check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"native build failed:\n{e.stdout}\n{e.stderr}") from e
    return _LIB_PATH


def lib() -> ct.CDLL:
    global _lib
    if _lib is None:
        build()
        _lib = ct.CDLL(str(_LIB_PATH))
        _lib.shrewd_golden_trials.restype = ct.c_int32
        _lib.shrewd_generate_trace.restype = ct.c_int32
    return _lib


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ct.POINTER(ct.c_int32))


def _u32p(a: np.ndarray):
    return a.ctypes.data_as(ct.POINTER(ct.c_uint32))


def _ascontig(a, dtype) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype=dtype)


def _trace_view(trace, arrays_keepalive: list) -> _TraceView:
    fields = {}
    for name, dt in (("opcode", np.int32), ("dst", np.int32),
                     ("src1", np.int32), ("src2", np.int32),
                     ("imm", np.uint32), ("taken", np.int32)):
        arr = _ascontig(getattr(trace, name), dt)
        arrays_keepalive.append(arr)
        fields[name] = arr
    return _TraceView(
        opcode=_i32p(fields["opcode"]), dst=_i32p(fields["dst"]),
        src1=_i32p(fields["src1"]), src2=_i32p(fields["src2"]),
        imm=_u32p(fields["imm"]), taken=_i32p(fields["taken"]),
        n=trace.n, nphys=trace.nphys, mem_words=trace.mem_words)


def golden_replay(trace) -> tuple[np.ndarray, np.ndarray]:
    """Fault-free native replay → (final_reg, final_mem)."""
    keep: list = []
    tv = _trace_view(trace, keep)
    init_reg = _ascontig(trace.init_reg, np.uint32)
    init_mem = _ascontig(trace.init_mem, np.uint32)
    out_reg = np.empty_like(init_reg)
    out_mem = np.empty_like(init_mem)
    lib().shrewd_golden_replay(ct.byref(tv), _u32p(init_reg), _u32p(init_mem),
                               _u32p(out_reg), _u32p(out_mem))
    return out_reg, out_mem


def golden_trials(trace, kinds, cycles, entries, bits, shadow_us,
                  coverage, compare_regs: bool = True) -> np.ndarray:
    """Serial C++ trial batch → outcomes int32[n_trials].

    The differential oracle for TrialKernel.run_batch and the serial-baseline
    denominator for the bench.  ``coverage`` is the per-µop shadow detection
    probability, float[trace.n] (``models.o3.compute_shadow_cov`` /
    ``TrialKernel.shadow_cov``).
    """
    keep: list = []
    tv = _trace_view(trace, keep)
    init_reg = _ascontig(trace.init_reg, np.uint32)
    init_mem = _ascontig(trace.init_mem, np.uint32)
    kinds = _ascontig(kinds, np.int32)
    cycles = _ascontig(cycles, np.int32)
    entries = _ascontig(entries, np.int32)
    bits = _ascontig(bits, np.int32)
    shadow_us = _ascontig(shadow_us, np.float32)
    cov = _ascontig(coverage, np.float32)
    if len(cov) != trace.n:
        raise ValueError(f"coverage must be per-µop (len {trace.n}), "
                         f"got {len(cov)}")
    n = len(kinds)
    if not (len(cycles) == len(entries) == len(bits) == len(shadow_us) == n):
        raise ValueError("fault field lengths differ")
    fv = _FaultView(
        kind=_i32p(kinds), cycle=_i32p(cycles), entry=_i32p(entries),
        bit=_i32p(bits),
        shadow_u=shadow_us.ctypes.data_as(ct.POINTER(ct.c_float)),
        n_trials=n)
    out = np.empty(n, dtype=np.int32)
    ran = lib().shrewd_golden_trials(
        ct.byref(tv), _u32p(init_reg), _u32p(init_mem), ct.byref(fv),
        cov.ctypes.data_as(ct.POINTER(ct.c_float)),
        ct.c_int32(1 if compare_regs else 0), _i32p(out))
    assert ran == n
    return out


def generate_trace(seed: int, n: int, nphys: int, mem_words: int,
                   working_set_words: int, frac_alu=0.50, frac_mul=0.05,
                   frac_load=0.20, frac_store=0.12, frac_branch=0.08,
                   locality=0.8, reuse_geo_p=0.3):
    """Native workload engine → Trace (fast path for large windows)."""
    from shrewd_tpu.trace.format import Trace
    p = _WorkloadParams(
        seed=seed, n=n, nphys=nphys, mem_words=mem_words,
        working_set_words=working_set_words, frac_alu=frac_alu,
        frac_mul=frac_mul, frac_load=frac_load, frac_store=frac_store,
        frac_branch=frac_branch, locality=locality, reuse_geo_p=reuse_geo_p)
    opcode = np.empty(n, dtype=np.int32)
    dst = np.empty(n, dtype=np.int32)
    src1 = np.empty(n, dtype=np.int32)
    src2 = np.empty(n, dtype=np.int32)
    imm = np.empty(n, dtype=np.uint32)
    taken = np.empty(n, dtype=np.int32)
    init_reg = np.empty(nphys, dtype=np.uint32)
    init_mem = np.empty(mem_words, dtype=np.uint32)
    rc = lib().shrewd_generate_trace(
        ct.byref(p), _i32p(opcode), _i32p(dst), _i32p(src1), _i32p(src2),
        _u32p(imm), _i32p(taken), _u32p(init_reg), _u32p(init_mem))
    if rc != 0:
        raise ValueError(f"shrewd_generate_trace failed with code {rc}")
    t = Trace(opcode=opcode, dst=dst, src1=src1, src2=src2, imm=imm,
              taken=taken, init_reg=init_reg, init_mem=init_mem)
    t.validate()
    return t
