"""The federation driver: gateway + N pods + supervisor in one loop.

``Federation`` composes the tier: it builds N pods (each a complete
``CampaignScheduler`` deployment — own spool, own outdir, own WAL), a
``Gateway`` routing over their published surfaces, and a
``PodSupervisor`` watching their heartbeat leases; then ``serve()``
round-robins every live pod through the scheduler's cooperative
``step()`` seam, one quantum per federation round, single-threaded and
deterministic.  Between rounds the driver runs the control plane:
claim gateway submissions, renew pod leases (withheld while a
``partition_pod`` chaos window is active), take supervisor verdicts
(death → ``Gateway.pod_dead`` failover; resurrection →
``Gateway.pod_heal`` + fencing evictions on the healed pod), advance
migrations, execute the quota revocations the gateway's sharded-merge
fold decides (``Gateway.shard_revocations`` →
``CampaignScheduler.revoke_quota``, idempotent and re-derived from the
ledger every round), and rebalance when one pod's ETA runs away.

In-process pods are the harness posture, not a toy: a pod "hard
killed" by ``kill_pod`` chaos simply stops being stepped and stops
beating — its durable outdir (dirty WAL, namespaced checkpoints, stale
heartbeat) is byte-for-byte what a SIGKILLed ``fleet.py --serve``
process leaves, so the failover the driver proves is the one a
multi-process deployment needs.  Bit-identity does the rest: every
placement resumes from frozen-key checkpoints, so the federation's
final tallies equal solo runs no matter which pods died, partitioned,
or traded tenants mid-campaign (the ``tests/test_federation.py``
pins).

Rebalancing policy (deliberately simple, journaled like everything
else): every ``rebalance_every`` rounds, if the hottest live pod's ETA
mass exceeds ``rebalance_factor ×`` the coldest's and the hot pod
serves more than one active tenant, the tenant with the largest
remaining ETA migrates to the coldest pod — drain-here/recover-there,
the same path failover uses.  A tenant is never migrated more than
``max_epochs`` times (placement flapping caps itself).

Import discipline: jax-free at module import.
"""

from __future__ import annotations

from shrewd_tpu.federation.gateway import Gateway, TERMINAL
from shrewd_tpu.federation.pods import PodHandle, PodKilled, PodSupervisor
from shrewd_tpu.service.queue import TenantSpec
from shrewd_tpu.service.scheduler import IDLE
from shrewd_tpu.utils import debug

import os
import time


class Federation:
    """One fleet-of-fleets (see module doc)."""

    def __init__(self, root: str, pod_names=("pod0", "pod1", "pod2"),
                 mesh=None, chaos=None, autoscale=None, on_round=None,
                 quantum: int = 1,
                 expiry_rounds: int = 3, rebalance_every: int = 0,
                 rebalance_factor: float = 4.0, max_epochs: int = 3,
                 idle_exit: bool = True, poll_interval: float = 0.2,
                 **sched_kw):
        self.root = root
        self.coord_dir = os.path.join(root, "coord")
        # ONE digest-keyed artifact store for the whole federation: a
        # binary ingested on any pod warm-starts in O(1) on every other
        # (failover/migration re-runs the tenant's ingest pipeline
        # against the same store, so re-placement costs zero lifts) —
        # and, since PR 18, one persistent executable cache: every pod
        # enables jax's on-disk compilation cache at the store's exec/
        # kind, so scheme-/thermal-mates dedupe compiles ACROSS pods
        sched_kw.setdefault("store_dir", os.path.join(root, "store"))
        # kept for pool reconciliation: journaled scale-ups spawn their
        # PodHandles with the same posture as the static pods
        self.mesh = mesh
        self.sched_kw = dict(sched_kw)
        self.pods = {
            name: PodHandle(name, os.path.join(root, "pods", name),
                            self.coord_dir, mesh=mesh, **sched_kw)
            for name in pod_names}
        self.gateway = Gateway(
            os.path.join(root, "gateway"),
            pods={n: p.port for n, p in self.pods.items()})
        self.supervisor = PodSupervisor(self.coord_dir,
                                        expiry_rounds=expiry_rounds)
        self.chaos = chaos
        self.autoscale = autoscale   # federation/autoscale.Autoscaler
        #: supervisor hook called once per round with the federation
        #: (the scenario runner's Pareto fold rides here) — callers own
        #: their own exception posture, same as the scheduler's on_tick
        self.on_round = on_round
        self.quantum = max(1, int(quantum))
        self.rebalance_every = int(rebalance_every)
        self.rebalance_factor = float(rebalance_factor)
        self.max_epochs = max(1, int(max_epochs))
        self.idle_exit = idle_exit
        self.poll_interval = float(poll_interval)
        self.round = 0
        self.idle_rounds = 0
        self.migrations = 0
        self.failovers = 0
        self.fenced = 0
        self.revoked = 0             # shard-convergence quota revocations
        self.scale_ups = 0           # pods added by pool autoscaling
        self.retired = 0             # pool retires completed

    @classmethod
    def recover(cls, root: str, pod_names=("pod0", "pod1", "pod2"),
                **kw) -> "Federation":
        """Rebuild a federation after ANY shutdown of the driver
        process — the gateway replays its WAL (``Gateway.recover``,
        repairing interrupted placements), and each pod's scheduler
        replays its own WAL lazily the first time the serve loop builds
        it (``PodHandle.build`` routes through
        ``CampaignScheduler.recover``).  The whole tier restarts the
        way it cold-starts: recovery IS the boot path."""
        fed = cls(root, pod_names=pod_names, **kw)
        # the freshly-built gateway is replaced by the recovered one
        # (same outdir, same spool object — Gateway.__init__ is
        # deliberately side-effect-free beyond mkdir, so the swap is
        # cheap; the WAL opens lazily on first append)
        fed.gateway = Gateway.recover(
            os.path.join(root, "gateway"),
            pods={n: p.port for n, p in fed.pods.items()},
            spool=fed.gateway.spool)
        return fed

    # --- submissions -------------------------------------------------------

    def submit(self, spec: TenantSpec) -> dict:
        """Direct admission through the gateway (tests, the CLI's
        --plans mode).  Spool/HTTP submissions go through
        ``gateway.poll_spool`` inside the serve loop instead."""
        return self.gateway.admit(spec)

    # --- chaos seams -------------------------------------------------------

    def _maybe_kill(self, pod: PodHandle) -> bool:
        """Consult the kill_pod schedule for this pod at the current
        (tick, round) coordinates; True when the pod just died.  The
        kill_action raises ``PodKilled`` so exactly one pod dies — the
        driver survives to supervise the failover, which is the point."""
        if self.chaos is None or pod.dead:
            return False
        name = pod.name

        def _kill(rc):
            raise PodKilled(name, rc)

        prev = self.chaos.kill_action
        self.chaos.kill_action = _kill
        try:
            tick = pod.sched.ticks if pod.sched is not None else 0
            self.chaos.maybe_kill_pod(name, tick=tick, round=self.round)
            # kill_new_pod: addressed by the journaled scale ordinal of
            # this pod's pool_scale_up record — consulted every step but
            # single-fire, so it lands on the fresh pod's FIRST quantum
            # no matter which round the autoscaler decided in
            scale = self.gateway.scaled_pods.get(name)
            if scale is not None:
                self.chaos.maybe_kill_new_pod(name, scale)
            # kill_shard: the schedule names a SUB-TENANT of a sharded
            # campaign; the fault kills whatever pod currently hosts it
            # — consult it for every shard child placed here so the
            # fault follows the shard through failover
            for e in self.gateway.entries.values():
                if e.shard_of and e.pod == name \
                        and e.status in ("routed", "placed"):
                    self.chaos.maybe_kill_shard(
                        e.spec.name, tick=tick, round=self.round)
        except PodKilled as e:
            debug.dprintf("Federation", "%s", e)
            pod.kill()
            return True
        finally:
            self.chaos.kill_action = prev
        return False

    # --- the elastic pool --------------------------------------------------

    def _drive_pool(self) -> None:
        """Reconcile pod processes to the gateway's journaled pool
        ledger — the WAL decides, this loop obeys.  Four passes, all
        idempotent per round:

        - let the autoscaler (when attached) journal at most one new
          decision;
        - spawn a ``PodHandle`` for every journaled scaled-up pod that
          has none yet (recovery lands here too: a ``pool_scale_up``
          replayed from the WAL gets its pod process back);
        - drive every pending retire: migrate non-terminal tenants off
          the fenced pod through the ordinary drain-here/recover-there
          path, and journal ``pool_retire_done`` once nothing
          non-terminal remains (a DEAD retiring pod needs no drain —
          lease expiry already failed its tenants over, which is what
          makes a hung retire safe);
        - drop handles for pods the ledger no longer owns.

        On convergence the elastic headroom is drained back to the
        static floor: every remaining autoscaled pod is retired, so a
        3→N federation always finishes at 3 — the pool's steady state
        is the hand-built one, and the WAL shows the full round trip."""
        gw = self.gateway
        if self.autoscale is not None:
            d = self.autoscale.tick(gw, self.round)
            if d is not None and d["action"] == "scale_up":
                self.scale_ups += 1
        if not gw.spool.pending() and gw.entries and gw.all_done():
            for name in sorted(gw.scaled_pods):
                if name in gw.retiring:
                    continue
                try:
                    gw.pool_retire_begin(name, reason="converged",
                                         round=self.round)
                except (ValueError, RuntimeError):
                    break
        for name in sorted(gw.pods):
            if name not in self.pods:
                self.pods[name] = PodHandle(
                    name, os.path.join(self.root, "pods", name),
                    self.coord_dir, mesh=self.mesh, **self.sched_kw)
        for name in sorted(gw.retiring):
            pod = self.pods.get(name)
            rec = gw.retires.get(name) or {}
            scale = int(rec.get("scale") or 0)
            if self.chaos is not None and pod is not None \
                    and not pod.dead:
                # the retire window is deterministically targetable:
                # kill_during_retire addresses this retire's journaled
                # scale ordinal, scoped to kill exactly this pod
                def _kill(rc, _n=name):
                    raise PodKilled(_n, rc)

                prev = self.chaos.kill_action
                self.chaos.kill_action = _kill
                try:
                    self.chaos.maybe_kill_during_retire(name, scale)
                except PodKilled as e:
                    debug.dprintf("Federation", "%s", e)
                    pod.kill()
                finally:
                    self.chaos.kill_action = prev
            live_here = [e for e in gw.entries.values()
                         if e.pod == name and e.status not in TERMINAL]
            if not live_here:
                if pod is not None and name not in gw.dead_pods:
                    pod.drain()
                gw.pool_retire_done(name, round=self.round)
                self.retired += 1
                continue
            if pod is None or pod.dead or name in gw.dead_pods:
                continue             # lease expiry moves the tenants
            for e in live_here:
                if e.status == "placed":
                    try:
                        target = gw._pick_pod(
                            exclude=(name,), avoid=gw._sibling_pods(e))
                    except RuntimeError:
                        break        # no live target: wait for one
                    gw.migrate(e.spec.name, target, "retire")
                if e.status == "draining" and pod.sched is not None \
                        and e.spec.name in pod.sched.tenants:
                    pod.sched.evict(e.spec.name, "retire")
        for name in list(self.pods):
            if name not in gw.pods:
                self.pods.pop(name)
        try:
            from shrewd_tpu.obs import metrics as obs_metrics

            obs_metrics.publish_pool(gw.outdir, gw.pool_status())
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    # --- the serve loop ----------------------------------------------------

    def _step_pod(self, pod: PodHandle) -> None:
        """One quantum of one pod: chaos-check at every scheduler tick
        boundary (kill_pod at_tick must land between ticks, exactly
        where a SIGKILL between run-loop iterations would), then step."""
        for _ in range(self.quantum):
            if self._maybe_kill(pod):
                return
            try:
                rc = pod.step()
            except PodKilled as e:
                debug.dprintf("Federation", "%s", e)
                pod.kill()
                return
            if rc is not None and rc is not IDLE:
                return                   # pod's scheduler went terminal

    def _supervise(self) -> None:
        """Take the supervisor's lease verdicts: deaths fail over,
        resurrections heal + fence."""
        alive = self.supervisor.observe(sorted(self.pods))
        for name, ok in alive.items():
            if not ok and name not in self.gateway.dead_pods:
                moved = self.gateway.pod_dead(name)
                self.failovers += len(moved)
            elif ok and name in self.gateway.dead_pods:
                pod = self.pods[name]
                stale = self.gateway.pod_heal(name)
                # fence the healed pod: any tenant the ledger moved
                # elsewhere while it was partitioned must stop being
                # served here — its copy's tallies are bit-identical,
                # but only the authoritative placement reports
                if pod.sched is not None and not pod.dead:
                    for tenant in stale:
                        t = pod.sched.tenants.get(tenant)
                        if t is not None and t.status in ("queued",
                                                          "running"):
                            pod.sched.evict(tenant, "fenced")
                            self.fenced += 1

    def _maybe_rebalance(self) -> None:
        if not self.rebalance_every \
                or self.round % self.rebalance_every:
            return
        live = self.gateway.live_pods()
        if len(live) < 2:
            return
        loads = {n: self.gateway.pod_load(n) for n in live}
        hot = max(live, key=lambda n: (loads[n]["score"], n))
        cold = min(live, key=lambda n: (loads[n]["score"], n))
        if hot == cold or loads[hot]["tenants"] < 2:
            return
        if loads[hot]["score"] < self.rebalance_factor \
                * max(loads[cold]["score"], 1.0):
            return
        # the hot pod's ETA ran away: move its largest-REMAINING-ETA
        # migratable tenant to the coldest pod (SLO-tightest first on
        # ties — the tenant with the least slack gets the fresh pod).
        # Remaining ETA is the LIVE per-tenant number the hot pod
        # publishes — the admission-time estimate on the entry is a
        # whole-plan + queue snapshot that never updates, and picking
        # by it would migrate nearly-finished tenants
        cands = [e for e in self.gateway.entries.values()
                 if e.pod == hot and e.status == "placed"
                 and e.epoch < self.max_epochs]
        if not cands:
            return
        try:
            from shrewd_tpu.obs import metrics as obs_metrics

            rows = obs_metrics.read(self.pods[hot].outdir).get(
                "tenants", {})
        except (OSError, ValueError):
            rows = {}

        def remaining(e):
            row = rows.get(e.spec.name) or {}
            eta = row.get("eta_trials")
            return float(eta) if eta is not None \
                else float(e.eta_trials or 0.0)

        pick = max(cands, key=lambda e: (
            remaining(e), -(e.spec.slo_s or float("inf")),
            e.spec.name))
        if remaining(pick) <= 0:
            return                       # nothing migratable is owed work
        if self.gateway.migrate(pick.spec.name, cold, "eta-runaway"):
            self.migrations += 1
            pod = self.pods[hot]
            if pod.sched is not None and not pod.dead:
                pod.sched.evict(pick.spec.name, "migrate")

    def serve(self, max_rounds: int = 100000) -> int:
        """Drive the federation until every admitted tenant is done,
        then drain the surviving pods to resumable checkpoints and
        snapshot the gateway.  Returns 0 on convergence."""
        while True:
            self.round += 1
            if self.round - self.idle_rounds > max_rounds:
                # only WORKING rounds count against the runaway guard:
                # a resident federation (idle_exit=False) polls an
                # empty spool indefinitely, and idling is not failing
                # to converge
                raise RuntimeError(
                    f"federation did not converge in {max_rounds} "
                    f"working rounds: {self.gateway._by_status()}")
            self.gateway.poll_spool()
            self._drive_pool()
            for name in sorted(self.pods):
                pod = self.pods[name]
                if pod.dead:
                    continue
                if pod.sched is None:
                    pod.build()
                self._step_pod(pod)
                pod.partitioned = self.chaos is not None and (
                    self.chaos.partition_active(name, self.round)
                    or self.chaos.partition_merge_active(
                        name, self.gateway.folds, self.round))
                if not pod.dead and not pod.partitioned:
                    pod.beat()
            self._supervise()
            self.gateway.poll()
            # shard convergence revocation: the gateway only decides
            # (journaled shard_converged + the stateless revocation
            # list); executing the revoke on each pod's scheduler is
            # the driver's job — same division of authority as
            # migration evictions.  revoke_quota is idempotent and the
            # list is re-derived from the ledger every poll, so a
            # revocation missed while a pod was dead or partitioned is
            # simply retried next round.
            for child, pod_name in self.gateway.shard_revocations():
                pod = self.pods.get(pod_name)
                if pod is None or pod.dead or pod.partitioned \
                        or pod.sched is None:
                    continue
                if pod.sched.revoke_quota(child, "shard-converged"):
                    self.revoked += 1
            self._maybe_rebalance()
            if self.on_round is not None:
                self.on_round(self)
            if not self.gateway.spool.pending() and (
                    self.gateway.all_done()
                    or not self.gateway.entries):
                if self.gateway.retiring or (self.gateway.entries
                                             and self.gateway.scaled_pods):
                    continue         # pool transitions still settling
                if self.idle_exit:
                    break
                self.idle_rounds += 1
                time.sleep(self.poll_interval)
        # converged: note chaos survivals (every injected pod fault the
        # federation finished through), drain survivors, snapshot
        if self.chaos is not None:
            for kind in ("kill_pod", "partition_pod", "kill_shard",
                         "partition_during_merge", "kill_during_retire",
                         "kill_new_pod"):
                done = self.chaos.injected.get(kind, 0) \
                    - self.chaos.survived.get(kind, 0)
                for _ in range(done):
                    self.chaos.note_survived(kind)
        for name in sorted(self.pods):
            self.pods[name].drain()
        self.gateway.shutdown()
        debug.dprintf("Federation", "converged in %d rounds "
                      "(%d failovers, %d migrations, %d fenced)",
                      self.round, self.failovers, self.migrations,
                      self.fenced)
        return 0

    # --- aggregate views ---------------------------------------------------

    def results(self) -> dict:
        return self.gateway.results()

    def tenant_tallies(self, name: str) -> dict:
        return self.gateway.tenant_tallies(name)

    def counters(self) -> dict:
        return {"rounds": self.round, "failovers": self.failovers,
                "migrations": self.migrations, "fenced": self.fenced,
                "revoked": self.revoked,
                "scale_ups": self.scale_ups, "retired": self.retired,
                "busy_s": {n: round(self.pods[n].busy_s, 4)
                           for n in sorted(self.pods)},
                "dead_pods": sorted(self.gateway.dead_pods)}
