"""The federation gateway: a crash-safe routing tier over N scheduler pods.

One ``Gateway`` owns the service surface of a fleet-of-fleets: it
accepts tenant submissions (the existing spool doc format — plus the
thin HTTP front in ``http_front.py``), decides which pod serves each
tenant, and survives its own hard kill the same way the pods survive
theirs — every routing decision is journaled to a write-ahead log
(``service/journal.py``'s ``FleetJournal``, reused verbatim) BEFORE any
in-memory ledger mutates, and ``Gateway.recover()`` replays
snapshot+journal back to the exact decision state.

**Routing is convergence-distance routing.**  Each pod's scheduler
publishes per-tenant ``eta_trials`` (the half-width-trajectory
trials-still-needed estimate — ``stopping.eta_trials``, the SAME
estimator its own interval planner consumes) and ``trials_per_s`` in
its ``metrics.json``; the gateway scores a pod by the ETA mass it is
already carrying plus the backlog the gateway has placed but the pod
has not yet surfaced, and routes to the minimum.  Admission therefore
returns a **deadline estimate** (projected seconds to this tenant's
convergence on the chosen pod), and a tenant's ``slo_s`` rides its
spec: the admission doc says up front whether the SLO looks feasible,
and the rebalancer uses the same projection to decide migrations.

**Placement is a two-phase handoff, and the WAL makes it exact.**  The
``route`` record (decision) is journaled first; then the tenant's spec
is submitted into the chosen pod's spool (the handoff — an fsync'd
atomic document); then the ``place`` record (commitment) is journaled.
A hard kill in EITHER window replays to safety: recovery re-scans the
decided pod's spool for the tenant's ticket — found means the handoff
landed (repair the ``place`` record), absent means it never did
(re-submit to the SAME journaled pod).  A tenant can never be placed
on two pods, because the only re-submission path replays the journaled
decision instead of re-deciding — the property ``crashcheck``'s
gateway sweep proves from every durability boundary.

**Migration is free because identity is bits.**  Every pod resumes a
tenant from its namespaced checkpoint on frozen per-batch PRNG keys,
so drain-on-pod-A → copy checkpoint → recover-on-pod-B finishes
bit-identical to an undisturbed solo run.  The same path serves both
planned rebalancing (``migrate``: evict on the source, re-place on the
target) and pod-death failover (``failover_pod``: the supervisor's
lease verdict, then re-place every stranded tenant from its last
checkpoint) — one mechanism, proven once.

Import discipline: jax-free (the gateway is pure host-side routing;
jax runs inside the pods).
"""

from __future__ import annotations

import os
import re
import shutil

import numpy as np

from shrewd_tpu import resilience as resil
from shrewd_tpu.federation.pods import PodPort
from shrewd_tpu.obs import trace as obs_trace
from shrewd_tpu.service.journal import FleetJournal
from shrewd_tpu.service.queue import SubmissionQueue, TenantSpec, sanitize
from shrewd_tpu.utils import debug

GATEWAY_CKPT_VERSION = 1

#: the gateway's durable names (under ``<outdir>/gateway_ckpt/``)
GATEWAY_SNAP = "gateway.json"
GATEWAY_JOURNAL = "journal.jsonl"

#: trials assumed for a plan that does not bound itself (deadline math
#: only — routing still works, the estimate is just labeled a guess)
DEFAULT_EST_TRIALS = 4096.0

#: entry statuses: accepted → routed → placed → done, with draining
#: (migration eviction pending on the source pod) re-entering routed
TERMINAL = ("done",)


def gateway_ckpt_dir(outdir: str) -> str:
    return os.path.join(outdir, "gateway_ckpt")


def gateway_journal_path(outdir: str) -> str:
    return os.path.join(gateway_ckpt_dir(outdir), GATEWAY_JOURNAL)


def gateway_snap_path(outdir: str) -> str:
    return os.path.join(gateway_ckpt_dir(outdir), GATEWAY_SNAP)


def est_trials(spec: TenantSpec) -> float:
    """Upper-bound trials estimate for one tenant (deadline math)."""
    plan = spec.plan or {}
    for key in ("max_trials", "min_trials"):
        v = plan.get(key)
        if v:
            return float(v)
    return DEFAULT_EST_TRIALS


def find_spool_ticket(spool_root: str, tenant: str):
    """``(subdir, ticket)`` of the named tenant's NEWEST submission
    anywhere in a pod's spool (pending/claimed/done/bad), or None — the
    handoff existence probe recovery replays the route decision
    against.  Matches the FULL ticket shape (6-digit seq + exact
    sanitized name — a bare suffix match would let tenant ``b_a``'s
    ticket answer for tenant ``a``); newest (highest seq) because a
    returning migration leaves the earlier placement's evicted ticket
    behind in ``done/``: the live placement is always the latest."""
    pat = re.compile(r"^\d{6}_" + re.escape(sanitize(tenant))
                     + r"\.json$")
    best = None
    for sub in ("pending", "claimed", "done", "bad"):
        d = os.path.join(spool_root, sub)
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for fn in names:
            if pat.match(fn) and (best is None or fn > best[1]):
                best = (sub, fn)
    return best


def copy_tenant_checkpoint(src_outdir: str, dst_outdir: str,
                           tenant: str) -> bool:
    """Migrate a tenant's namespaced state by bit-identity: copy
    ``tenants/<name>/`` (checkpoints + artifacts) from one pod's outdir
    to another's, then fsync the copied tree BEFORE the handoff — the
    checkpoint must be durable on the target before the target can be
    told to resume from it.  Idempotent (re-copy overwrites); returns
    False when the source has no namespace yet (a tenant that never
    started migrates as a fresh start — bit-identical anyway, frozen
    keys)."""
    src = os.path.join(src_outdir, "tenants", sanitize(tenant))
    if not os.path.isdir(src):
        return False
    dst = os.path.join(dst_outdir, "tenants", sanitize(tenant))
    shutil.copytree(src, dst, dirs_exist_ok=True)
    for root, _dirs, files in os.walk(dst):
        for name in files:
            with open(os.path.join(root, name), "rb") as f:
                os.fsync(f.fileno())
        resil.fsync_dir(root)
    return True


class RouteEntry:
    """One tenant's life at the gateway: spec + placement + ledgers."""

    def __init__(self, spec: TenantSpec, order: int, ticket: str = ""):
        self.spec = spec
        self.order = order           # acceptance order (tiebreak)
        self.ticket = ticket         # gateway-spool ticket ("" = direct)
        self.pod = ""                # the authoritative placement
        self.pod_ticket = ""         # ticket in the pod's spool
        self.from_pod = ""           # migration/failover source pod
        self.epoch = 0               # placements so far (route counter)
        self.status = "accepted"
        self.migrate_to = ""         # pending migration target
        self.deadline_s = None       # admission deadline estimate (s)
        self.eta_trials = None       # pod ETA mass at admission
        self.result = None           # the pod's done-doc
        self.history: list[dict] = []  # [{pod, reason, epoch}]
        # single-campaign sharding (spec.shards > 1): a PARENT entry
        # holds the merge ledger (never placed on any pod itself — its
        # status is "sharded" until the merged campaign completes); a
        # CHILD (sub-tenant) entry carries its parent's name and its
        # stripe index and is otherwise an ordinary routed tenant —
        # migration, failover and fencing need no shard-specific mode
        self.shard_of = ""           # child: parent tenant name
        self.shard_index = -1        # child: round-robin stripe offset
        self.shards: list[str] = []  # parent: children in stripe order
        self.fold_shards: dict = {}  # parent: last folded shard reports
        self.fold_merged: dict = {}  # parent: last merged lane state
        self.fold_seq = 0            # parent: shard_fold records so far
        self.converged = False       # parent: merged stopping rule fired

    def to_dict(self) -> dict:
        return {"spec": self.spec.to_dict(), "order": self.order,
                "ticket": self.ticket, "pod": self.pod,
                "pod_ticket": self.pod_ticket, "from_pod": self.from_pod,
                "epoch": self.epoch, "status": self.status,
                "migrate_to": self.migrate_to,
                "deadline_s": self.deadline_s,
                "eta_trials": self.eta_trials,
                "result": self.result, "history": list(self.history),
                "shard_of": self.shard_of,
                "shard_index": self.shard_index,
                "shards": list(self.shards),
                "fold_shards": dict(self.fold_shards),
                "fold_merged": dict(self.fold_merged),
                "fold_seq": self.fold_seq,
                "converged": self.converged}

    @classmethod
    def from_dict(cls, d: dict) -> "RouteEntry":
        e = cls(TenantSpec.from_dict(d["spec"]),
                order=int(d.get("order", 0)),
                ticket=d.get("ticket", ""))
        e.pod = str(d.get("pod") or "")
        e.pod_ticket = str(d.get("pod_ticket") or "")
        e.from_pod = str(d.get("from_pod") or "")
        e.epoch = int(d.get("epoch", 0))
        e.status = str(d.get("status", "accepted"))
        e.migrate_to = str(d.get("migrate_to") or "")
        e.deadline_s = d.get("deadline_s")
        e.eta_trials = d.get("eta_trials")
        e.result = d.get("result")
        e.history = list(d.get("history") or [])
        e.shard_of = str(d.get("shard_of") or "")
        e.shard_index = int(d.get("shard_index", -1))
        e.shards = list(d.get("shards") or [])
        e.fold_shards = dict(d.get("fold_shards") or {})
        e.fold_merged = dict(d.get("fold_merged") or {})
        e.fold_seq = int(d.get("fold_seq", 0))
        e.converged = bool(d.get("converged", False))
        return e


class Gateway:
    """The crash-safe routing tier (see module doc).

    ``pods`` maps pod name → ``PodPort`` (or anything with
    ``.spool``/``.outdir``); the gateway only ever touches a pod
    through its spool and its published durable surfaces, so the same
    gateway code serves in-process pods and separate server
    processes."""

    def __init__(self, outdir: str, pods: dict | None = None,
                 spool: SubmissionQueue | None = None,
                 compact_every: int = 64):
        self.outdir = outdir
        self.pods: dict[str, PodPort] = {}
        for name, p in (pods or {}).items():
            self.pods[name] = (p if isinstance(p, PodPort)
                               else PodPort(name, p.spool_dir, p.outdir)
                               if hasattr(p, "spool_dir")
                               else PodPort(name, p.spool, p.outdir))
        self.spool = spool if spool is not None else SubmissionQueue(
            os.path.join(outdir, "spool"))
        self.compact_every = max(1, int(compact_every))
        self.entries: dict[str, RouteEntry] = {}
        self.dead_pods: set[str] = set()
        # --- the elastic pool ledger (autoscaling) ---
        # every pool transition is journaled BEFORE any pod is touched
        # (pool_scale_up / pool_retire_begin / pool_retire_done), so the
        # pool membership below is pure WAL-derived state: recovery
        # replays it, the obs gauges read it, and nothing else may be a
        # second source of truth for what pods exist
        self.retiring: set[str] = set()      # retire begun, not done
        self.scale_seq = 0                   # journaled scale ordinal
        self.scaled_pods: dict[str, int] = {}  # autoscaled pod -> ordinal
        self.retires: dict[str, dict] = {}   # pod -> retire bookkeeping
        self.recoveries = 0
        self.journal_torn = 0
        self._journal: FleetJournal | None = None
        self._journal_floor = 0

    # --- the write-ahead routing ledger -----------------------------------

    def _open_journal(self) -> FleetJournal:
        if self._journal is None:
            floor = self._journal_floor
            if floor == 0:
                try:
                    snap = resil.load_json_verified(
                        gateway_snap_path(self.outdir))
                    floor = int(snap.get("journal_seq", -1)) + 1
                except (OSError, ValueError):
                    pass
            self._journal = FleetJournal(
                gateway_journal_path(self.outdir), next_seq=floor)
            self._journal.append("gw_config", {
                "pods": sorted(self.pods),
                "compact_every": self.compact_every})
        return self._journal

    def _jlog(self, kind: str, data: dict | None = None) -> None:
        """Durably journal one routing transition BEFORE the in-memory
        ledgers are trusted — the same WAL contract the pod schedulers
        carry (GL201-certified): a hard kill can interrupt the gateway
        between any two instructions and replay reconstructs the exact
        decision state."""
        self._open_journal().append(kind, data)

    def _maybe_compact(self) -> None:
        j = self._journal
        if j is not None and j.since_compact >= self.compact_every:
            self.checkpoint()

    # --- load / routing policy --------------------------------------------

    def live_pods(self) -> list[str]:
        """Pods eligible to receive placements.  A RETIRING pod is
        fenced out the instant ``pool_retire_begin`` lands — it may
        keep beating while it drains (a hung retire may beat for a long
        time), but no placement decision can ever choose it again: the
        journaled retire IS the fence, not the lease."""
        return [n for n in sorted(self.pods)
                if n not in self.dead_pods and n not in self.retiring]

    def pod_load(self, name: str) -> dict:
        """One pod's live load, read from its published ``metrics.json``
        (never its internals): the ETA mass it is carrying
        (``eta_trials`` summed over non-terminal tenants — convergence
        distance, not instantaneous throughput), its serving rate, and
        the backlog the gateway has placed but the pod has not yet
        surfaced in metrics."""
        port = self.pods[name]
        load = {"pod": name, "eta_trials": 0.0, "trials_per_s": 0.0,
                "tenants": 0, "backlog_trials": 0.0,
                "dead": name in self.dead_pods}
        try:
            from shrewd_tpu.obs import metrics as obs_metrics

            snap = obs_metrics.read(port.outdir)
        except (OSError, ValueError):
            snap = {}
        seen = set()
        for tname, row in (snap.get("tenants") or {}).items():
            seen.add(tname)
            if row.get("status") in ("queued", "running"):
                load["tenants"] += 1
                load["eta_trials"] += float(row.get("eta_trials") or 0.0)
                load["trials_per_s"] += float(row.get("trials_per_s")
                                              or 0.0)
        for e in self.entries.values():
            if e.pod == name and e.status in ("routed", "placed") \
                    and e.spec.name not in seen:
                load["backlog_trials"] += est_trials(e.spec)
        load["score"] = load["eta_trials"] + load["backlog_trials"]
        return load

    def pod_loads(self) -> dict:
        return {n: self.pod_load(n) for n in sorted(self.pods)}

    def _rate(self, loads: dict) -> float:
        """Observed serving rate for deadline projection: the mean
        per-pod trials/s where data exists (0.0 = no data yet — the
        estimate is withheld rather than invented).  LIVE pods only: a
        dead pod's frozen metrics would keep inflating the projection
        and report feasible SLOs the survivors cannot meet."""
        rates = [ld["trials_per_s"] for ld in loads.values()
                 if ld["trials_per_s"] > 0 and not ld["dead"]]
        return sum(rates) / len(rates) if rates else 0.0

    def _pick_pod(self, exclude=(), loads: dict | None = None,
                  avoid=()) -> str:
        """The routing decision: the live pod carrying the least ETA
        mass (score = published ETA + unplaced backlog), ties broken by
        name — reproducible given the same published metrics.
        ``loads`` lets a caller that already read the pods' metrics
        reuse them (one read per placement, not one per question).
        ``avoid`` is a SOFT preference (``exclude`` is hard): candidates
        outside it win when any exist, but when every live pod is
        avoided the pick falls back to the full set — liveness over
        spread."""
        cands = [n for n in self.live_pods() if n not in exclude]
        if not cands:
            raise RuntimeError("no live pod to route to")
        preferred = [n for n in cands if n not in avoid] or cands
        if loads is None:
            loads = {n: self.pod_load(n) for n in cands}
        return min(preferred, key=lambda n: (loads[n]["score"], n))

    def _migration_target(self, e: RouteEntry) -> str:
        """Where a drained tenant goes: the journaled ``migrate``
        target while that pod is still alive, else a fresh pick — the
        target's lease may have expired between the migrate decision
        and the source drain completing, and a placement on a dead pod
        would strand the tenant forever."""
        if e.migrate_to and e.migrate_to in self.pods \
                and e.migrate_to not in self.dead_pods \
                and e.migrate_to not in self.retiring:
            return e.migrate_to
        return self._pick_pod(exclude=(e.pod,))

    # --- admission --------------------------------------------------------

    def admit(self, spec: TenantSpec, ticket: str = "") -> dict:
        """Accept one tenant, decide its pod, hand it off.  Returns the
        admission doc: placement, the deadline estimate (projected
        seconds to convergence on the chosen pod, from the ETA mass
        ahead of it and the observed serving rate), and whether the
        spec's SLO looks feasible against it."""
        if spec.name in self.entries:
            raise ValueError(f"tenant {spec.name!r} already admitted")
        if spec.shards > 1:
            # collision check BEFORE the accept record becomes durable:
            # a refused admission must leave no zombie ledger entry
            for nm in self._shard_names(spec):
                if nm in self.entries:
                    raise ValueError(
                        f"tenant {spec.name!r}: sub-tenant name {nm!r} "
                        "already admitted")
        e = RouteEntry(spec, order=len(self.entries), ticket=ticket)
        self._jlog("accept", {"tenant": spec.name,
                              "spec": spec.to_dict(), "ticket": ticket,
                              "order": e.order})
        self.entries[spec.name] = e
        obs_trace.tracer().emit(
            "gw_accept", cat="federation", tenant=spec.name,
            order=e.order, slo_s=spec.slo_s)
        if spec.shards > 1:
            self._shard_split(e)
            self._maybe_compact()
            kids = [self.entries[n] for n in e.shards]
            dls = [c.deadline_s for c in kids if c.deadline_s is not None]
            # the sharded campaign finishes when its LAST shard does;
            # each shard's deadline already reflects only its slice of
            # the batch space (est_trials on the scaled sub-plan), so
            # the max is the N-way-parallel finish estimate — not the
            # solo trajectory overstated by N
            deadline = max(dls) if dls and len(dls) == len(kids) else None
            doc = {"tenant": spec.name, "pod": "", "ticket": "",
                   "shards": list(e.shards), "deadline_s": deadline,
                   "eta_trials": sum(c.eta_trials or 0.0 for c in kids),
                   "slo_s": spec.slo_s}
            doc["slo_ok"] = (None if not spec.slo_s or deadline is None
                             else deadline <= spec.slo_s)
            return doc
        loads = self.pod_loads()
        pod = self._pick_pod(loads=loads)
        self._route_to(e, pod, reason="admit", loads=loads)
        self._maybe_compact()
        doc = {"tenant": spec.name, "pod": e.pod,
               "ticket": e.pod_ticket, "deadline_s": e.deadline_s,
               "eta_trials": e.eta_trials, "slo_s": spec.slo_s}
        doc["slo_ok"] = (None if not spec.slo_s or e.deadline_s is None
                         else e.deadline_s <= spec.slo_s)
        return doc

    def poll_spool(self) -> int:
        """Claim pending gateway-spool submissions into admission (the
        service front: ``tools/federation.py --submit`` and the HTTP
        front both land here)."""
        n = 0
        for ticket, spec in self.spool.claim():
            try:
                self.admit(spec, ticket=ticket)
                n += 1
            except ValueError as e:
                debug.dprintf("Federation", "refused %s: %s", ticket, e)
                self.spool.mark_done(ticket, {
                    "tenant": spec.name, "status": "refused",
                    "error": str(e)})
        return n

    # --- placement (the two-phase handoff) --------------------------------

    def _route_to(self, e: RouteEntry, pod: str, reason: str,
                  from_pod: str = "", loads: dict | None = None) -> None:
        """Journal the route DECISION, then perform the handoff.  The
        decision record carries everything replay needs to finish the
        placement without re-deciding (pod, epoch, migration source);
        the deadline estimate rides along as observability."""
        if loads is None:
            loads = self.pod_loads()
        rate = self._rate(loads)
        ahead = loads[pod]["score"]
        eta = ahead + est_trials(e.spec)
        deadline = round(eta / rate, 2) if rate > 0 else None
        epoch = e.epoch + 1
        self._jlog("route", {"tenant": e.spec.name, "pod": pod,
                             "epoch": epoch, "reason": reason,
                             "from": from_pod,
                             "eta_trials": round(eta, 1),
                             "deadline_s": deadline})
        e.pod = pod
        e.from_pod = from_pod
        e.epoch = epoch
        e.status = "routed"
        e.migrate_to = ""
        e.eta_trials = round(eta, 1)
        e.deadline_s = deadline
        e.history.append({"pod": pod, "reason": reason, "epoch": epoch})
        obs_trace.tracer().emit(
            "gw_route", cat="federation", tenant=e.spec.name, pod=pod,
            reason=reason, epoch=epoch)
        debug.dprintf("Federation", "%s -> %s (%s, epoch %d, eta %.0f "
                      "trials)", e.spec.name, pod, reason, epoch, eta)
        self._place(e)

    def _place(self, e: RouteEntry) -> None:
        """The handoff: migrate the checkpoint (durable BEFORE the pod
        can be told to resume from it), submit the spec into the
        decided pod's spool, journal the ``place`` commitment.  A kill
        before the submit replays the route and re-submits; a kill
        after it finds the ticket by scan and repairs the record —
        either way the tenant lands on exactly one pod."""
        port = self.pods[e.pod]
        if e.from_pod and e.from_pod in self.pods:
            copy_tenant_checkpoint(self.pods[e.from_pod].outdir,
                                   port.outdir, e.spec.name)
        ticket = SubmissionQueue(port.spool).submit(e.spec)
        self._jlog("place", {"tenant": e.spec.name, "pod": e.pod,
                             "ticket": ticket, "epoch": e.epoch})
        e.pod_ticket = ticket
        e.status = "placed"
        obs_trace.tracer().emit(
            "gw_place", cat="federation", tenant=e.spec.name,
            pod=e.pod, ticket=ticket)

    # --- results / completion ---------------------------------------------

    def _mark_done(self, e: RouteEntry, doc: dict) -> None:
        self._jlog("done", {"tenant": e.spec.name, "pod": e.pod,
                            "epoch": e.epoch, "result": dict(doc)})
        e.result = dict(doc)
        e.status = "done"
        obs_trace.tracer().emit(
            "gw_done", cat="federation", tenant=e.spec.name, pod=e.pod,
            status=str(doc.get("status")))
        if e.ticket:
            self.spool.mark_done(e.ticket, {
                "tenant": e.spec.name, "pod": e.pod,
                "status": doc.get("status"), "rc": doc.get("rc"),
                "trials": doc.get("trials"),
                "results": doc.get("results")})
        debug.dprintf("Federation", "%s done on %s (%s)", e.spec.name,
                      e.pod, doc.get("status"))

    def _pod_done_doc(self, pod: str, e: RouteEntry) -> dict | None:
        if not e.pod_ticket:
            return None
        return SubmissionQueue(self.pods[pod].spool).done(e.pod_ticket)

    def poll(self) -> None:
        """Learn completions and advance in-flight migrations from the
        pods' published done-docs — the gateway's only result channel,
        so it works identically for in-process and subprocess pods."""
        for e in self.entries.values():
            if e.status not in ("placed", "draining") \
                    or e.pod not in self.pods:
                continue         # unknown pods are the failover pass's job
            doc = self._pod_done_doc(e.pod, e)
            if doc is None:
                continue
            status = doc.get("status")
            if status == "evicted":
                # the eviction this gateway requested (migration) — or
                # a fencing eviction replayed late; either way the
                # checkpoint is free to move now.  (A campaign that
                # finished before the drain landed publishes its real
                # terminal doc instead: nothing left to migrate.)
                self._route_to(e, self._migration_target(e),
                               reason="migrate", from_pod=e.pod)
            elif status == "refused":
                # the pod could not serve this placement — e.g. a
                # healed partition's stale TERMINAL copy of the name
                # still holds its roster slot.  A refusal carries no
                # results, so it must never be adopted as the final
                # doc: place elsewhere (the checkpoint the last drain
                # left makes the move free, and bit-identity makes a
                # staler checkpoint merely recompute, never diverge)
                self._route_to(e, self._pick_pod(exclude=(e.pod,)),
                               reason="refused", from_pod=e.from_pod)
            else:
                self._mark_done(e, doc)
        for e in list(self.entries.values()):
            if e.status == "sharded":
                self._advance_shards(e)
        self._maybe_compact()

    # --- single-campaign sharding (the merge fold) --------------------------
    #
    # One tenant with ``shards: N`` splits into N journaled sub-tenants,
    # each serving the round-robin stripe {i, i+N, ...} of the parent's
    # frozen batch-id space (plan.shard_index/shard_count — the
    # orchestrator re-dispatches on the same frozen per-batch PRNG
    # keys).  The gateway folds the shards' per-stratum tallies with an
    # ORDER-FIXED merge (ascending shard index — the psum-vs-shard
    # invariant integrity.py checks per batch, lifted one level), so
    # the merged trajectory is bit-identical to the solo run.  Every
    # fold transition is journaled BEFORE the merge ledger mutates
    # (shard_split / shard_fold / shard_converged — the same GL201 WAL
    # contract as routing), which is what makes a mid-merge pod kill
    # replayable from the gateway WAL: crashcheck sweeps every fold
    # boundary like every placement boundary.

    def _shard_names(self, spec: TenantSpec) -> list[str]:
        n, _ceiling, _bs = self._shard_geometry(spec)
        return [f"{spec.name}+shard{i}" for i in range(n)]

    def _shard_geometry(self, spec: TenantSpec) -> tuple[int, int, int]:
        """(effective shard count, parent ceiling batches, batch size):
        the shard count is clamped to the parent's batch ceiling — a
        stripe with no batch ids would be a zero-work sub-tenant."""
        plan = spec.plan or {}
        bs = int(plan.get("batch_size") or 4096)
        ceiling = max(1, -(-int(est_trials(spec)) // bs))
        return min(int(spec.shards), ceiling), ceiling, bs

    def _shard_specs(self, spec: TenantSpec) -> list[TenantSpec]:
        """Derive the sub-tenant specs: shard i of N gets the stripe
        {i, i+N, ...} below the parent ceiling, and its plan's
        min/max_trials are BOTH set to the stripe's trial budget — a
        shard must never self-converge early (the stopping rule runs on
        the MERGED trajectory at the gateway), and its published ETA /
        admission deadline then reflect exactly its share of the
        remaining batch space instead of overstating the sharded
        campaign's finish time by N×."""
        n, ceiling, bs = self._shard_geometry(spec)
        quota = int(spec.quota_batches or 0)
        out = []
        for i in range(n):
            p = dict(spec.plan)
            p["shard_index"] = i
            p["shard_count"] = n
            slice_batches = (ceiling - i + n - 1) // n
            p["max_trials"] = p["min_trials"] = slice_batches * bs
            out.append(TenantSpec(
                name=f"{spec.name}+shard{i}", plan=p,
                priority=spec.priority, weight=spec.weight,
                quota_batches=((quota - i + n - 1) // n if quota else 0),
                submitted_at=spec.submitted_at, slo_s=0.0, shards=1))
        return out

    def _shard_split(self, e: RouteEntry) -> None:
        """Journal the split decision, then create + place the
        sub-tenants.  The record carries the full child specs so replay
        reconstructs the exact same stripes without re-deriving
        anything; placement itself goes through the ordinary journaled
        route→handoff→place protocol per child."""
        specs = self._shard_specs(e.spec)
        names = [s.name for s in specs]
        self._jlog("shard_split", {"tenant": e.spec.name,
                                   "shards": names,
                                   "specs": [s.to_dict() for s in specs]})
        e.status = "sharded"
        e.shards = names
        for i, s in enumerate(specs):
            if s.name in self.entries:
                continue             # replayed split already built it
            c = RouteEntry(s, order=len(self.entries))
            c.shard_of = e.spec.name
            c.shard_index = i
            self.entries[s.name] = c
        obs_trace.tracer().emit(
            "gw_shard_split", cat="federation", tenant=e.spec.name,
            shards=len(names))
        debug.dprintf("Federation", "%s split into %d shards",
                      e.spec.name, len(names))
        self._place_shards(e)

    def _place_shards(self, e: RouteEntry) -> int:
        """Place every still-queued sub-tenant on a live pod hosting no
        LIVE sibling (distinct pods — the point of sharding is stripe
        parallelism).  With more shards than free pods the surplus
        stays queued at the gateway ("accepted", no pod) and lands here
        again when a sibling finishes — admission never fails on
        shards > pods.  Failover is deliberately NOT held to the
        distinct-pod rule (liveness over spread): only this initial/
        backfill placement is."""
        placed = 0
        kids = [self.entries[n] for n in e.shards if n in self.entries]
        for c in kids:
            if c.status != "accepted":
                continue
            busy = {k.pod for k in kids
                    if k is not c and k.pod
                    and k.status in ("routed", "placed", "draining")}
            cands = [p for p in self.live_pods() if p not in busy]
            if not cands:
                continue
            loads = self.pod_loads()
            pod = min(cands, key=lambda n: (loads[n]["score"], n))
            self._route_to(c, pod, reason="shard", loads=loads)
            placed += 1
        return placed

    def _shard_report(self, c: RouteEntry, last: dict | None) -> dict:
        """One sub-tenant's freshest per-lane cumulative counts: the
        final done-doc when terminal (authoritative), else the hosting
        pod's published metrics row (``lanes`` — the same live numbers
        the pod's own stopping rule reads).  Monotone against the last
        folded report: a shard recovered from pod death resumes from
        its last checkpoint, which may trail its last published
        metrics — the fold keeps the deeper prefix (any cumulative
        snapshot of a frozen-key stripe is exact; deeper is simply
        closer to done)."""
        def total(lanes: dict) -> int:
            return sum(int(v.get("trials") or 0) for v in lanes.values())

        if c.result is not None:
            res = c.result.get("results") or {}
            lanes = {lane: {"tallies": row["tallies"],
                            "trials": row["trials"],
                            "strata": row.get("strata")}
                     for lane, row in res.items()}
            if lanes or not last:
                return lanes
            return dict(last)
        row = None
        if c.pod and c.pod in self.pods:
            try:
                from shrewd_tpu.obs import metrics as obs_metrics

                snap = obs_metrics.read(self.pods[c.pod].outdir)
                row = (snap.get("tenants") or {}).get(c.spec.name)
            except (OSError, ValueError):
                row = None
        lanes = dict((row or {}).get("lanes") or {})
        if last and total(last) > total(lanes):
            return dict(last)
        return lanes

    def _merged_fold(self, e: RouteEntry, lanes_by_shard: dict) -> dict:
        """The order-fixed merge + merged stopping evaluation, with the
        PARENT plan's precision target (``stopping.merged_fold`` — the
        same rule selection the solo campaign's convergence check
        applies; lazy import keeps this module jax-free at import)."""
        from shrewd_tpu.parallel import stopping

        plan = e.spec.plan or {}
        return stopping.merged_fold(
            lanes_by_shard, bool(plan.get("stratify")),
            float(plan.get("confidence") or 0.95),
            float(plan.get("target_halfwidth") or 0.01),
            int(plan.get("min_trials") or 0))

    def _expected_lanes(self, plan: dict) -> int:
        """Lane count of the merged campaign (simpoints × per-simpoint
        structures + plan-level coherence tiers) — the merged stopping
        rule may only revoke shard quota once EVERY lane's merged CI is
        tight, so a lane no shard has started yet must block
        convergence, not be invisible to it."""
        sps = len(plan.get("simpoints") or [])
        per_sp = [s for s in plan.get("structures") or []
                  if s.split(":", 1)[0] not in ("mesi", "noc")]
        plan_level = [s for s in plan.get("structures") or []
                      if s.split(":", 1)[0] in ("mesi", "noc")]
        return sps * len(per_sp) + len(plan_level)

    def _advance_shards(self, e: RouteEntry) -> None:
        """One merge-fold pass for one sharded parent: backfill queued
        shards, fold the freshest per-shard cumulative tallies
        (journaled BEFORE the merge ledger mutates), evaluate the
        merged stopping rule, and finalize the parent when every shard
        is terminal.  Idempotent per poll — a fold with no new trials
        journals nothing."""
        if e.status != "sharded":
            return
        self._place_shards(e)
        kids = [self.entries[n] for n in e.shards if n in self.entries]
        reports = {c.spec.name: self._shard_report(
            c, e.fold_shards.get(c.spec.name)) for c in kids}
        merged = self._merged_fold(
            e, {c.shard_index: reports[c.spec.name] for c in kids})
        prev = sum(int(m.get("trials") or 0)
                   for m in e.fold_merged.values())
        cur = sum(int(m.get("trials") or 0) for m in merged.values())
        if cur > prev or e.fold_seq == 0:
            self._jlog("shard_fold", {"tenant": e.spec.name,
                                      "fold": e.fold_seq + 1,
                                      "shards": reports,
                                      "merged": merged})
            e.fold_shards = reports
            e.fold_merged = merged
            e.fold_seq += 1
            obs_trace.tracer().emit(
                "gw_shard_fold", cat="federation", tenant=e.spec.name,
                fold=e.fold_seq, trials=cur)
            debug.dprintf("Federation", "%s fold %d: %d merged trials",
                          e.spec.name, e.fold_seq, cur)
        if not e.converged:
            m = e.fold_merged
            if m and len(m) >= self._expected_lanes(e.spec.plan or {}) \
                    and all(v.get("converged") for v in m.values()):
                # the merged trajectory satisfies the until-CI stopping
                # rule on every lane: journal the verdict, then revoke
                # what remains.  Late-arriving shard trials past this
                # fold stay honest — they are valid frozen-key trials
                # the final merge simply includes, exactly like the
                # pipelined engine's honest late stop.
                self._jlog("shard_converged", {"tenant": e.spec.name,
                                               "fold": e.fold_seq})
                e.converged = True
                obs_trace.tracer().emit(
                    "gw_shard_converged", cat="federation",
                    tenant=e.spec.name, fold=e.fold_seq, trials=cur)
                debug.dprintf("Federation",
                              "%s converged at fold %d (%d trials)",
                              e.spec.name, e.fold_seq, cur)
        if e.converged:
            for c in kids:
                if c.status == "accepted":
                    # a queued surplus shard never reached any pod: its
                    # revocation is a pure gateway decision
                    self._mark_done(c, {
                        "tenant": c.spec.name, "status": "pruned",
                        "rc": 4, "trials": 0, "batches": 0,
                        "results": {}, "reason": "shard-converged"})
        if kids and all(c.status in TERMINAL for c in kids):
            self._finalize_shards(e, kids)

    def shard_revocations(self) -> list[tuple[str, str]]:
        """[(sub-tenant, pod)] whose remaining quota must be revoked on
        the hosting pod — the merged trajectory converged.  The driver
        executes these through the pods' journaled ``revoke_quota``
        seam; the list is re-derived from the ledger every poll, so a
        crash between the verdict and any revocation replays to the
        same pending set (pod-side revoke_quota is idempotent)."""
        out = []
        for e in self.entries.values():
            if e.status != "sharded" or not e.converged:
                continue
            for n in e.shards:
                c = self.entries.get(n)
                if c is not None and c.status in ("routed", "placed") \
                        and c.pod and c.pod not in self.dead_pods:
                    out.append((n, c.pod))
        return out

    @property
    def folds(self) -> int:
        """Total shard_fold records across every sharded tenant — the
        deterministic merge-progress ordinal chaos triggers key on
        (``partition_during_merge``'s ``at_fold``)."""
        return sum(e.fold_seq for e in self.entries.values())

    def _finalize_shards(self, e: RouteEntry, kids: list) -> None:
        """Every shard terminal: build the parent's merged done-doc
        from each shard's DEEPEST exact evidence — its final done-doc
        or, when deeper, its last journaled fold (order-fixed merge of
        complete runs and revocation-pruned partials — both
        first-class: a pruned shard's tallies are exact cumulative
        counts over its consumed stripe prefix) and mark the parent
        done through the ordinary journaled completion path.  The fold
        ledger can legitimately be AHEAD of a shard's final result: a
        crash after a ``shard_fold`` record became durable rolls the
        pod back to older checkpoints, and the replayed convergence
        verdict then prunes the resumed shard before it recomputes
        trials the WAL already folded — the journaled fold is exact
        durable evidence of that deeper prefix, so the final merge
        keeps it (bit-identity to the undisturbed run is exactly this
        monotone rule, the one ``_shard_report`` applies live)."""
        def total(lanes: dict) -> int:
            return sum(int(v.get("trials") or 0) for v in lanes.values())

        lanes_by_shard = {}
        for c in kids:
            res = (c.result or {}).get("results") or {}
            lanes = {lane: {"tallies": row["tallies"],
                            "trials": row["trials"],
                            "strata": row.get("strata")}
                     for lane, row in res.items()}
            last = e.fold_shards.get(c.spec.name)
            if last and total(last) > total(lanes):
                lanes = dict(last)
            lanes_by_shard[c.shard_index] = lanes
        merged = self._merged_fold(e, lanes_by_shard)
        results = {lane: {"tallies": m["tallies"], "trials": m["trials"],
                          "avf": m["avf"], "converged": m["converged"],
                          "strata": m["strata"]}
                   for lane, m in merged.items()}
        bad = [c for c in kids if (c.result or {}).get("status")
               not in ("complete", "pruned")]
        doc = {
            "tenant": e.spec.name,
            "status": ("complete" if not bad
                       else str((bad[0].result or {}).get("status"))),
            "rc": (0 if not bad else (bad[0].result or {}).get("rc")),
            "trials": sum(int(m["trials"]) for m in merged.values()),
            "batches": sum(int((c.result or {}).get("batches") or 0)
                           for c in kids),
            "wall_s": max([float((c.result or {}).get("wall_s") or 0.0)
                           for c in kids] or [0.0]),
            "results": results,
            "shards": {c.spec.name: {
                "status": (c.result or {}).get("status"),
                "trials": (c.result or {}).get("trials"),
                "pod": c.pod} for c in kids},
            "folds": e.fold_seq,
            "converged": e.converged,
        }
        self._mark_done(e, doc)

    # --- migration / failover ----------------------------------------------

    def migrate(self, tenant: str, to_pod: str, reason: str = "") -> bool:
        """Begin a live rebalancing migration: journal the intent, mark
        the entry draining.  The caller (the federation driver) evicts
        the tenant on the source pod; ``poll()`` completes the move
        when the source publishes the eviction done-doc.  Returns False
        when the tenant is not currently placed."""
        e = self.entries.get(tenant)
        if e is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        if e.status != "placed" or to_pod not in self.pods \
                or to_pod in self.dead_pods or to_pod in self.retiring \
                or to_pod == e.pod:
            return False
        self._jlog("migrate", {"tenant": tenant, "from": e.pod,
                               "to": to_pod,
                               "reason": reason or "rebalance"})
        e.migrate_to = to_pod
        e.status = "draining"
        obs_trace.tracer().emit(
            "gw_migrate", cat="federation", tenant=tenant,
            src=e.pod, dst=to_pod, reason=reason or "rebalance")
        debug.dprintf("Federation", "migrating %s: %s -> %s (%s)",
                      tenant, e.pod, to_pod, reason or "rebalance")
        return True

    def pod_dead(self, pod: str) -> list[str]:
        """The supervisor's verdict: the pod's lease expired.  Journal
        the death, then fail every stranded tenant over to a surviving
        pod from its namespaced checkpoint (tenants that already
        published a final done-doc keep their result — the dead pod's
        spool is durable state, not a liveness surface).  Returns the
        tenants that moved."""
        if pod in self.dead_pods or pod not in self.pods:
            return []
        self._jlog("pod_dead", {"pod": pod})
        self.dead_pods.add(pod)
        obs_trace.tracer().emit("gw_pod_dead", cat="federation", pod=pod)
        debug.dprintf("Federation", "pod %s declared dead", pod)
        return self._failover_stranded()

    def _failover_stranded(self) -> list[str]:
        """Re-place every non-terminal tenant whose pod is dead — or
        UNKNOWN: a recovery with a smaller pod set than the snapshot's
        (``--recover --pods N``) must fail the orphans over, not crash
        on them.  Called on a death verdict AND from recovery repair (a
        crash mid-failover leaves stranded entries; this pass is
        idempotent)."""
        moved = []
        for e in self.entries.values():
            stranded = e.pod in self.dead_pods \
                or (e.pod and e.pod not in self.pods)
            if e.status in TERMINAL or not stranded:
                continue
            if e.status in ("placed", "draining") and e.pod in self.pods:
                doc = self._pod_done_doc(e.pod, e)
                if doc is not None and doc.get("status") != "evicted":
                    # completed before the death: the result is durable
                    # in the dead pod's spool — adopt it, don't recompute
                    self._mark_done(e, doc)
                    continue
            # loads re-read per tenant ON PURPOSE: each placement adds
            # backlog to its target, so stranded tenants spread across
            # survivors instead of piling onto one snapshot's minimum
            loads = self.pod_loads()
            target = self._pick_pod(exclude=(e.pod,), loads=loads,
                                    avoid=self._sibling_pods(e))
            self._route_to(e, target, reason="failover",
                           from_pod=e.pod, loads=loads)
            moved.append(e.spec.name)
        return moved

    def _sibling_pods(self, e: RouteEntry) -> set[str]:
        """Pods already hosting a LIVE sibling shard of this entry's
        parent (empty for unsharded tenants): the stripe-aware failover
        preference.  Initial shard placement enforces distinct pods
        hard; failover only PREFERS them (soft ``avoid``) — a shard
        must land somewhere even when every survivor hosts a sibling."""
        if not e.shard_of:
            return set()
        parent = self.entries.get(e.shard_of)
        if parent is None:
            return set()
        return {c.pod for n in parent.shards
                if n != e.spec.name
                and (c := self.entries.get(n)) is not None
                and c.pod
                and c.status in ("routed", "placed", "draining")}

    def pod_heal(self, pod: str) -> list[str]:
        """A dead-declared pod resumed beating (a partition healed, not
        a death).  Journal the heal and return the tenants the healed
        pod may still be serving STALELY (failed over meanwhile): the
        driver fences those — evicts them on the healed pod — and the
        authoritative placement in this ledger guarantees each tenant
        is counted exactly once no matter what the stale pod computed
        (its copy's tallies are bit-identical anyway; only the ledger
        decides who reports)."""
        if pod not in self.dead_pods:
            return []
        self._jlog("pod_heal", {"pod": pod})
        self.dead_pods.discard(pod)
        obs_trace.tracer().emit("gw_pod_heal", cat="federation", pod=pod)
        debug.dprintf("Federation", "pod %s healed", pod)
        stale = []
        for e in self.entries.values():
            if e.pod != pod and any(h["pod"] == pod
                                    for h in e.history):
                stale.append(e.spec.name)
        return stale

    # --- the elastic pool (autoscaling transitions) -------------------------
    #
    # The gateway's pool membership is itself WAL state: an autoscaler
    # (federation/autoscale.py) DECIDES scale events, but the decision
    # only exists once its record is durable — ``pool_scale_up`` before
    # any pod directory is touched, ``pool_retire_begin`` before any
    # tenant is drained, ``pool_retire_done`` after the last one left.
    # Retirement rides the ordinary drain-here/recover-there migration
    # path (the federation driver migrates every non-terminal tenant off
    # the fenced pod), and a hung retire is safe because the fence is
    # the journaled record, not the pod's cooperation: ``live_pods``
    # excludes retiring pods, so a retiring pod that beats one last time
    # can never be re-placed onto, and lease expiry (``pod_dead``) moves
    # its tenants if it dies mid-drain.  Recovery replays the pool
    # ledger like every routing decision — the driver reconciles pod
    # processes to it, never the other way around.

    def _pool_port(self, name: str) -> PodPort:
        """The canonical pod layout for an autoscaled pod — derived
        from the federation root (the gateway outdir's parent), never
        journaled as an absolute path: pool records must replay after
        the whole tree is relocated (crashcheck copies snapshots into
        scratch roots)."""
        root = os.path.join(os.path.dirname(self.outdir), "pods", name)
        return PodPort(name, os.path.join(root, "spool"),
                       os.path.join(root, "out"))

    def pool_scale_up(self, reason: str = "", pressure: dict | None = None,
                      round: int | None = None) -> str:
        """Journal one scale-up decision and add the new pod to the
        pool.  The pod's name derives from the scale ordinal
        (``auto<n>`` — never reused), its layout from ``_pool_port``;
        the record carries the pressure evidence so every autoscaling
        decision is auditable from the WAL alone.  Returns the new pod
        name; the driver spawns the actual ``PodHandle`` by reconciling
        against the ledger."""
        scale = self.scale_seq + 1
        name = f"auto{scale}"
        if name in self.pods:
            raise ValueError(f"pool pod {name!r} already exists")
        self._jlog("pool_scale_up", {"pod": name, "scale": scale,
                                     "reason": reason,
                                     "pressure": dict(pressure or {}),
                                     "round": round})
        self.scale_seq = scale
        self.scaled_pods[name] = scale
        self.pods[name] = self._pool_port(name)
        obs_trace.tracer().emit(
            "gw_pool_scale_up", cat="federation", pod=name, scale=scale,
            reason=reason)
        debug.dprintf("Federation", "pool scale-up -> %s (scale=%d, %s)",
                      name, scale, reason)
        self._maybe_compact()
        return name

    def pool_retire_begin(self, pod: str, reason: str = "",
                          round: int | None = None) -> int:
        """Journal one retire decision and fence the pod out of every
        future placement.  The pod keeps serving what it already holds;
        the driver drains it through the journaled migration path and
        calls ``pool_retire_done`` when nothing non-terminal remains.
        Returns the retire's scale ordinal (the chaos trigger
        coordinate for ``kill_during_retire``)."""
        if pod not in self.pods or pod in self.retiring:
            raise ValueError(f"pod {pod!r} not retirable")
        if not [n for n in self.live_pods() if n != pod]:
            raise RuntimeError(
                f"refusing to retire {pod!r}: no live pod would remain")
        scale = self.scale_seq + 1
        self._jlog("pool_retire_begin", {"pod": pod, "scale": scale,
                                         "reason": reason,
                                         "round": round})
        self.scale_seq = scale
        self.retiring.add(pod)
        self.retires[pod] = {"scale": scale, "begin_round": round,
                             "done_round": None}
        obs_trace.tracer().emit(
            "gw_pool_retire_begin", cat="federation", pod=pod,
            scale=scale, reason=reason)
        debug.dprintf("Federation", "pool retire begin: %s (scale=%d, %s)",
                      pod, scale, reason)
        return scale

    def pool_retire_done(self, pod: str, round: int | None = None) -> None:
        """Journal the retire's completion and drop the pod from the
        pool.  Idempotent (a replayed completion is a no-op); the pod's
        durable tree stays on disk — done-docs already adopted live in
        the routing ledger, and the tree is evidence, not state."""
        if pod not in self.retiring:
            return
        rec = self.retires.get(pod) or {}
        self._jlog("pool_retire_done", {"pod": pod,
                                        "scale": rec.get("scale"),
                                        "round": round})
        self.retiring.discard(pod)
        rec["done_round"] = round
        self.retires[pod] = rec
        self.pods.pop(pod, None)
        self.dead_pods.discard(pod)
        self.scaled_pods.pop(pod, None)
        obs_trace.tracer().emit(
            "gw_pool_retire_done", cat="federation", pod=pod,
            scale=rec.get("scale"))
        debug.dprintf("Federation", "pool retire done: %s", pod)
        self._maybe_compact()

    def pool_status(self) -> dict:
        """The pool ledger's observable view — pure WAL-derived state
        (the obs gauges and the HTTP front read THIS, never a second
        count of pod processes).  ``retire_drain_rounds`` is the
        per-pod drain duration in federation rounds (in-flight retires
        report their duration so far as None until done)."""
        drains = {}
        for pod, rec in sorted(self.retires.items()):
            b, d = rec.get("begin_round"), rec.get("done_round")
            drains[pod] = (d - b if d is not None and b is not None
                           else None)
        return {"size": len(self.pods),
                "live": len(self.live_pods()),
                "retiring": sorted(self.retiring),
                "pending_scale_decisions": len(self.retiring),
                "scale_seq": self.scale_seq,
                "scaled_pods": dict(self.scaled_pods),
                "retire_drain_rounds": drains}

    # --- aggregate results -------------------------------------------------

    def all_done(self) -> bool:
        return bool(self.entries) and all(
            e.status in TERMINAL for e in self.entries.values())

    def results(self) -> dict:
        return {n: e.result for n, e in self.entries.items()}

    def tenant_tallies(self, name: str) -> dict:
        """{(simpoint, structure): int64 tallies} for one tenant, from
        its AUTHORITATIVE placement's done-doc — the bit-identity
        surface the federation tests pin against solo runs.  Each
        tenant is counted exactly once, per the routing ledger."""
        e = self.entries[name]
        out = {}
        for key, row in ((e.result or {}).get("results") or {}).items():
            sp, st = key.split("/", 1)
            out[(sp, st)] = np.asarray(row["tallies"], dtype=np.int64)
        return out

    def status(self) -> dict:
        """JSON-able service status (the CLI/HTTP read surface)."""
        return {
            "pods": {n: {"dead": n in self.dead_pods,
                         **{k: v for k, v in self.pod_load(n).items()
                            if k != "pod"}}
                     for n in sorted(self.pods)},
            "tenants": {n: {"status": e.status, "pod": e.pod,
                            "epoch": e.epoch,
                            "deadline_s": e.deadline_s,
                            "slo_s": e.spec.slo_s,
                            "history": list(e.history)}
                        for n, e in sorted(self.entries.items())},
            "dead_pods": sorted(self.dead_pods),
            "pool": self.pool_status(),
            "recoveries": self.recoveries,
        }

    # --- persistence / recovery -------------------------------------------

    def checkpoint(self) -> str:
        """Snapshot the routing ledger (atomic, checksummed) and compact
        the WAL behind it — the scheduler's snapshot-first ordering:
        a crash between the two leaves seq-deduped duplicates, never a
        gap."""
        ckpt_dir = gateway_ckpt_dir(self.outdir)
        os.makedirs(ckpt_dir, exist_ok=True)
        doc = {"version": GATEWAY_CKPT_VERSION,
               "pods": sorted(self.pods),
               "dead_pods": sorted(self.dead_pods),
               "retiring": sorted(self.retiring),
               "scale_seq": self.scale_seq,
               "scaled_pods": dict(self.scaled_pods),
               "retires": {p: dict(rec)
                           for p, rec in self.retires.items()},
               "recoveries": self.recoveries,
               "compact_every": self.compact_every,
               "journal_seq": (self._journal.next_seq - 1
                               if self._journal is not None else
                               self._journal_floor - 1),
               "entries": [e.to_dict() for e in self.entries.values()]}
        doc["checksum"] = resil.doc_checksum(doc)
        resil.write_json_atomic(gateway_snap_path(self.outdir), doc)
        if self._journal is not None:
            self._journal.compact()
        return ckpt_dir

    def shutdown(self) -> None:
        self._jlog("gw_shutdown", {"statuses": self._by_status()})
        self.checkpoint()

    def _by_status(self) -> dict:
        out: dict[str, int] = {}
        for e in self.entries.values():
            out[e.status] = out.get(e.status, 0) + 1
        return out

    def _apply_record(self, r: dict) -> None:
        """Replay one journal record onto the routing ledger
        (idempotent: records carry absolute values)."""
        kind = r.get("kind")
        if kind in ("gw_config", "gw_shutdown", "gw_recover"):
            # lifecycle markers: nothing to restore, handled explicitly
            # so the GL202 exhaustiveness check proves every appended
            # kind has a considered replay story
            return
        if kind == "pod_dead":
            self.dead_pods.add(str(r.get("pod")))
            return
        if kind == "pod_heal":
            self.dead_pods.discard(str(r.get("pod")))
            return
        if kind == "pool_scale_up":
            name = str(r.get("pod"))
            self.scale_seq = max(self.scale_seq, int(r.get("scale", 0)))
            self.scaled_pods[name] = int(r.get("scale", 0))
            if name not in self.pods:
                # ports are re-derived from the relocatable layout, not
                # the record: the snapshot tree may have moved
                self.pods[name] = self._pool_port(name)
            return
        if kind == "pool_retire_begin":
            pod = str(r.get("pod"))
            self.scale_seq = max(self.scale_seq, int(r.get("scale", 0)))
            self.retiring.add(pod)
            self.retires[pod] = {"scale": int(r.get("scale", 0)),
                                 "begin_round": r.get("round"),
                                 "done_round": None}
            return
        if kind == "pool_retire_done":
            pod = str(r.get("pod"))
            self.retiring.discard(pod)
            rec = self.retires.setdefault(
                pod, {"scale": r.get("scale"), "begin_round": None})
            rec["done_round"] = r.get("round")
            self.pods.pop(pod, None)
            self.dead_pods.discard(pod)
            self.scaled_pods.pop(pod, None)
            return
        if kind == "accept":
            if r.get("tenant") not in self.entries:
                e = RouteEntry(TenantSpec.from_dict(r["spec"]),
                               order=int(r.get("order", 0)),
                               ticket=r.get("ticket", ""))
                self.entries[e.spec.name] = e
            return
        if kind == "shard_split":
            e = self.entries.get(r.get("tenant", ""))
            if e is not None:
                e.status = "sharded"
                e.shards = list(r.get("shards") or [])
            for i, sd in enumerate(r.get("specs") or []):
                if sd.get("name") in self.entries:
                    continue
                c = RouteEntry(TenantSpec.from_dict(sd),
                               order=len(self.entries))
                c.shard_of = str(r.get("tenant") or "")
                c.shard_index = i
                self.entries[c.spec.name] = c
            return
        e = self.entries.get(r.get("tenant", ""))
        if e is None:
            return
        if kind == "route":
            e.pod = str(r.get("pod"))
            e.from_pod = str(r.get("from") or "")
            e.epoch = int(r.get("epoch", e.epoch))
            e.status = "routed"
            e.migrate_to = ""
            e.eta_trials = r.get("eta_trials")
            e.deadline_s = r.get("deadline_s")
            e.history.append({"pod": e.pod,
                              "reason": str(r.get("reason") or "route"),
                              "epoch": e.epoch})
        elif kind == "place":
            e.pod_ticket = str(r.get("ticket") or "")
            e.status = "placed"
        elif kind == "migrate":
            e.migrate_to = str(r.get("to") or "")
            e.status = "draining"
        elif kind == "shard_fold":
            # the record IS the fold (journaled before the ledger
            # mutated): replay restores the exact merge trajectory,
            # which is what makes a mid-merge kill replayable
            e.fold_shards = dict(r.get("shards") or {})
            e.fold_merged = dict(r.get("merged") or {})
            e.fold_seq = int(r.get("fold", e.fold_seq + 1))
        elif kind == "shard_converged":
            e.converged = True
        elif kind == "done":
            e.result = r.get("result")
            e.status = "done"

    def _repair(self) -> None:
        """Post-replay repair: finish every placement the crash
        interrupted, WITHOUT re-deciding anything a journal record
        already decided.

        - ``accepted``: the route decision never became durable — make
          it now (a fresh decision is correct: none was ever made).
        - ``routed``: the decision is durable, the handoff uncertain —
          scan the DECIDED pod's spool; a found ticket means the
          handoff landed (repair the ``place`` record), absent means
          re-submit to the journaled pod.  Never a second pod.
        - stranded on a dead pod: re-run the failover pass (idempotent).
        - sharded parents: an ``accepted`` parent means the accept
          became durable but the split didn't — perform it now; queued
          sub-tenants are placed by the fold pass (the distinct-pod
          rule), never by the plain accepted clause.
        """
        for e in list(self.entries.values()):
            if e.status == "accepted":
                if e.spec.shards > 1:
                    self._shard_split(e)
                    continue
                if e.shard_of:
                    continue     # queued surplus shard: the fold pass
                self._route_to(e, self._pick_pod(), reason="admit")
            elif e.status == "routed" and e.pod in self.pods:
                # a decided pod no longer in the recovered pod set is
                # an orphan: the failover pass below re-places it
                hit = self._live_ticket(e)
                if hit is not None:
                    self._jlog("place", {"tenant": e.spec.name,
                                         "pod": e.pod,
                                         "ticket": hit,
                                         "epoch": e.epoch,
                                         "repaired": True})
                    e.pod_ticket = hit
                    e.status = "placed"
                else:
                    self._place(e)
        self._failover_stranded()
        for e in list(self.entries.values()):
            if e.status == "sharded":
                # finish any merge the crash interrupted (idempotent:
                # a fold with no new trials journals nothing, and an
                # already-journaled convergence only re-derives the
                # pending revocation set)
                self._advance_shards(e)

    def _live_ticket(self, e: RouteEntry) -> str | None:
        """The decided pod's LIVE ticket for this tenant, or None when
        the handoff must be (re-)performed.  A scan hit is live when it
        is still pending/claimed, or terminal with a REAL result — a
        returning migration leaves the earlier epoch's ticket behind in
        ``done/`` with status ``evicted`` (and ``bad/`` holds poisoned
        docs): adopting one of those as the placement would turn the
        repair into a spurious re-migration or a results-free final
        doc.  A terminal ``complete`` doc from an earlier epoch IS safe
        to adopt: frozen keys make any completed run of this tenant
        bit-identical."""
        port = self.pods[e.pod]
        hit = find_spool_ticket(port.spool, e.spec.name)
        if hit is None:
            return None
        sub, ticket = hit
        if sub in ("pending", "claimed"):
            return ticket
        if sub == "done":
            doc = SubmissionQueue(port.spool).done(ticket)
            if doc is not None and doc.get("status") not in ("evicted",
                                                            "refused"):
                return ticket
        return None

    @classmethod
    def recover(cls, outdir: str, pods: dict | None = None,
                **kw) -> "Gateway":
        """Rebuild the gateway after ANY shutdown — clean or hard kill —
        by replaying snapshot + journal, then repairing interrupted
        placements (see ``_repair``).  A fresh outdir recovers to an
        empty gateway: the restart path IS the cold-start path."""
        snap = None
        snap_path = gateway_snap_path(outdir)
        if os.path.exists(snap_path):
            snap = resil.load_json_verified(snap_path)
            if snap.get("version") != GATEWAY_CKPT_VERSION:
                raise ValueError(
                    f"gateway checkpoint version {snap.get('version')} "
                    f"!= {GATEWAY_CKPT_VERSION}")
        jpath = gateway_journal_path(outdir)
        records, torn, _valid = (FleetJournal.replay_path(jpath)
                                 if os.path.exists(jpath) else ([], 0, 0))
        snap_seq = int(snap.get("journal_seq", -1)) if snap else -1
        fresh = [r for r in records if int(r["seq"]) > snap_seq]
        dirty = any(r["kind"] != "gw_config" for r in fresh) or torn > 0
        gw = cls(outdir, pods=pods,
                 compact_every=kw.pop(
                     "compact_every",
                     snap.get("compact_every", 64) if snap else 64),
                 **kw)
        gw.journal_torn = torn
        if snap:
            gw.recoveries = int(snap.get("recoveries", 0))
            gw.dead_pods = set(snap.get("dead_pods") or [])
            gw.retiring = set(snap.get("retiring") or [])
            gw.scale_seq = int(snap.get("scale_seq", 0))
            gw.scaled_pods = {k: int(v) for k, v in
                              (snap.get("scaled_pods") or {}).items()}
            gw.retires = {k: dict(v) for k, v in
                          (snap.get("retires") or {}).items()}
            for ed in sorted(snap["entries"], key=lambda d: d["order"]):
                e = RouteEntry.from_dict(ed)
                gw.entries[e.spec.name] = e
        for r in fresh:
            gw._apply_record(r)
        # reconcile the pod map to the replayed pool ledger: snapshot-
        # restored scaled pods get their relocatable ports back, and a
        # completed retire drops its pod even when the caller's static
        # pod set still names it — the WAL, not the constructor
        # argument, owns pool membership
        for name in gw.scaled_pods:
            if name not in gw.pods:
                gw.pods[name] = gw._pool_port(name)
        for pod in gw.retires:
            if pod not in gw.retiring:
                gw.pods.pop(pod, None)
                gw.scaled_pods.pop(pod, None)
        gw._journal_floor = max(
            snap_seq + 1, (records[-1]["seq"] + 1) if records else 0)
        gw._open_journal()
        if dirty:
            gw.recoveries += 1
            gw._jlog("gw_recover", {"recoveries": gw.recoveries,
                                    "replayed": len(fresh),
                                    "torn_dropped": torn})
            obs_trace.tracer().emit(
                "gw_recover", cat="federation",
                recoveries=gw.recoveries, replayed=len(fresh))
            debug.dprintf("Federation", "recovered dirty gateway: %d "
                          "records replayed, %d torn dropped",
                          len(fresh), torn)
        gw._repair()
        gw.checkpoint()
        return gw
