"""Journaled pool autoscaling: pressure signals in, WAL records out.

The gateway already publishes everything a pool controller needs — each
pod's ETA mass (``pod_load``: convergence distance, not instantaneous
throughput), the backlog it has accepted but not yet placed, and every
tenant's admission deadline against its SLO.  The ``Autoscaler`` folds
those three signals into one pressure score and, when the score crosses
its thresholds, asks the gateway to change the pool — and that is ALL
it does.  The decision only exists once the gateway journals it
(``pool_scale_up`` / ``pool_retire_begin`` land in the gateway WAL
before any pod is touched); the federation driver then reconciles pod
processes to the journaled ledger (spawning handles, draining retiring
pods through the ordinary migration path, completing retires with
``pool_retire_done``).  The split is deliberate: recovery without an
autoscaler attached (``Federation.recover``, the crashcheck sweep)
still completes every pending pool transition, because completing is
the driver's job and deciding was already durable.

Determinism: thresholds and cooldowns are counted in federation rounds
and trials — never wall-clock seconds — so the same submissions against
the same chaos schedule scale the pool at the same rounds on every run.

Import discipline: jax-free (pure host-side control arithmetic).
"""

from __future__ import annotations

from shrewd_tpu.federation.gateway import TERMINAL, est_trials
from shrewd_tpu.utils import debug


class Autoscaler:
    """The pool control loop (see module doc).

    ``min_pods``/``max_pods`` bound the LIVE pool; ``up_trials`` /
    ``down_trials`` are per-pod pressure thresholds in trials (the unit
    every signal already carries); ``cooldown_rounds`` spaces decisions
    so one burst of submissions cannot fork the pool faster than the
    drains it causes can settle; ``slo_weight`` scales how much each
    projected SLO miss inflates the pressure score."""

    def __init__(self, min_pods: int = 1, max_pods: int = 8,
                 up_trials: float = 8192.0, down_trials: float = 512.0,
                 cooldown_rounds: int = 2, slo_weight: float = 0.5):
        self.min_pods = max(1, int(min_pods))
        self.max_pods = max(self.min_pods, int(max_pods))
        self.up_trials = float(up_trials)
        self.down_trials = float(down_trials)
        self.cooldown_rounds = max(0, int(cooldown_rounds))
        self.slo_weight = float(slo_weight)
        self.last_round: int | None = None   # round of the last decision
        self.decisions: list[dict] = []      # local audit (the WAL is truth)

    def pressure(self, gw) -> dict:
        """The pool pressure evidence: ETA mass across live pods,
        unplaced backlog (accepted entries with no pod yet — queued
        surplus shards included), and projected SLO-deadline misses.
        The combined ``score`` is per-pod trials inflated by misses; the
        whole dict rides into the ``pool_scale_up`` record so every
        decision is auditable from the WAL alone."""
        live = gw.live_pods()
        loads = {n: gw.pod_load(n) for n in live}
        eta_mass = sum(ld["score"] for ld in loads.values())
        backlog = 0.0
        unplaced = 0
        slo_misses = 0
        for e in gw.entries.values():
            if e.status in TERMINAL or e.status == "sharded":
                continue
            if e.status == "accepted":
                unplaced += 1
                backlog += est_trials(e.spec)
            if e.spec.slo_s and e.deadline_s is not None \
                    and e.deadline_s > e.spec.slo_s:
                slo_misses += 1
        per_pod = (eta_mass + backlog) / max(len(live), 1)
        score = per_pod * (1.0 + self.slo_weight * slo_misses)
        return {"live": len(live), "eta_mass": round(eta_mass, 1),
                "backlog_trials": round(backlog, 1),
                "unplaced": unplaced, "slo_misses": slo_misses,
                "per_pod_trials": round(per_pod, 1),
                "score": round(score, 1)}

    def tick(self, gw, rnd: int) -> dict | None:
        """One control decision for federation round ``rnd``: scale up,
        begin one retire, or do nothing.  At most one decision per
        cooldown window, never more than one pending retire at a time
        (a second retire before the first drain settles would read the
        drain's transient as idleness), and the returned decision is
        only ever a REPORT — the gateway journaled it already."""
        if self.last_round is not None \
                and rnd - self.last_round < self.cooldown_rounds:
            return None
        p = self.pressure(gw)
        if p["score"] > self.up_trials and p["live"] < self.max_pods:
            pod = gw.pool_scale_up(reason="pressure", pressure=p,
                                   round=rnd)
            self.last_round = rnd
            d = {"action": "scale_up", "pod": pod, "round": rnd,
                 "pressure": p}
            self.decisions.append(d)
            debug.dprintf("Federation", "autoscale up -> %s (score %.0f)",
                          pod, p["score"])
            return d
        if p["score"] < self.down_trials and p["live"] > self.min_pods \
                and not gw.retiring:
            victim = self._victim(gw)
            if victim is None:
                return None
            scale = gw.pool_retire_begin(victim, reason="idle", round=rnd)
            self.last_round = rnd
            d = {"action": "retire", "pod": victim, "scale": scale,
                 "round": rnd, "pressure": p}
            self.decisions.append(d)
            debug.dprintf("Federation",
                          "autoscale retire %s (score %.0f)",
                          victim, p["score"])
            return d
        return None

    def _victim(self, gw) -> str | None:
        """Which pod retires: the coldest live pod, autoscaled pods
        strictly first — the pool contracts back to its static floor
        before any hand-built pod is ever considered."""
        live = gw.live_pods()
        if len(live) <= self.min_pods:
            return None
        loads = {n: gw.pod_load(n) for n in live}
        scaled = [n for n in live if n in gw.scaled_pods]
        pool = scaled or live
        return min(pool, key=lambda n: (loads[n]["score"], n))
