"""Federated fleet-of-fleets: one service surface over many meshes.

The service arc (PRs 7–12) made ONE resident scheduler a certifiable,
crash-safe campaign service — but still one process, one mesh.  This
package is the tier above it: a **gateway** routes tenants across N
independent ``CampaignScheduler`` pods by live convergence distance
(the half-width-trajectory ETA each pod publishes in its
``metrics.json``), journals every routing decision to its own
write-ahead ledger BEFORE acting on it, and survives any single pod's
hard death by recovering that pod's tenants on survivors from their
namespaced checkpoints — **migration by bit-identity**: every pod
resumes on frozen per-batch PRNG keys, so a tenant drained on pod A
and recovered on pod B finishes bit-identical to a solo run, which
makes failover, live rebalancing and partition fencing all the same
free operation.

- ``gateway.py``    — ``Gateway``: the crash-safe routing ledger
  (``FleetJournal`` reused as the gateway WAL), ETA/SLO admission with
  deadline estimates, the two-phase route→handoff→place placement that
  ``recover()`` replays without ever double-placing a tenant;
- ``pods.py``       — ``PodHandle`` (one scheduler deployment: spool +
  outdir + coord-dir heartbeat lease) and ``PodSupervisor``
  (round-counted lease expiry over ``parallel/elastic.py`` heartbeats
  — a deterministic failure detector);
- ``autoscale.py``  — ``Autoscaler``: the journaled pool control loop
  (ETA mass + unplaced backlog + SLO-miss pressure in, GL201-certified
  ``pool_scale_up`` / ``pool_retire_begin`` / ``pool_retire_done`` WAL
  records out; retirement rides the ordinary drain-here/recover-there
  migration path, so every pool decision replays from the WAL alone);
- ``driver.py``     — ``Federation``: the single-threaded round-robin
  over the pods' cooperative ``CampaignScheduler.step()`` seam, chaos
  integration (``kill_pod`` / ``partition_pod``), failover, healing
  + fencing, ETA-runaway rebalancing;
- ``http_front.py`` — the thin network adapter: POST /submit into the
  gateway spool, GET /status off the published snapshot.

The invariant, pinned in ``tests/test_federation.py``: the
federation's aggregate tallies are bit-identical to solo serial runs
under any schedule of pod deaths, partitions and migrations, and each
tenant is counted exactly once — the routing ledger, not whoever
happened to compute, decides who reports.

Import discipline: jax-free at package import (jax enters inside the
pods' schedulers).
"""

from shrewd_tpu.federation.autoscale import Autoscaler
from shrewd_tpu.federation.driver import Federation
from shrewd_tpu.federation.gateway import (Gateway, RouteEntry,
                                           copy_tenant_checkpoint,
                                           find_spool_ticket,
                                           gateway_journal_path,
                                           gateway_snap_path)
from shrewd_tpu.federation.http_front import GatewayHTTPFront
from shrewd_tpu.federation.pods import (PodHandle, PodKilled, PodPort,
                                        PodSupervisor)

__all__ = ["Autoscaler", "Federation", "Gateway", "GatewayHTTPFront",
           "PodHandle",
           "PodKilled", "PodPort", "PodSupervisor", "RouteEntry",
           "copy_tenant_checkpoint", "find_spool_ticket",
           "gateway_journal_path", "gateway_snap_path"]
