"""Pods and the pod supervisor: liveness for the fleet-of-fleets.

A **pod** is one complete `CampaignScheduler` deployment — its own
submission spool, its own outdir (namespaced tenant checkpoints, WAL,
``metrics.json``), its own mesh — exactly what one ``fleet.py --serve``
process owns.  ``PodHandle`` wraps that deployment for the in-process
federation driver: it builds (or hard-kill-recovers) the scheduler
lazily, steps it one cooperative quantum at a time
(``CampaignScheduler.step``), and announces liveness through the
coord-dir heartbeat machinery (``parallel/elastic.py``) — one atomic
``hb_<pod>.json`` lease renewal per federation round.

The **supervisor** is the other side of that lease: it reads each pod's
heartbeat and declares the pod dead when the lease expires.  Expiry is
counted in SUPERVISOR POLLS (federation rounds), never wall-clock
seconds, so a chaos schedule that suppresses a pod's beats
(``partition_pod``) produces the same death verdict at the same round
on every run — the federation's failure detector is as deterministic
as the chaos DSL that tests it.  (For a multi-process deployment the
same heartbeat files work with ``elastic.Membership``'s wall-clock
staleness; the supervisor's poll-counted view is the harness-grade
mode, and the one the chaos proofs pin.)

A pod killed by ``kill_pod`` chaos leaves EXACTLY what a SIGKILLed
server process leaves: a stale heartbeat, an undrained outdir, a dirty
WAL, namespaced tenant checkpoints — no drain, no snapshot.  That
equivalence is what lets the in-process federation prove the same
failover story a real multi-process deployment needs.

Import discipline: jax-free at module import (jax enters when a pod's
scheduler elaborates its first tenant).
"""

from __future__ import annotations

import os
from typing import NamedTuple

from shrewd_tpu.parallel import elastic
from shrewd_tpu.resilience import load_json_verified
from shrewd_tpu.service.queue import SubmissionQueue
from shrewd_tpu.utils import debug

debug.register_flag("Federation", "gateway / pods / failover")


class PodKilled(RuntimeError):
    """A chaos ``kill_pod`` fired: this pod's scheduler is dead (the
    in-process analog of SIGKILLing one pod's server process — the
    driver stops stepping it and its heartbeat goes stale)."""

    def __init__(self, pod: str, rc: int):
        super().__init__(f"pod {pod!r} killed by chaos (rc {rc})")
        self.pod = pod
        self.rc = rc


class PodPort(NamedTuple):
    """A pod's service surface as the gateway sees it: where to hand
    off submissions (spool), and where its durable state lives
    (outdir: ``metrics.json`` for load, ``tenants/<name>/`` for the
    checkpoints migration copies)."""

    name: str
    spool: str
    outdir: str


class PodHandle:
    """One pod of the federation (see module doc)."""

    def __init__(self, name: str, root: str, coord_dir: str, mesh=None,
                 **sched_kw):
        self.name = name
        self.root = root
        self.spool_dir = os.path.join(root, "spool")
        self.outdir = os.path.join(root, "out")
        self.queue = SubmissionQueue(self.spool_dir)
        self.heartbeat = elastic.HeartbeatWriter(coord_dir, name)
        self.mesh = mesh
        self.sched_kw = dict(sched_kw)
        self.sched = None
        self.dead = False            # kill_pod fired (stepping stops)
        self.partitioned = False     # beats suppressed, still computing
        self.steps = 0
        # cumulative seconds inside step() (obs.clock — observability
        # only, GL106: the sharded-speedup evidence compares the solo
        # run's busy time against the hottest shard pod's; it never
        # feeds a scheduling decision)
        self.busy_s = 0.0

    @property
    def port(self) -> PodPort:
        return PodPort(self.name, self.spool_dir, self.outdir)

    def build(self):
        """Build the pod's resident scheduler — via ``recover()``, which
        is a fresh build when no durable state exists and a
        snapshot+WAL replay when a previous incarnation died hard (the
        pod restart path is the recovery path; there is no separate
        cold-start code to drift)."""
        from shrewd_tpu.service.scheduler import CampaignScheduler

        if self.sched is None:
            self.sched = CampaignScheduler.recover(
                self.outdir, mesh=self.mesh, queue=self.queue,
                idle_exit=False, **self.sched_kw)
        return self.sched

    def step(self):
        """One cooperative scheduler quantum (``None`` / ``IDLE`` / rc)."""
        from shrewd_tpu.obs import clock as obs_clock

        self.steps += 1
        t0 = obs_clock.monotonic()
        try:
            return self.build().step()
        finally:
            self.busy_s += obs_clock.monotonic() - t0

    def beat(self) -> None:
        """Renew this pod's liveness lease (atomic heartbeat write).
        The driver withholds the call while a ``partition_pod`` window
        is active — suppression IS the partition."""
        self.heartbeat.beat()

    def kill(self) -> None:
        """Mark the pod hard-dead (chaos): stepping stops, beats stop,
        and everything durable stays exactly as the kill left it."""
        self.dead = True
        self.sched = None

    def drain(self) -> int | None:
        """Gracefully drain a live pod to resumable checkpoints
        (federation shutdown); returns the pod's fleet rc."""
        if self.dead or self.sched is None:
            return None
        from shrewd_tpu.service.scheduler import IDLE

        self.sched.request_drain()
        while True:
            rc = self.sched.step()
            if rc is not IDLE and rc is not None:
                return rc


class PodSupervisor:
    """Lease-expiry liveness over the coord-dir heartbeats.

    ``observe()`` is called once per federation round: a pod whose
    heartbeat content has not advanced for ``expiry_rounds``
    consecutive polls (or that never beat at all) has let its lease
    expire and is reported dead.  The verdict is a pure function of
    the observed beat sequence — deterministic under the chaos
    schedule that drives suppression."""

    def __init__(self, coord_dir: str, expiry_rounds: int = 3):
        self.coord_dir = coord_dir
        os.makedirs(coord_dir, exist_ok=True)
        self.expiry_rounds = max(1, int(expiry_rounds))
        self.membership = elastic.Membership(coord_dir)
        self._seen: dict[str, tuple[int | None, int]] = {}

    def _beats(self, pod: str) -> int | None:
        try:
            return int(load_json_verified(
                self.membership._hb_path(pod))["beats"])
        except (OSError, ValueError, KeyError, TypeError):
            return None              # never beat / torn mid-read

    def observe(self, pods) -> dict[str, bool]:
        """One supervisor poll: ``{pod: alive}`` for every named pod."""
        out = {}
        for name in pods:
            beats = self._beats(name)
            prev, stale = self._seen.get(name, (None, 0))
            stale = 0 if (beats is not None and beats != prev) \
                else stale + 1
            self._seen[name] = (beats if beats is not None else prev,
                                stale)
            out[name] = stale < self.expiry_rounds
            if not out[name]:
                debug.dprintf("Federation",
                              "pod %s lease expired (%d stale polls)",
                              name, stale)
        return out

    def alive(self, pod: str) -> bool:
        _beats, stale = self._seen.get(pod, (None, 0))
        return stale < self.expiry_rounds
