"""The thin HTTP front: submissions and status over the wire.

The gateway's native submission surface is its durable spool; this
module is the network adapter over it — a stdlib ``http.server`` that
turns POSTs into spool documents and GETs into reads of the gateway's
PUBLISHED artifacts.  Deliberately decoupled: the HTTP threads never
touch live gateway objects, only the same atomic files any process
could touch, so a wedged request can't corrupt routing state and the
front can run beside an in-process federation or next to a recovered
gateway equally.

Endpoints::

    POST /submit     body = TenantSpec JSON (optionally with "slo_s")
                     → 200 {"ticket": ..., "tenant": ...}; the gateway
                     claims it on its next poll and routes it.
                     A RAW BINARY is a valid submission: carry
                     ``binary_b64`` + ``binary_digest`` (sha256 of the
                     decoded bytes) + optional ``ingest`` axes, with
                     ``plan`` holding only scenario axes — the serving
                     pod runs the journaled ingest pipeline
                     (capture→lift→liveness→simpoint→window) against
                     the federation's digest-keyed artifact store and
                     the campaign starts from the lifted windows; a
                     poisoned payload (digest mismatch, unparseable
                     ELF, lift divergence) lands in durable quarantine
                     with evidence, never a pod death
    GET  /status     → the gateway's persisted snapshot (routing
                     ledger: per-tenant placement/epoch/deadline,
                     plus the elastic pool ledger: scale_seq,
                     retiring set, scaled pods, retire history)
    GET  /pool       → the published pool surface (``pool.json``:
                     size/live/retiring/scale_seq/drain durations,
                     derived from journaled records each round)
    GET  /healthz    → 200 {"ok": true}

No TLS, no auth — a localhost service front for harness and
single-host deployments (say so loudly rather than pretending).

Import discipline: jax-free (pure stdlib HTTP + the spool).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from shrewd_tpu import resilience as resil
from shrewd_tpu.federation.gateway import gateway_snap_path
from shrewd_tpu.obs import metrics as obs_metrics
from shrewd_tpu.service.queue import SubmissionQueue, TenantSpec
from shrewd_tpu.utils import debug


class GatewayHTTPFront:
    """Serve the gateway's spool + published status over HTTP (see
    module doc).  ``port=0`` binds an ephemeral port (tests); read the
    bound port from ``.port`` after ``start()``."""

    def __init__(self, gateway_outdir: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.outdir = gateway_outdir
        self.spool = SubmissionQueue(os.path.join(gateway_outdir,
                                                  "spool"))
        self.host = host
        self._server = ThreadingHTTPServer((host, port),
                                           self._handler_class())
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def _handler_class(self):
        front = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
                debug.dprintf("Federation", "http: " + fmt, *args)

            def _reply(self, code: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib name
                if self.path == "/healthz":
                    self._reply(200, {"ok": True})
                elif self.path == "/status":
                    try:
                        self._reply(200, resil.load_json_verified(
                            gateway_snap_path(front.outdir)))
                    except (OSError, ValueError):
                        self._reply(404, {"error": "no gateway snapshot"})
                elif self.path == "/pool":
                    try:
                        self._reply(200, obs_metrics.read_pool(
                            front.outdir))
                    except (OSError, ValueError):
                        self._reply(404, {"error": "no pool surface"})
                else:
                    self._reply(404, {"error": f"unknown path "
                                               f"{self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib name
                if self.path != "/submit":
                    self._reply(404, {"error": f"unknown path "
                                               f"{self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    spec = TenantSpec.from_dict(
                        json.loads(self.rfile.read(n)))
                except (ValueError, KeyError, TypeError) as e:
                    self._reply(400, {"error": f"bad submission: {e}"})
                    return
                ticket = front.spool.submit(spec)
                self._reply(200, {"ticket": ticket,
                                  "tenant": spec.name})

        return Handler

    def start(self) -> "GatewayHTTPFront":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="gateway-http")
            self._thread.start()
            debug.dprintf("Federation", "http front on %s:%d",
                          self.host, self.port)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
