"""``python -m shrewd_tpu`` — see shrewd_tpu/main.py."""

import sys

from shrewd_tpu.main import main

sys.exit(main())
